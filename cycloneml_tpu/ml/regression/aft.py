"""Weibull AFT survival regression.

Re-design of the reference estimator (ref: ml/regression/
AFTSurvivalRegression.scala — AFTAggregator loss/gradient, L-BFGS over
[β, intercept, log σ]): the hand-derived gradient of the censored Weibull
log-likelihood is replaced by ``jax.grad`` through the per-block loss, fused
with the mesh psum — one jit program per L-BFGS evaluation.

log-likelihood per instance (t=label, δ=censor, ε=(log t − Xβ − b)/σ):
    ll = δ·(ε − log σ) − exp(ε)          (constants in t dropped)

The censor indicator rides as column 0 of the device block; the dataset's
``w`` slot is the validity mask (padding rows contribute nothing — the
−exp(ε) term is NOT weight-neutral, unlike the weighted losses, so a mask is
required rather than w=0 alone).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import PredictionModel, Predictor
from cycloneml_tpu.ml.optim import LBFGS
from cycloneml_tpu.ml.shared import (
    HasAggregationDepth, HasFitIntercept, HasLabelCol, HasMaxIter, HasTol,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _AFTParams(HasMaxIter, HasTol, HasFitIntercept, HasAggregationDepth,
                 HasLabelCol):
    def _declare_aft_params(self):
        self._p_label_col()
        self._p_max_iter(100)
        self._p_tol(1e-6)
        self._p_fit_intercept(True)
        self._p_aggregation_depth(2)
        self._param("censorCol", "censor column (1=event, 0=censored)",
                    default="censor")
        self._param("quantileProbabilities", "quantiles to predict",
                    default=[0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 0.99])
        self._param("quantilesCol", "quantiles output column", default="")

    def set_censor_col(self, v):
        return self.set("censorCol", v)

    def set_quantile_probabilities(self, v):
        """(ref AFTSurvivalRegression[Model].setQuantileProbabilities)"""
        return self.set("quantileProbabilities", list(v))

    def set_quantiles_col(self, v):
        return self.set("quantilesCol", v)


class AFTSurvivalRegression(Predictor, _AFTParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_aft_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "AFTSurvivalRegressionModel":
        x = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        y = np.asarray(frame[self.get("labelCol")], dtype=np.float64)
        censor = np.asarray(frame[self.get("censorCol")], dtype=np.float64)
        return self._fit_arrays(x, y, censor)

    def _fit_arrays(self, x, y, censor) -> "AFTSurvivalRegressionModel":
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.context import CycloneContext

        n, d = x.shape
        if np.any(y <= 0):
            raise ValueError("AFT labels must be positive survival times")

        # feature standardization without centering (ref trainImpl: scales by
        # 1/std so L-BFGS conditioning matches; coefficients unscaled at end)
        std = x.std(axis=0, ddof=0)
        inv_std = np.where(std > 0, 1.0 / np.where(std > 0, std, 1.0), 0.0)
        x_std = x * inv_std[None, :]

        ctx = CycloneContext.get_or_create()
        x_dev = np.concatenate([censor[:, None], x_std], axis=1)
        ds = InstanceDataset.from_numpy(ctx, x_dev, np.log(y), None)
        fit_icpt = self.get("fitIntercept")

        def block_loss(x_blk, logy, mask, params):
            delta = x_blk[:, 0]
            xf = x_blk[:, 1:]
            beta, icpt, log_sigma = params[:d], params[d], params[d + 1]
            sigma = jnp.exp(log_sigma)
            eta = jnp.dot(xf, beta, precision=jax.lax.Precision.HIGHEST)
            if fit_icpt:
                eta = eta + icpt
            eps = (logy - eta) / sigma
            ll = delta * (eps - log_sigma) - jnp.exp(eps)
            return {"loss": -jnp.sum(mask * ll), "count": jnp.sum(mask)}

        def loss_and_grad(xb, yb, wb, p):
            v, g = jax.value_and_grad(
                lambda q: block_loss(xb, yb, wb, q)["loss"])(p)
            return {"loss": v, "grad": g}

        agg = ds.tree_aggregate_fn(loss_and_grad)
        n_total = float(n)

        def loss_fn(params):
            out = agg(jnp.asarray(params))
            return (float(out["loss"]) / n_total,
                    np.asarray(out["grad"], dtype=np.float64) / n_total)

        opt = LBFGS(max_iter=self.get("maxIter"), tol=self.get("tol"))
        x0 = np.zeros(d + 2)  # β=0, b=0, log σ=0 (ref initial values)
        state = opt.minimize(loss_fn, x0)
        sol = state.x
        coef = sol[:d] * inv_std
        icpt = float(sol[d]) if fit_icpt else 0.0
        scale = float(np.exp(sol[d + 1]))

        model = AFTSurvivalRegressionModel(coef, icpt, scale, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.loss_history = list(state.loss_history)
        return model


class AFTSurvivalRegressionModel(PredictionModel, _AFTParams,
                                 MLWritable, MLReadable):
    def __init__(self, coefficients: Optional[np.ndarray] = None,
                 intercept: float = 0.0, scale: float = 1.0, uid=None):
        super().__init__(uid)
        self._declare_aft_params()
        self._coef = np.asarray(coefficients) if coefficients is not None else None
        self._icpt = float(intercept)
        self._scale = float(scale)
        self.loss_history: List[float] = []

    @property
    def coefficients(self) -> DenseVector:
        return Vectors.dense(self._coef)

    @property
    def intercept(self) -> float:
        return self._icpt

    @property
    def scale(self) -> float:
        return self._scale

    @property
    def num_features(self) -> int:
        return self._coef.shape[0]

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        return np.exp(x @ self._coef + self._icpt)

    def _transform(self, frame: MLFrame) -> MLFrame:
        out = super()._transform(frame)
        qcol = self.get("quantilesCol")
        if qcol:
            x = frame[self.get("featuresCol")]
            if x.ndim == 1:
                x = x[:, None]
            out = out.with_column(qcol, self.predict_quantiles(x))
        return out

    def predict_quantiles(self, features) -> np.ndarray:
        """t_q = exp(Xβ+b) · (−log(1−q))^σ (ref predictQuantiles)."""
        x = np.atleast_2d(np.asarray(features, dtype=np.float64))
        lam = np.exp(x @ self._coef + self._icpt)
        qs = np.asarray(self.get("quantileProbabilities"))
        return lam[:, None] * np.power(-np.log1p(-qs)[None, :], self._scale)

    def _save_data(self, path: str) -> None:
        save_arrays(path, coef=self._coef, icpt=np.array(self._icpt),
                    scale=np.array(self._scale))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._coef = arrs["coef"]
        self._icpt = float(arrs["icpt"])
        self._scale = float(arrs["scale"])
