from cycloneml_tpu.ml.regression.linear_regression import (
    LinearRegression, LinearRegressionModel,
)
from cycloneml_tpu.ml.regression.fm import FMRegressionModel, FMRegressor
from cycloneml_tpu.ml.regression.trees import (
    DecisionTreeRegressionModel, DecisionTreeRegressor,
    GBTRegressionModel, GBTRegressor,
    RandomForestRegressionModel, RandomForestRegressor,
)
from cycloneml_tpu.ml.regression.glm import (
    GeneralizedLinearRegression, GeneralizedLinearRegressionModel,
)
from cycloneml_tpu.ml.regression.aft import (
    AFTSurvivalRegression, AFTSurvivalRegressionModel,
)
from cycloneml_tpu.ml.regression.isotonic import (
    IsotonicRegression, IsotonicRegressionModel,
)

__all__ = [
    "LinearRegression", "LinearRegressionModel",
    "FMRegressor", "FMRegressionModel",
    "DecisionTreeRegressor", "DecisionTreeRegressionModel",
    "RandomForestRegressor", "RandomForestRegressionModel",
    "GBTRegressor", "GBTRegressionModel",
    "GeneralizedLinearRegression", "GeneralizedLinearRegressionModel",
    "AFTSurvivalRegression", "AFTSurvivalRegressionModel",
    "IsotonicRegression", "IsotonicRegressionModel",
]
