from cycloneml_tpu.ml.regression.linear_regression import (
    LinearRegression, LinearRegressionModel,
)
from cycloneml_tpu.ml.regression.trees import (
    DecisionTreeRegressionModel, DecisionTreeRegressor,
    GBTRegressionModel, GBTRegressor,
    RandomForestRegressionModel, RandomForestRegressor,
)

__all__ = [
    "LinearRegression", "LinearRegressionModel",
    "DecisionTreeRegressor", "DecisionTreeRegressionModel",
    "RandomForestRegressor", "RandomForestRegressionModel",
    "GBTRegressor", "GBTRegressionModel",
]
