from cycloneml_tpu.ml.regression.linear_regression import (
    LinearRegression, LinearRegressionModel,
)
from cycloneml_tpu.ml.regression.fm import FMRegressionModel, FMRegressor
from cycloneml_tpu.ml.regression.trees import (
    DecisionTreeRegressionModel, DecisionTreeRegressor,
    GBTRegressionModel, GBTRegressor,
    RandomForestRegressionModel, RandomForestRegressor,
)

__all__ = [
    "LinearRegression", "LinearRegressionModel",
    "FMRegressor", "FMRegressionModel",
    "DecisionTreeRegressor", "DecisionTreeRegressionModel",
    "RandomForestRegressor", "RandomForestRegressionModel",
    "GBTRegressor", "GBTRegressionModel",
]
