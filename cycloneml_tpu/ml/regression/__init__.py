from cycloneml_tpu.ml.regression.linear_regression import (
    LinearRegression, LinearRegressionModel,
)

__all__ = ["LinearRegression", "LinearRegressionModel"]
