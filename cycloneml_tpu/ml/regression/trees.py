"""Tree-based regressors: DecisionTree, RandomForest, GBT
(ref: ml/regression/DecisionTreeRegressor.scala,
RandomForestRegressor.scala, GBTRegressor.scala — SquaredError/AbsoluteError
losses from mllib/tree/loss). Same dense histogram engine as the
classifiers."""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import PredictionModel, Predictor
from cycloneml_tpu.ml.classification.trees import _boost, _prepare
from cycloneml_tpu.ml.tree import (
    ForestConfig, ForestData, _DecisionTreeParams, _GBTParams,
    _RandomForestParams, grow_forest,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class _TreeRegressorModelBase(PredictionModel):
    _forest: ForestData

    @property
    def num_features(self) -> int:
        return self._forest.num_features

    @property
    def feature_importances(self) -> np.ndarray:
        return self._forest.feature_importances()

    @property
    def total_num_nodes(self) -> int:
        return int(self._forest.n_nodes.sum())

    def to_debug_string(self) -> str:
        return "\n\n".join(self._forest.debug_string(t)
                           for t in range(self._forest.num_trees))

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        raw = self._forest.predict_raw(np.asarray(x, dtype=np.float64))[:, 0]
        if self._forest.num_trees > 1:
            raw = raw / self._forest.tree_weights.sum()   # forest averages
        return raw

    def _save_data(self, path: str) -> None:
        save_arrays(path, **self._forest.to_arrays())

    def _load_data(self, path: str, meta) -> None:
        self._forest = ForestData.from_arrays(load_arrays(path))


class DecisionTreeRegressor(Predictor, _DecisionTreeParams, MLWritable, MLReadable):
    """ref: ml/regression/DecisionTreeRegressor.scala:44."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "DecisionTreeRegressionModel":
        binned, y, w = _prepare(self, frame)
        cfg = ForestConfig(
            task="regression", impurity="variance",
            max_depth=self.get("maxDepth"),
            min_instances_per_node=self.get("minInstancesPerNode"),
            min_weight_fraction_per_node=self.get("minWeightFractionPerNode"),
            min_info_gain=self.get("minInfoGain"), num_trees=1,
            feature_subset_strategy="all", subsampling_rate=1.0,
            bootstrap=False, seed=self.get("seed"))
        m = DecisionTreeRegressionModel(grow_forest(binned, y, w, cfg))
        self._copy_values(m)
        return m


class DecisionTreeRegressionModel(_TreeRegressorModelBase, _DecisionTreeParams,
                                  MLWritable, MLReadable):
    def __init__(self, forest: Optional[ForestData] = None, uid=None):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._forest = forest

    @property
    def depth(self) -> int:
        return self._forest.tree_depth(0)

    @property
    def num_nodes(self) -> int:
        return int(self._forest.n_nodes[0])


class RandomForestRegressor(Predictor, _RandomForestParams, MLWritable, MLReadable):
    """ref: ml/regression/RandomForestRegressor.scala:46."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._declare_rf_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "RandomForestRegressionModel":
        binned, y, w = _prepare(self, frame)
        cfg = ForestConfig(
            task="regression", impurity="variance",
            max_depth=self.get("maxDepth"),
            min_instances_per_node=self.get("minInstancesPerNode"),
            min_weight_fraction_per_node=self.get("minWeightFractionPerNode"),
            min_info_gain=self.get("minInfoGain"),
            num_trees=self.get("numTrees"),
            feature_subset_strategy=self.get("featureSubsetStrategy"),
            subsampling_rate=self.get("subsamplingRate"),
            bootstrap=self.get("bootstrap"), seed=self.get("seed"))
        m = RandomForestRegressionModel(grow_forest(binned, y, w, cfg))
        self._copy_values(m)
        return m


class RandomForestRegressionModel(_TreeRegressorModelBase, _RandomForestParams,
                                  MLWritable, MLReadable):
    def __init__(self, forest: Optional[ForestData] = None, uid=None):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._declare_rf_params()
        self._forest = forest

    @property
    def num_trees(self) -> int:
        return self._forest.num_trees


class GBTRegressor(Predictor, _GBTParams, MLWritable, MLReadable):
    """ref: ml/regression/GBTRegressor.scala:52 — squared loss
    (neg. gradient 2(y−F)) or absolute loss (sign(y−F))."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._declare_gbt_params(["squared", "absolute"], "squared")
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "GBTRegressionModel":
        binned, y, w = _prepare(self, frame)
        if self.get("lossType") == "absolute":
            neg_grad = lambda f: np.sign(y - f)  # noqa: E731
        else:
            neg_grad = lambda f: 2.0 * (y - f)   # noqa: E731
        forests, weights = _boost(self, binned, w, first_target=y,
                                  neg_gradient=neg_grad)
        m = GBTRegressionModel(forests, np.array(weights))
        self._copy_values(m)
        return m


class GBTRegressionModel(PredictionModel, _GBTParams, MLWritable, MLReadable):
    def __init__(self, forests=None, tree_weights: Optional[np.ndarray] = None,
                 uid=None):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._declare_gbt_params(["squared", "absolute"], "squared")
        self._forests = forests or []
        self._tree_weights = (np.asarray(tree_weights)
                              if tree_weights is not None else np.zeros(0))

    @property
    def num_trees(self) -> int:
        return len(self._forests)

    @property
    def tree_weights(self) -> np.ndarray:
        return self._tree_weights

    @property
    def num_features(self) -> int:
        return self._forests[0].num_features

    @property
    def feature_importances(self) -> np.ndarray:
        imp = np.zeros(self.num_features)
        for fo in self._forests:
            imp += fo.feature_importances()
        s = imp.sum()
        return imp / s if s > 0 else imp

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        x = np.asarray(x, dtype=np.float64)
        f = np.zeros(x.shape[0])
        for fo, tw in zip(self._forests, self._tree_weights):
            f += tw * fo.predict_raw(x)[:, 0]
        return f

    def _save_data(self, path: str) -> None:
        arrs = {"gbt_weights": self._tree_weights,
                "gbt_n": np.array(len(self._forests))}
        for i, fo in enumerate(self._forests):
            arrs.update({f"t{i}_{k}": v for k, v in fo.to_arrays().items()})
        save_arrays(path, **arrs)

    def _load_data(self, path: str, meta) -> None:
        a = load_arrays(path)
        self._tree_weights = a["gbt_weights"]
        self._forests = [
            ForestData.from_arrays(
                {k[len(f"t{i}_"):]: v for k, v in a.items()
                 if k.startswith(f"t{i}_")})
            for i in range(int(a["gbt_n"]))]
