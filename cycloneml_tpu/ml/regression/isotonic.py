"""Isotonic regression via pool-adjacent-violators.

Re-design of the reference estimator (ref: ml/regression/
IsotonicRegression.scala delegating to mllib/regression/
IsotonicRegression.scala — parallel per-partition PAV then a final driver
PAV over pooled boundaries): tie-aggregation + the PAV pooling loop are
sequential by nature, so they run on the driver over numpy arrays; the
partition pre-pass (exact: PAV of concatenated PAV'd runs re-pooled) keeps
driver work proportional to pool count for sharded inputs.

Prediction is linear interpolation between retained pool boundaries with
boundary clamping outside the range — identical semantics to the
reference's ``predict`` (java.util.Arrays.binarySearch + interpolation).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import PredictionModel, Predictor
from cycloneml_tpu.ml.shared import HasLabelCol
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


def _pav(y: np.ndarray, w: np.ndarray) -> np.ndarray:
    """Pool-adjacent-violators over a pre-sorted sequence; returns fitted
    values (same length). O(n) stack algorithm (ref poolAdjacentViolators)."""
    n = len(y)
    fitted = np.empty(n)
    # stacks of (weighted sum, weight, count)
    means = np.empty(n)
    weights = np.empty(n)
    counts = np.empty(n, dtype=np.int64)
    top = 0
    for i in range(n):
        m, ww, c = y[i], w[i], 1
        while top > 0 and means[top - 1] >= m:
            top -= 1
            tw = weights[top] + ww
            m = (means[top] * weights[top] + m * ww) / tw
            ww = tw
            c += counts[top]
        means[top], weights[top], counts[top] = m, ww, c
        top += 1
    pos = 0
    for j in range(top):
        fitted[pos:pos + counts[j]] = means[j]
        pos += counts[j]
    return fitted


class _IsotonicParams(HasLabelCol):
    def _declare_iso_params(self):
        self._p_label_col()
        self._param("isotonic", "true=increasing, false=decreasing",
                    default=True)
        self._param("featureIndex", "index into vector features", default=0)


class IsotonicRegression(Predictor, _IsotonicParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_iso_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_isotonic(self, v):
        return self.set("isotonic", bool(v))

    def set_feature_index(self, v):
        return self.set("featureIndex", int(v))

    def _fit(self, frame: MLFrame) -> "IsotonicRegressionModel":
        feats = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        if feats.ndim > 1:
            feats = feats[:, self.get("featureIndex")]
        y = np.asarray(frame[self.get("labelCol")], dtype=np.float64)
        wcol = self.get("weightCol")
        w = np.asarray(frame[wcol], dtype=np.float64) if wcol else np.ones(len(y))
        return self._fit_arrays(feats, y, w)

    def _fit_arrays(self, feature, y, w) -> "IsotonicRegressionModel":
        increasing = self.get("isotonic")
        y_fit = y if increasing else -y

        # sort by (feature, label) — the reference's tie-break ordering —
        # then aggregate duplicate features by weighted mean (ref makeUnique)
        order = np.lexsort((y_fit, feature))
        f_s, y_s, w_s = feature[order], y_fit[order], w[order]
        uniq, start = np.unique(f_s, return_index=True)
        wsum = np.add.reduceat(w_s, start)
        ysum = np.add.reduceat(w_s * y_s, start)
        y_agg = ysum / wsum

        fitted = _pav(y_agg, wsum)

        # keep only pool boundary points (first+last of each constant run)
        n = len(fitted)
        if n == 0:
            raise ValueError("empty input")
        keep = np.zeros(n, dtype=bool)
        keep[0] = keep[-1] = True
        if n > 1:
            change = fitted[1:] != fitted[:-1]
            keep[1:][change] = True
            keep[:-1][change] = True
        boundaries = uniq[keep]
        predictions = fitted[keep] if increasing else -fitted[keep]

        model = IsotonicRegressionModel(boundaries, predictions, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model


class IsotonicRegressionModel(PredictionModel, _IsotonicParams,
                              MLWritable, MLReadable):
    def __init__(self, boundaries: Optional[np.ndarray] = None,
                 predictions: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._declare_iso_params()
        self.boundaries = np.asarray(boundaries) if boundaries is not None else None
        self.predictions = np.asarray(predictions) if predictions is not None else None

    @property
    def num_features(self) -> int:
        return 1

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        if x.ndim > 1:
            x = x[:, self.get("featureIndex")]
        return np.interp(x, self.boundaries, self.predictions)

    def _save_data(self, path: str) -> None:
        save_arrays(path, boundaries=self.boundaries,
                    predictions=self.predictions)

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self.boundaries = arrs["boundaries"]
        self.predictions = arrs["predictions"]
