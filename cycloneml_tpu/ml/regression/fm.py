"""Factorization-machine regressor (ref: ml/regression/FMRegressor.scala)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.matrices import DenseMatrix
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import PredictionModel, Predictor
from cycloneml_tpu.ml.optim.fm_core import fm_margin_np, split_fm_coef, train_fm
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import (
    HasFitIntercept, HasMaxIter, HasRegParam, HasSeed, HasSolver, HasTol,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class _FMParams(HasMaxIter, HasRegParam, HasTol, HasFitIntercept, HasSeed,
                HasSolver):
    def _declare_fm_params(self):
        self._p_max_iter(100)
        self._p_reg_param(0.0)
        self._p_tol(1e-6)
        self._p_fit_intercept(True)
        self._p_seed(17)
        self._p_solver(["adamW", "gd"], "adamW")
        self.factorSize = self._param(
            "factorSize", "dimensionality of the factors (> 0)",
            V.gt(0), default=8)
        self.fitLinear = self._param(
            "fitLinear", "whether to fit the 1-way linear term", default=True)
        self.miniBatchFraction = self._param(
            "miniBatchFraction", "minibatch fraction in (0, 1]",
            V.in_range(0.0, 1.0, lower_inclusive=False), default=1.0)
        self.initStd = self._param(
            "initStd", "stddev of initial factors", V.gt(0.0), default=0.01)
        self.stepSize = self._param(
            "stepSize", "optimizer step size", V.gt(0.0), default=1.0)


class FMRegressor(Predictor, _FMParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_fm_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_factor_size(self, v):
        return self.set("factorSize", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_step_size(self, v):
        return self.set("stepSize", v)

    def _fit(self, frame: MLFrame) -> "FMRegressionModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"), None)
        d = ds.n_features
        coef, history = train_fm(
            ds, d, "squaredError", self.get("factorSize"),
            self.get("fitIntercept"), self.get("fitLinear"),
            self.get("regParam"), self.get("miniBatchFraction"),
            self.get("initStd"), self.get("maxIter"), self.get("stepSize"),
            self.get("tol"), self.get("solver"), self.get("seed"))
        V_, w, b = split_fm_coef(coef, d, self.get("factorSize"),
                                 self.get("fitIntercept"),
                                 self.get("fitLinear"))
        model = FMRegressionModel(V_, w, b, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.objective_history = history
        return model


class FMRegressionModel(PredictionModel, _FMParams, MLWritable, MLReadable):
    def __init__(self, factors: Optional[np.ndarray] = None,
                 linear: Optional[np.ndarray] = None,
                 intercept: float = 0.0, uid=None):
        super().__init__(uid)
        self._declare_fm_params()
        self._V = np.asarray(factors) if factors is not None else None
        self._w = np.asarray(linear) if linear is not None else None
        self._b = float(intercept)
        self.objective_history = []

    @property
    def factors(self) -> DenseMatrix:
        return DenseMatrix.from_array(self._V)

    @property
    def linear(self) -> DenseVector:
        return Vectors.dense(self._w)

    @property
    def intercept(self) -> float:
        return self._b

    @property
    def num_features(self) -> int:
        return self._V.shape[0]

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        return fm_margin_np(x, self._V, self._w, self._b)

    def _save_data(self, path: str) -> None:
        save_arrays(path, V=self._V, w=self._w, b=np.array(self._b))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._V, self._w, self._b = arrs["V"], arrs["w"], float(arrs["b"])
