"""Linear regression with elastic-net.

Re-design of the reference estimator (ref: ml/regression/LinearRegression.scala,
1,079 LoC): identical objective —

  f(β̂) = 1/(2n) Σ wᵢ((x̂ᵢ−x̄̂)·β̂ − (ŷᵢ−ȳ̂))² + regParam·(α‖β̄‖₁ + (1−α)/2‖β̄‖²)

in doubly-standardized space (features AND label divided by their std, the
glmnet convention the reference follows). ``standardization=false``
penalises original-space β exactly as the reference's
DifferentiableRegularization does. Solvers mirror ``solver``: "l-bfgs"/
OWL-QN trains without an intercept via the centering trick (intercept
recovered in closed form ȳ − β·x̄, Summarizer unbiased std — the
reference's l-bfgs path); "normal" DELEGATES to the
``ml.optim.wls.WeightedLeastSquares`` component exactly as the reference
does (LinearRegression.scala:446-448 — population-weighted moments,
appended-bias standardized system, Cholesky with singular→quasi-Newton
fallback); "auto" picks normal when d ≤ 4096 and α·regParam == 0.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import PredictionModel, Predictor
from cycloneml_tpu.ml.optim import LBFGS, OWLQN, aggregators
from cycloneml_tpu.ml.optim.loss import DistributedLossFunction, l2_regularization
from cycloneml_tpu.ml.shared import (
    HasAggregationDepth, HasElasticNetParam, HasFitIntercept, HasLabelCol,
    HasMaxIter, HasRegParam, HasSolver, HasStandardization, HasTol,
)
from cycloneml_tpu.ml.stat import Summarizer
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

# the component owns the real cap (wls.py raises at fit time) — this
# alias only steers the auto-solver choice
from cycloneml_tpu.ml.optim.wls import \
    MAX_NUM_FEATURES as MAX_FEATURES_FOR_NORMAL  # noqa: E402


class _LinearRegressionParams(HasMaxIter, HasRegParam, HasElasticNetParam,
                              HasTol, HasFitIntercept, HasStandardization,
                              HasSolver, HasAggregationDepth, HasLabelCol):
    def _declare_linreg_params(self):
        self._p_label_col()
        self._p_max_iter(100)
        self._p_reg_param(0.0)
        self._p_elastic_net(0.0)
        self._p_tol(1e-6)
        self._p_fit_intercept(True)
        self._p_standardization(True)
        self._p_solver(["auto", "l-bfgs", "normal"], "auto")
        self._p_aggregation_depth(2)


class LinearRegression(Predictor, _LinearRegressionParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_linreg_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_reg_param(self, v):
        return self.set("regParam", v)

    def set_elastic_net_param(self, v):
        return self.set("elasticNetParam", v)

    def set_solver(self, v):
        return self.set("solver", v)

    def _fit(self, frame: MLFrame) -> "LinearRegressionModel":
        # fp8-capable: the l-bfgs path folds the per-column dequant scales
        # into inv_std; the normal (WLS) solver is NOT fp8-eligible and
        # dequantizes back to bf16 below (a visible PrecisionFallback)
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"),
            self.get("weightCol") or None, fp8_capable=True)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "LinearRegressionModel":
        import jax
        import jax.numpy as jnp

        from cycloneml_tpu.oocore import StreamingDataset, streaming_mode
        streamed = isinstance(ds, StreamingDataset)
        force = not streamed and \
            streaming_mode(getattr(ds.ctx, "conf", None)) == "force"

        d = ds.n_features
        reg = self.get("regParam")
        alpha = self.get("elasticNetParam")
        solver = self.get("solver")
        if solver == "auto":
            # streamed fits always take the quasi-Newton path: the normal
            # solver's moment system wants the in-core design matrix
            solver = "normal" if (alpha * reg == 0.0
                                  and d <= MAX_FEATURES_FOR_NORMAL
                                  and not (streamed or force)) else "l-bfgs"
        if (streamed or force) and solver == "normal":
            # validated BEFORE any force-mode spill: an explicit normal
            # request must not pay an O(n·d) shard write just to raise
            raise ValueError(
                "solver='normal' requires an in-core dataset; streamed "
                "fits use solver='l-bfgs' (or 'auto')")
        if force:
            from cycloneml_tpu.oocore import shard_dataset
            sds = shard_dataset(ds)
            try:
                return self._fit_dataset(sds)
            finally:
                sds.close()

        if solver == "normal":
            if getattr(ds, "x_scale", None) is not None:
                # the moment solver reads ds.x directly; e4m3 codes are
                # not values — leave the fp8 rung, visibly
                from cycloneml_tpu.dataset.dataset import fp8_fallback
                ds = fp8_fallback(ds, "LinearRegression",
                                  "solver='normal' is not fp8-eligible")
            # delegate to the WLS COMPONENT exactly as the reference does
            # (LinearRegression.scala:446-448: WeightedLeastSquares with
            # solverType=Auto, standardizeLabel=true) — population-weighted
            # moments, appended-bias system, Cholesky with singular→QN
            # fallback, and the constant-label/zero-variance degeneracies
            # live in ONE place (ml/optim/wls.py)
            from cycloneml_tpu.ml.optim.wls import (AUTO,
                                                    WeightedLeastSquares)
            wls = WeightedLeastSquares(
                fit_intercept=self.get("fitIntercept"), reg_param=reg,
                elastic_net_param=alpha,
                standardize_features=self.get("standardization"),
                standardize_label=True, solver_type=AUTO,
                max_iter=self.get("maxIter"), tol=self.get("tol"))
            wm = wls.fit(ds.x, ds.y, ds.w)
            model = LinearRegressionModel(wm.coefficients, wm.intercept,
                                          uid=self.uid)
            self._copy_values(model)
            model._set_parent(self)
            model.summary = LinearRegressionTrainingSummary(
                wm.objective_history,
                max(len(wm.objective_history) - 1, 0))
            return model

        stats = ds.summary() if streamed else Summarizer.summarize(ds)
        if not streamed:
            # fp8 safety rail: envelope probe, bf16 fallback on failure
            from cycloneml_tpu.dataset.dataset import resolve_fp8_fit
            ds = resolve_fp8_fit(ds, stats, "LinearRegression")
        x_mean, x_std = stats.mean, stats.std
        w_sum = stats.weight_sum

        # label moments: one psum pass in-core; already harvested in the
        # shard write pass for streamed datasets
        if streamed:
            s1y, s2y, w2y = ds.y_moments()
            ymom = {"s1": s1y, "s2": s2y, "w2": w2y}
        else:
            ymom = ds.tree_aggregate_fn(
                lambda x, y, w: {"s1": jnp.sum(w * y),
                                 "s2": jnp.sum(w * y * y),
                                 "w2": jnp.sum(w * w)})()
        y_mean = float(ymom["s1"]) / w_sum
        denom = w_sum - float(ymom["w2"]) / w_sum
        y_var = max((float(ymom["s2"]) - w_sum * y_mean ** 2) / denom, 0.0) if denom > 0 else 0.0
        y_std = float(np.sqrt(y_var))
        if y_std == 0.0:
            # constant label (ref LinearRegression.scala:388-414, mirroring
            # WeightedLeastSquares.scala:117-141): with an intercept (or an
            # all-zero label) the exact fit is zero coefficients; WITHOUT
            # an intercept a nonzero constant label still needs solving —
            # the reference sets yStd = |yMean| so the label is "not scaled
            # anymore" and proceeds, and REFUSES regularization because the
            # label-standardized penalty is undefined at σy=0
            if self.get("fitIntercept") or y_mean == 0.0:
                model = LinearRegressionModel(
                    np.zeros(d), y_mean if self.get("fitIntercept") else 0.0,
                    uid=self.uid)
                self._copy_values(model)
                model._set_parent(self)
                model.summary = LinearRegressionTrainingSummary([0.0], 0)
                return model
            if reg > 0.0:
                raise ValueError(
                    "The standard deviation of the label is zero. Model "
                    "cannot be regularized when labels are standardized "
                    "(ref WeightedLeastSquares require)")
            y_std = abs(y_mean)

        # glmnet semantics (the reference's parity target): the penalty is
        # applied on the label-standardized problem, so the user's regParam
        # is divided by the label std (ref LinearRegression.scala:396
        # effectiveRegParam = regParam / yStd; WeightedLeastSquares.scala:209)
        eff_reg = reg / y_std
        coef, icpt, history = self._solve_quasi_newton(
            ds, stats, y_mean, y_std, eff_reg, alpha)

        model = LinearRegressionModel(coef, icpt, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.summary = LinearRegressionTrainingSummary(
            history, max(len(history) - 1, 0), streamed=streamed)
        return model

    # -- quasi-Newton in doubly standardized space -----------------------------
    def _solve_quasi_newton(self, ds, stats, y_mean, y_std, reg, alpha):
        import jax
        import jax.numpy as jnp

        d = ds.n_features
        fit_intercept = self.get("fitIntercept")
        standardize = self.get("standardization")
        x_mean, x_std = stats.mean, stats.std
        inv_std = np.where(x_std > 0, 1.0 / np.where(x_std > 0, x_std, 1.0), 0.0)

        # the doubly-standardized objective folds INTO the aggregator read
        # (aggregators.least_squares_scaled): err = x·(inv_std∘β) −
        # (μ̂·β − ȳ̂) − y/σ_y, grad unscales by inv_std — algebraically the
        # aggregation over (x̂−μ̂, ŷ−ȳ̂) without EVER materializing the
        # standardized X copy or the scaled-y vector (pre-tier this path
        # re-wrote both, a full read+write X sweep and 2x the HBM working
        # set per fit). Raw data-tier blocks (bf16 by default) are read at
        # storage width with fp32 accumulation inside the kernel; the
        # fused Pallas kernel is the default sweep on native backends.
        from cycloneml_tpu.dataset.instance import compute_dtype
        from cycloneml_tpu.ops.kernels import use_fused_kernels
        adt = compute_dtype()
        scaled_mean = (x_mean * inv_std) if fit_intercept else np.zeros(d)
        y_mean_std = (y_mean / y_std) if fit_intercept else 0.0
        y_pars = np.array([1.0 / y_std, y_mean_std])
        # fp8 tier: the per-column dequant scale folds into the
        # aggregator-side inv_std (x̂ = codes∘(scale/σ) − μ/σ); the final
        # unscaling keeps the original inv_std
        fp8_scale = getattr(ds, "x_scale", None)
        inv_std_agg = inv_std * fp8_scale if fp8_scale is not None \
            else inv_std
        agg = (aggregators.least_squares_pallas_scaled(d)
               if use_fused_kernels(ds.ctx)
               else aggregators.least_squares_scaled(d))

        l2 = (1.0 - alpha) * reg
        l1 = alpha * reg
        l2_fn = l2_regularization(l2, d, False, features_std=x_std,
                                  standardize=standardize) if l2 > 0 else None
        extras = (jnp.asarray(inv_std_agg.astype(adt)),
                  jnp.asarray(scaled_mean.astype(adt)),
                  jnp.asarray(y_pars.astype(adt)))
        from cycloneml_tpu.oocore import StreamingDataset
        if isinstance(ds, StreamingDataset):
            # the streamed twin: same scaled aggregator, same extras —
            # each loss/grad evaluation is one double-buffered epoch
            from cycloneml_tpu.oocore import StreamingLossFunction
            loss_fn = StreamingLossFunction(ds, agg, l2_fn,
                                            stats.weight_sum,
                                            extra_args=extras)
        else:
            loss_fn = DistributedLossFunction(ds, agg, l2_fn,
                                              stats.weight_sum,
                                              extra_args=extras)

        if l1 > 0:
            l1_vec = np.full(d, l1)
            if not standardize:
                l1_vec = np.where(x_std > 0, l1 / np.where(x_std > 0, x_std, 1.0), 0.0)
            opt = OWLQN(max_iter=self.get("maxIter"), tol=self.get("tol"),
                        l1_reg=l1_vec)
        else:
            opt = LBFGS(max_iter=self.get("maxIter"), tol=self.get("tol"))
        state = opt.minimize(loss_fn, np.zeros(d))
        if state.converged_reason == "max iterations reached":
            logger.warning("LinearRegression did not converge in %d iterations",
                           self.get("maxIter"))
        if fp8_scale is not None and not np.all(np.isfinite(state.x)):
            # overflowed e4m3 surfaces as NaN — refit on the bf16 rung
            from cycloneml_tpu.dataset.dataset import fp8_fallback
            return self._solve_quasi_newton(
                fp8_fallback(ds, "LinearRegression",
                             "non-finite fp8 solution"),
                stats, y_mean, y_std, reg, alpha)

        beta_hat = state.x  # standardized-space coefficients
        coef = beta_hat * inv_std * y_std
        icpt = y_mean - float(coef @ x_mean) if fit_intercept else 0.0
        return coef, icpt, list(state.loss_history)


class LinearRegressionModel(PredictionModel, _LinearRegressionParams,
                            MLWritable, MLReadable):
    def __init__(self, coefficients: Optional[np.ndarray] = None,
                 intercept: float = 0.0, uid=None):
        super().__init__(uid)
        self._declare_linreg_params()
        self._coef = np.asarray(coefficients) if coefficients is not None else None
        self._icpt = float(intercept)
        self.summary: Optional[LinearRegressionTrainingSummary] = None

    @property
    def coefficients(self) -> DenseVector:
        return Vectors.dense(self._coef)

    @property
    def intercept(self) -> float:
        return self._icpt

    @property
    def num_features(self) -> int:
        return self._coef.shape[0]

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        return x @ self._coef + self._icpt

    def evaluate(self, frame: MLFrame):
        """RegressionSummary metrics on a frame (ref LinearRegressionSummary)."""
        x = frame[self.get("featuresCol")]
        y = frame[self.get("labelCol")]
        pred = self._predict_batch(x)
        resid = y - pred
        sse = float(resid @ resid)
        sst = float(((y - y.mean()) ** 2).sum())
        n = len(y)
        return {
            "rmse": float(np.sqrt(sse / n)),
            "mse": sse / n,
            "mae": float(np.abs(resid).mean()),
            "r2": 1.0 - sse / sst if sst > 0 else float("nan"),
        }

    def _save_data(self, path: str) -> None:
        save_arrays(path, coef=self._coef, icpt=np.array(self._icpt))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._coef = arrs["coef"]
        self._icpt = float(arrs["icpt"])


class LinearRegressionTrainingSummary:
    def __init__(self, objective_history, total_iterations, streamed=False):
        self.objective_history = objective_history
        self.total_iterations = total_iterations
        # True when the fit ran on the out-of-core streaming engine
        self.streamed = streamed
