"""Naive Bayes classifier.

Re-design of the reference (ref: ml/classification/NaiveBayes.scala —
``trainDiscreteImpl`` aggregates per-class feature sums with one
treeAggregate-style pass for multinomial/bernoulli/complement,
``trainGaussianImpl`` aggregates per-class mean/variance). TPU-first: the
per-class sums are ONE one-hot(y)ᵀ·X MXU matmul psum'd over the mesh; the
driver finishes with the tiny (k, d) smoothing/log transforms.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.matrices import DenseMatrix
from cycloneml_tpu.ml.base import Predictor, ProbabilisticClassificationModel
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_MODEL_TYPES = ["multinomial", "bernoulli", "complement", "gaussian"]


class NaiveBayes(Predictor, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_nb_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def _declare_nb_params(self):
        self.smoothing = self._param("smoothing", "additive smoothing (>= 0)",
                                     V.gt_eq(0.0), default=1.0)
        self.modelType = self._param(
            "modelType", "multinomial|bernoulli|complement|gaussian",
            V.in_array(_MODEL_TYPES), default="multinomial")

    def set_smoothing(self, v):
        return self.set("smoothing", v)

    def set_model_type(self, v):
        return self.set("modelType", v)

    def _fit(self, frame: MLFrame) -> "NaiveBayesModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"),
            self.get("weightCol") or None)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "NaiveBayesModel":
        import jax
        import jax.numpy as jnp

        d = ds.n_features
        model_type = self.get("modelType")
        lam = self.get("smoothing")
        k = int(np.asarray(ds.y).max()) + 1 if ds.n_rows else 2
        hi = jax.lax.Precision.HIGHEST

        if model_type in ("multinomial", "complement"):
            # nonneg check mirrors requireNonnegativeValues (ref :must be
            # nonzero counts); done in the same pass
            def stats(x, y, w, _z):
                onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=w.dtype)
                ow = onehot * w[:, None]
                return {"feat": jnp.dot(ow.T, x, precision=hi),    # (k, d)
                        "wsum": jnp.sum(ow, axis=0),
                        "neg": jnp.sum(jnp.where(x < 0, 1.0, 0.0))}
        elif model_type == "bernoulli":
            def stats(x, y, w, _z):
                xb = (x != 0).astype(w.dtype)
                onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=w.dtype)
                ow = onehot * w[:, None]
                bad = jnp.sum(jnp.where(
                    jnp.logical_and(x != 0, x != 1), 1.0, 0.0))
                return {"feat": jnp.dot(ow.T, xb, precision=hi),
                        "wsum": jnp.sum(ow, axis=0), "neg": bad}
        else:  # gaussian
            def stats(x, y, w, _z):
                onehot = jax.nn.one_hot(y.astype(jnp.int32), k, dtype=w.dtype)
                ow = onehot * w[:, None]
                return {"feat": jnp.dot(ow.T, x, precision=hi),
                        "sq": jnp.dot(ow.T, x * x, precision=hi),
                        "wsum": jnp.sum(ow, axis=0), "neg": jnp.zeros(())}

        out = ds.tree_aggregate_fn(stats)(jnp.zeros((), ds.w.dtype))
        if float(out["neg"]) > 0:
            kind = ("zero-or-one" if model_type == "bernoulli"
                    else "nonnegative")
            raise ValueError(f"{model_type} NaiveBayes requires {kind} "
                             "feature values")
        feat = np.asarray(out["feat"], np.float64)      # (k, d)
        wsum = np.asarray(out["wsum"], np.float64)      # (k,)
        pi = np.log(wsum + lam) - np.log(wsum.sum() + k * lam)

        sigma = np.zeros((0, 0))
        if model_type == "multinomial":
            theta = (np.log(feat + lam)
                     - np.log(feat.sum(axis=1, keepdims=True) + lam * d))
        elif model_type == "complement":
            # ref trainDiscreteImpl complement branch (Rennie et al. 2003):
            # per-class stats of the COMPLEMENT, normalized, negated
            total = feat.sum(axis=0, keepdims=True)     # (1, d)
            comp = total - feat
            logc = np.log(comp + lam) - np.log(
                comp.sum(axis=1, keepdims=True) + lam * d)
            theta = -logc
        elif model_type == "bernoulli":
            theta = (np.log(feat + lam)
                     - np.log(wsum[:, None] + 2.0 * lam))
        else:  # gaussian — unbiased-ish variance with epsilon flooring
            mu = feat / np.maximum(wsum[:, None], 1e-300)
            sq = np.asarray(out["sq"], np.float64)
            var = sq / np.maximum(wsum[:, None], 1e-300) - mu * mu
            # ref uses max-variance epsilon: 1e-9 * max var
            eps = 1e-9 * max(var.max(), 1e-300)
            sigma = np.maximum(var, eps)
            theta = mu

        model = NaiveBayesModel(pi, theta, sigma, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model


class NaiveBayesModel(ProbabilisticClassificationModel, MLWritable, MLReadable):
    def __init__(self, pi: Optional[np.ndarray] = None,
                 theta: Optional[np.ndarray] = None,
                 sigma: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        NaiveBayes._declare_nb_params(self)
        self._pi = np.asarray(pi) if pi is not None else None
        self._theta = np.asarray(theta) if theta is not None else None
        self._sigma = np.asarray(sigma) if sigma is not None else None

    @property
    def pi(self) -> np.ndarray:
        return self._pi

    @property
    def theta(self) -> DenseMatrix:
        return DenseMatrix.from_array(self._theta)

    @property
    def sigma(self) -> DenseMatrix:
        return DenseMatrix.from_array(self._sigma)

    @property
    def num_classes(self) -> int:
        return len(self._pi)

    @property
    def num_features(self) -> int:
        return self._theta.shape[1]

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        mt = self.get("modelType")
        if mt in ("multinomial", "complement"):
            raw = x @ self._theta.T
            if mt == "multinomial":
                raw = raw + self._pi[None, :]
            return raw
        if mt == "bernoulli":
            xb = (x != 0).astype(np.float64)
            neg_theta = np.log1p(-np.exp(self._theta))
            raw = (xb @ self._theta.T + (1.0 - xb) @ neg_theta.T
                   + self._pi[None, :])
            return raw
        # gaussian
        mu, var = self._theta, self._sigma
        ll = -0.5 * (((x[:, None, :] - mu[None, :, :]) ** 2 / var[None, :, :])
                     + np.log(2 * np.pi * var)[None, :, :]).sum(axis=2)
        return ll + self._pi[None, :]

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        m = raw.max(axis=1, keepdims=True)
        e = np.exp(raw - m)
        return e / e.sum(axis=1, keepdims=True)

    def _save_data(self, path: str) -> None:
        save_arrays(path, pi=self._pi, theta=self._theta,
                    sigma=self._sigma if self._sigma is not None else np.zeros((0, 0)))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._pi = arrs["pi"]
        self._theta = arrs["theta"]
        self._sigma = arrs["sigma"]
