"""Multilayer perceptron classifier.

Re-design of the reference (ref: ml/classification/
MultilayerPerceptronClassifier.scala:93 over the ml/ann/ feed-forward stack —
sigmoid hidden layers + softmax output with cross-entropy
(FeedForwardTopology.multiLayerPerceptron), trained by Breeze LBFGS (or GD)
on a flat weight vector; BreezeUtil.scala:40 calls native dgemm directly).
TPU-first: the whole forward/backward for a row block is one jit program —
layer matmuls on the MXU, backward from ``jax.grad`` instead of the
reference's hand-written LayerModel.grad — psum'd over the mesh into the
same L-BFGS driver loop every linear model uses.

Weight packing (self-consistent, persisted as one vector like the
reference): per layer i, W_i (fan_out × fan_in) row-major, then b_i.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import Predictor, ProbabilisticClassificationModel
from cycloneml_tpu.ml.optim import LBFGS
from cycloneml_tpu.ml.optim.loss import DistributedLossFunction
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import HasMaxIter, HasSeed, HasSolver, HasTol
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def _n_weights(layers: Sequence[int]) -> int:
    return sum((layers[i] + 1) * layers[i + 1] for i in range(len(layers) - 1))


def _forward(jnp, flat, x, layers, precision):
    """Returns output-layer logits for a row block."""
    off = 0
    h = x
    n = len(layers) - 1
    for i in range(n):
        fin, fout = layers[i], layers[i + 1]
        W = flat[off: off + fin * fout].reshape(fout, fin)
        off += fin * fout
        b = flat[off: off + fout]
        off += fout
        h = jnp.dot(h, W.T, precision=precision) + b
        if i < n - 1:
            import jax
            h = jax.nn.sigmoid(h)
    return h  # logits; softmax applied in the loss / probability


class _MLPParams(HasMaxIter, HasTol, HasSeed, HasSolver):
    def _declare_mlp_params(self):
        self._p_max_iter(100)
        self._p_tol(1e-6)
        self._p_seed(17)
        self._p_solver(["l-bfgs", "gd"], "l-bfgs")
        self.layers = self._param(
            "layers", "layer sizes from input to output", default=None)
        self.blockSize = self._param(
            "blockSize", "block size (kept for parity; blocks are the "
            "physical layout already)", V.gt(0), default=128)
        self.stepSize = self._param("stepSize", "gd step size", V.gt(0.0),
                                    default=0.03)
        self.initialWeights = self._param(
            "initialWeights", "explicit initial weight vector", default=None)


class MultilayerPerceptronClassifier(Predictor, _MLPParams,
                                     MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_mlp_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_layers(self, v):
        return self.set("layers", list(v))

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_seed(self, v):
        return self.set("seed", v)

    def _fit(self, frame: MLFrame) -> "MultilayerPerceptronClassificationModel":
        import jax
        import jax.numpy as jnp

        layers = (self.get("layers")
                  if self.is_defined(self.get_param("layers")) else None)
        if not layers or len(layers) < 2:
            raise ValueError("layers must list >= 2 sizes (input and output)")
        layers = [int(v) for v in layers]
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"), None)
        if ds.n_features != layers[0]:
            raise ValueError(f"input layer size {layers[0]} != "
                             f"feature dim {ds.n_features}")
        k = layers[-1]
        y_real = ds.unpad(np.asarray(ds.y))
        if ds.n_rows and (y_real.min() < 0 or y_real.max() >= k
                          or np.any(y_real != np.floor(y_real))):
            raise ValueError(
                f"labels must be integers in [0, {k}) to match the output "
                f"layer; found range [{y_real.min()}, {y_real.max()}] "
                "(out-of-range indices would be silently clamped under jit)")
        hi = jax.lax.Precision.HIGHEST

        def agg(x, y, w, flat):
            def total_loss(f):
                logits = _forward(jnp, f, x, layers, hi)
                logz = jax.nn.logsumexp(logits, axis=1)
                picked = jnp.take_along_axis(
                    logits, y.astype(jnp.int32)[:, None], axis=1)[:, 0]
                return jnp.sum(w * (logz - picked))

            loss, grad = jax.value_and_grad(total_loss)(flat)
            return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

        loss_fn = DistributedLossFunction(ds, agg)

        n_w = _n_weights(layers)
        init = (self.get("initialWeights")
                if self.is_defined(self.get_param("initialWeights")) else None)
        if init is not None:
            x0 = np.asarray(init, np.float64)
            if len(x0) != n_w:
                raise ValueError(f"initialWeights has {len(x0)} values, "
                                 f"topology needs {n_w}")
        else:
            # ref FeedForwardModel init: uniform scaled by fan-in-ish factor
            rng = np.random.RandomState(self.get("seed"))
            x0 = np.empty(n_w)
            off = 0
            for i in range(len(layers) - 1):
                fin, fout = layers[i], layers[i + 1]
                scale = np.sqrt(6.0 / (fin + fout))  # Glorot uniform
                x0[off: off + fin * fout] = rng.uniform(
                    -scale, scale, fin * fout)
                off += fin * fout
                x0[off: off + fout] = 0.0
                off += fout

        if self.get("solver") == "l-bfgs":
            state = LBFGS(max_iter=self.get("maxIter"),
                          tol=self.get("tol")).minimize(loss_fn, x0)
            sol, history, iters = state.x, list(state.loss_history), state.iteration
        else:  # gd
            lr = self.get("stepSize")
            sol = x0.copy()
            history = []
            for _ in range(self.get("maxIter")):
                loss, grad = loss_fn(sol)
                history.append(loss)
                sol = sol - lr * grad
            iters = self.get("maxIter")

        model = MultilayerPerceptronClassificationModel(layers, sol, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.objective_history = history
        model.total_iterations = iters
        return model


class MultilayerPerceptronClassificationModel(ProbabilisticClassificationModel,
                                              _MLPParams, MLWritable, MLReadable):
    def __init__(self, layers: Optional[List[int]] = None,
                 weights: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._declare_mlp_params()
        self._layers = list(layers) if layers is not None else None
        self._weights = np.asarray(weights) if weights is not None else None
        self.objective_history = []
        self.total_iterations = 0

    @property
    def weights(self) -> DenseVector:
        return Vectors.dense(self._weights)

    @property
    def num_classes(self) -> int:
        return self._layers[-1]

    @property
    def num_features(self) -> int:
        return self._layers[0]

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        h = x
        off = 0
        n = len(self._layers) - 1
        for i in range(n):
            fin, fout = self._layers[i], self._layers[i + 1]
            W = self._weights[off: off + fin * fout].reshape(fout, fin)
            off += fin * fout
            b = self._weights[off: off + fout]
            off += fout
            h = h @ W.T + b
            if i < n - 1:
                h = 1.0 / (1.0 + np.exp(-h))
        return h

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        m = raw.max(axis=1, keepdims=True)
        e = np.exp(raw - m)
        return e / e.sum(axis=1, keepdims=True)

    def _save_data(self, path: str) -> None:
        save_arrays(path, layers=np.asarray(self._layers, np.int64),
                    weights=self._weights)

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._layers = [int(v) for v in arrs["layers"]]
        self._weights = arrs["weights"]
