"""Linear support vector classifier.

Re-design of the reference (ref: ml/classification/LinearSVC.scala — hinge
loss via HingeBlockAggregator, L2-only regularization, Breeze LBFGS driver
loop over standardized blocks, threshold on the raw margin). Same training
skeleton as LogisticRegression: one summarizer pass, standardize in HBM,
jit-compiled hinge gradient psum'd per iteration.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import ClassificationModel, Predictor
from cycloneml_tpu.ml.optim import LBFGS, aggregators
from cycloneml_tpu.ml.optim.loss import (
    DistributedLossFunction, l2_regularization, standardize_dataset,
    validate_binary_labels,
)
from cycloneml_tpu.ml.shared import (
    HasAggregationDepth, HasFitIntercept, HasMaxIter, HasRegParam,
    HasStandardization, HasTol,
)
from cycloneml_tpu.ml.stat import Summarizer
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _LinearSVCParams(HasMaxIter, HasRegParam, HasTol, HasFitIntercept,
                       HasStandardization, HasAggregationDepth):
    def _declare_svc_params(self):
        self._p_max_iter(100)
        self._p_reg_param(0.0)
        self._p_tol(1e-6)
        self._p_fit_intercept(True)
        self._p_standardization(True)
        # thresholds on the RAW margin (unbounded), unlike the shared
        # probability threshold param — ref LinearSVC.threshold semantics
        self.threshold = self._param(
            "threshold", "margin threshold for the positive class",
            default=0.0)
        self._p_aggregation_depth(2)


class LinearSVC(Predictor, _LinearSVCParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_svc_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_reg_param(self, v):
        return self.set("regParam", v)

    def set_threshold(self, v):
        return self.set("threshold", v)

    def _fit(self, frame: MLFrame) -> "LinearSVCModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"),
            self.get("weightCol") or None)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "LinearSVCModel":
        d = ds.n_features
        stats = Summarizer.summarize(ds)
        features_std = stats.std
        weight_sum = stats.weight_sum
        fit_intercept = self.get("fitIntercept")
        standardize = self.get("standardization")
        reg = self.get("regParam")

        validate_binary_labels(ds.unpad(np.asarray(ds.y)), "LinearSVC")
        ds_std, inv_std = standardize_dataset(ds, features_std)

        agg = aggregators.hinge(d, fit_intercept)
        l2_fn = l2_regularization(reg, d, fit_intercept,
                                  features_std=features_std,
                                  standardize=standardize) if reg > 0 else None
        loss_fn = DistributedLossFunction(ds_std, agg, l2_fn, weight_sum)

        n_coef = d + (1 if fit_intercept else 0)
        opt = LBFGS(max_iter=self.get("maxIter"), tol=self.get("tol"))
        state = opt.minimize(loss_fn, np.zeros(n_coef))
        if state.converged_reason == "max iterations reached":
            logger.warning("LinearSVC did not converge in %d iterations",
                           self.get("maxIter"))

        beta = state.x[:d] * inv_std
        icpt = float(state.x[d]) if fit_intercept else 0.0
        model = LinearSVCModel(beta, icpt, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.objective_history = list(state.loss_history)
        return model


class LinearSVCModel(ClassificationModel, _LinearSVCParams,
                     MLWritable, MLReadable):
    def __init__(self, coefficients: Optional[np.ndarray] = None,
                 intercept: float = 0.0, uid=None):
        super().__init__(uid)
        self._declare_svc_params()
        self._coef = np.asarray(coefficients) if coefficients is not None else None
        self._icpt = float(intercept)
        self.objective_history = []

    @property
    def coefficients(self) -> DenseVector:
        return Vectors.dense(self._coef)

    @property
    def intercept(self) -> float:
        return self._icpt

    @property
    def num_classes(self) -> int:
        return 2

    @property
    def num_features(self) -> int:
        return len(self._coef)

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        m = x @ self._coef + self._icpt
        return np.stack([-m, m], axis=1)

    def _raw_to_prediction(self, raw: np.ndarray) -> np.ndarray:
        # threshold applies to the raw margin (ref LinearSVC rawPrediction
        # semantics), not a probability
        return (raw[:, 1] > self.get("threshold")).astype(np.float64)

    def _save_data(self, path: str) -> None:
        save_arrays(path, coef=self._coef, icpt=np.array(self._icpt))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._coef = arrs["coef"]
        self._icpt = float(arrs["icpt"])
