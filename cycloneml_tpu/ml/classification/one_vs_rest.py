"""One-vs-rest multiclass reduction.

Re-design of the reference (ref: ml/classification/OneVsRest.scala — fits
one binary copy of the base classifier per class over relabeled data, with
a ``parallelism`` thread pool; the model picks the class whose binary
margin is largest). The relabel is a host-side column swap.

``parallelism > 1`` routes through the STACKED fit engine when the base
classifier supports it (``fit_stacked``): the K binary fits share one
design matrix, so ``vmap`` runs them as ONE gang-scheduled SPMD program —
one trace + compile amortized over all K models, one psum per step
carrying K gradients, per-model convergence masks. The reference's thread
pool (and this repo's pre-stacking port of it) dispatched K concurrent
SPMD programs onto the shared mesh and deadlocked XLA's collective
rendezvous (graftlint JX007 now mechanizes that hazard); the serial loop
remains as the fallback for classifiers/configs the stacked engine does
not cover. See docs/multi-model.md.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import ClassificationModel, Estimator, Model
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import (
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasRawPredictionCol,
    HasWeightCol,
)
from cycloneml_tpu.ml.util_io import (
    MLReadable, MLWritable, load_pipeline_stages, save_pipeline_stages,
)
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _OVRParams(HasFeaturesCol, HasLabelCol, HasPredictionCol,
                 HasRawPredictionCol, HasWeightCol):
    def _declare_ovr_params(self):
        self._p_features_col()
        self._p_label_col()
        self._p_prediction_col()
        self._p_raw_prediction_col()
        self._p_weight_col()
        self.parallelism = self._param(
            "parallelism", "max concurrent binary fits (>= 1)",
            V.gt_eq(1), default=1)


class OneVsRest(Estimator, _OVRParams, MLWritable, MLReadable):
    def __init__(self, classifier: Optional[Estimator] = None, uid=None,
                 **kwargs):
        super().__init__(uid)
        self._declare_ovr_params()
        self.classifier = classifier
        for k, v in kwargs.items():
            self.set(k, v)

    def set_classifier(self, clf: Estimator) -> "OneVsRest":
        self.classifier = clf
        return self

    def set_parallelism(self, v):
        return self.set("parallelism", v)

    def _fit(self, frame: MLFrame) -> "OneVsRestModel":
        if self.classifier is None:
            raise ValueError("classifier must be set")
        label_col = self.get("labelCol")
        y = np.asarray(frame[label_col])
        num_classes = int(y.max()) + 1

        from cycloneml_tpu.dataset.instance import compute_dtype, data_dtype

        def _configure(clf):
            clf.set("featuresCol", self.get("featuresCol"))
            wc = self.get("weightCol")
            if wc and "weightCol" in clf._params:
                clf.set("weightCol", wc)
            return clf

        from cycloneml_tpu.mesh import safe_fit_parallelism
        requested = self.get("parallelism")
        clf = _configure(self.classifier.copy())
        stackable = (requested > 1 and num_classes > 1
                     and hasattr(clf, "fit_stacked")
                     and clf.can_fit_stacked()
                     and hasattr(frame, "to_instance_dataset"))
        if stackable:
            effective = safe_fit_parallelism(requested,
                                             stacked_width=num_classes)
            logger.info(
                "OneVsRest: fitting %d binary models as ONE stacked SPMD "
                "program (effective parallelism %d)", num_classes, effective)
            clf.set("labelCol", label_col)
            # ONE (K, n) binary label matrix in the DATA-tier dtype ({0, 1}
            # is exact in bf16) — not K fp64 host vectors (JX004 data-tier
            # discipline); the stacked engine consumes all K rows at once
            y_stack = (np.arange(num_classes)[:, None]
                       == y[None, :]).astype(
                           data_dtype(getattr(frame.ctx, "conf", None)))
            models = clf.fit_stacked(frame, y_stack)
        else:
            # serial fallback: SPMD fits stay on this thread (a >1 thread
            # pool deadlocks the shared mesh — mesh.safe_fit_parallelism);
            # relabels are one TRANSIENT data-tier-dtype vector per class
            # (a full (n, K) matrix would sit in host memory for all K
            # sequential fits for no reader)
            safe_fit_parallelism(requested)
            models = []
            for c in range(num_classes):
                binary = (y == c).astype(compute_dtype())
                sub = frame.with_column("_ovr_label", binary)
                one = _configure(self.classifier.copy())
                one.set("labelCol", "_ovr_label")
                models.append(one.fit(sub))

        model = OneVsRestModel(models, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model

    def copy(self, extra=None) -> "OneVsRest":
        that = super().copy(extra)
        that.classifier = self.classifier.copy() if self.classifier else None
        return that

    def _save_data(self, path: str) -> None:
        save_pipeline_stages([self.classifier], path)

    def _load_data(self, path: str, meta) -> None:
        self.classifier = load_pipeline_stages(path)[0]


class OneVsRestModel(Model, _OVRParams, MLWritable, MLReadable):
    def __init__(self, models: Optional[List[ClassificationModel]] = None,
                 uid=None):
        super().__init__(uid)
        self._declare_ovr_params()
        self.models = list(models or [])

    @property
    def num_classes(self) -> int:
        return len(self.models)

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        # margin of the positive class from each binary model
        margins = np.stack(
            [m._raw_prediction(x)[:, 1] for m in self.models], axis=1)
        out = frame
        if self.get("rawPredictionCol"):
            out = out.with_column(self.get("rawPredictionCol"), margins)
        out = out.with_column(self.get("predictionCol"),
                              margins.argmax(1).astype(np.float64))
        return out

    def copy(self, extra=None) -> "OneVsRestModel":
        that = super().copy(extra)
        that.models = [m.copy() for m in self.models]
        return that

    def _save_data(self, path: str) -> None:
        save_pipeline_stages(self.models, path)

    def _load_data(self, path: str, meta) -> None:
        self.models = load_pipeline_stages(path)
