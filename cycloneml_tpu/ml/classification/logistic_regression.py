"""Logistic regression (binomial + multinomial).

TPU-native re-design of the reference estimator
(ref: ml/classification/LogisticRegression.scala:286; train path
``trainImpl:935``): the same statistical semantics — label histogram +
feature std via one summarizer pass, training in standardized feature space,
elastic-net with the L1/L2 split handled by OWL-QN/L-BFGS
(``createOptimizer:777-814``), log-odds intercept initialisation, coefficient
unscaling back to original space, objective history in the summary — but the
per-iteration gradient is ONE jit-compiled XLA program: block margins on the
MXU, hierarchical psum instead of treeAggregate (SURVEY §3.3's hot loop).

Feature blocks stay resident in device HBM across iterations (the analog of
persisting standardized blocks MEMORY_AND_DISK at :968).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.matrices import DenseMatrix
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import Predictor, ProbabilisticClassificationModel
from cycloneml_tpu.ml.optim import LBFGS, LBFGSB, OWLQN, aggregators
from cycloneml_tpu.ml.optim.loss import (
    DistributedLossFunction, l2_regularization,
)
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import (
    HasAggregationDepth, HasElasticNetParam, HasFitIntercept, HasLabelCol,
    HasMaxBlockSizeInMB, HasMaxIter, HasRegParam, HasStandardization,
    HasThreshold, HasTol,
)
from cycloneml_tpu.ml.stat import Summarizer
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _LogisticRegressionParams(HasMaxIter, HasRegParam, HasElasticNetParam,
                                HasTol, HasFitIntercept, HasStandardization,
                                HasThreshold, HasAggregationDepth,
                                HasMaxBlockSizeInMB):
    def _declare_lr_params(self):
        self._p_max_iter(100)
        self._p_reg_param(0.0)
        self._p_elastic_net(0.0)
        self._p_tol(1e-6)
        self._p_fit_intercept(True)
        self._p_standardization(True)
        self._p_threshold(0.5)
        self._p_aggregation_depth(2)
        self._p_max_block_size(0.0)
        self.family = self._param(
            "family", "label distribution family",
            V.in_array(["auto", "binomial", "multinomial"]), default="auto")
        # step-level training checkpoints — the improvement SURVEY §5.4
        # flags over the reference, which only persists finished models (the
        # param NAME mirrors the reference's checkpointInterval on ALS/trees)
        self.checkpointDir = self._param(
            "checkpointDir", "directory for mid-training optimizer "
            "checkpoints; fit() resumes from the newest one", default="")
        self.checkpointInterval = self._param(
            "checkpointInterval", "iterations between checkpoints",
            V.gt(0), default=10)
        # box constraints on the solution select the bound-constrained
        # optimizer, exactly as the reference's createOptimizer does
        # (LogisticRegression.scala:777-814, BreezeLBFGSB at :788);
        # shapes follow the reference: coefficient bounds are
        # (numClasses-ish, d) matrices (binomial: (1, d)), intercept
        # bounds are vectors
        self.lowerBoundsOnCoefficients = self._param(
            "lowerBoundsOnCoefficients",
            "(k, d) lower bounds on coefficients", default=None)
        self.upperBoundsOnCoefficients = self._param(
            "upperBoundsOnCoefficients",
            "(k, d) upper bounds on coefficients", default=None)
        self.lowerBoundsOnIntercepts = self._param(
            "lowerBoundsOnIntercepts", "(k,) lower bounds on intercepts",
            default=None)
        self.upperBoundsOnIntercepts = self._param(
            "upperBoundsOnIntercepts", "(k,) upper bounds on intercepts",
            default=None)

    def _opt(self, name):
        """Optional param: None when never set (these have no default)."""
        return self.get(name) if self.is_defined(self.get_param(name)) else None

    def _has_bounds(self) -> bool:
        return any(self._opt(p) is not None for p in (
            "lowerBoundsOnCoefficients", "upperBoundsOnCoefficients",
            "lowerBoundsOnIntercepts", "upperBoundsOnIntercepts"))


class LogisticRegression(Predictor, _LogisticRegressionParams,
                         MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_lr_params()
        for k, v in kwargs.items():
            self.set(k, v)

    # fluent setters (PySpark-style camelCase params, snake-case methods)
    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_reg_param(self, v):
        return self.set("regParam", v)

    def set_elastic_net_param(self, v):
        return self.set("elasticNetParam", v)

    def set_tol(self, v):
        return self.set("tol", v)

    def set_fit_intercept(self, v):
        return self.set("fitIntercept", v)

    def set_standardization(self, v):
        return self.set("standardization", v)

    def set_family(self, v):
        return self.set("family", v)

    def set_threshold(self, v):
        return self.set("threshold", v)

    def _flat_bounds(self, d, num_classes, is_multinomial, fit_intercept,
                     n_coef, features_std):
        """Flatten user bounds into the optimizer's coefficient layout, in
        STANDARDIZED space: β_std = β_orig·std, so coefficient bounds scale
        by featuresStd exactly as the reference's createBounds does
        (LogisticRegression.scala:2085-2156). Intercepts are unscaled."""
        k_rows = num_classes if is_multinomial else 1
        n_feat = d * k_rows
        out = []
        for cp, ip, fill in (
                ("lowerBoundsOnCoefficients", "lowerBoundsOnIntercepts",
                 -np.inf),
                ("upperBoundsOnCoefficients", "upperBoundsOnIntercepts",
                 np.inf)):
            b = np.full(n_coef, fill)
            cb = self._opt(cp)
            if cb is not None:
                cb = np.asarray(cb, dtype=np.float64)
                if cb.ndim == 1 and k_rows == 1 and cb.size == d:
                    cb = cb[None, :]  # binomial convenience: a plain vector
                if cb.shape != (k_rows, d):
                    # exact-shape check: size alone would silently accept a
                    # TRANSPOSED multinomial matrix and scramble the box
                    raise ValueError(
                        f"{cp} must have shape ({k_rows}, {d}); "
                        f"got {cb.shape}")
                b[:n_feat] = (cb
                              * np.asarray(features_std)[None, :]).reshape(-1)
            ib = self._opt(ip)
            if ib is not None:
                if not fit_intercept:
                    raise ValueError(
                        f"{ip} requires fitIntercept=True")
                ib = np.asarray(ib, dtype=np.float64).reshape(-1)
                if ib.size != k_rows:
                    raise ValueError(
                        f"{ip} must have {k_rows} entries; got {ib.size}")
                b[n_feat:] = ib
            out.append(b)
        return out[0], out[1]

    def _optimize(self, opt, loss_fn, x0, fp_parts):
        """Shared optimize tail for the dense and sparse fit paths:
        checkpointed training (fingerprint-bound to dataset+params) when a
        checkpointDir is set, plain minimize otherwise, plus the
        non-convergence warning."""
        if self.get("checkpointDir"):
            import hashlib
            from cycloneml_tpu.parallel.resilience import (
                train_with_checkpoints)
            from cycloneml_tpu.util.checkpoint import TrainingCheckpointer
            # resuming someone else's checkpoint would silently return the
            # wrong model — bind the dir to this dataset+params
            fp = hashlib.sha1(repr(fp_parts).encode()).hexdigest()[:16]
            state = train_with_checkpoints(
                opt, loss_fn, x0,
                TrainingCheckpointer(self.get("checkpointDir")),
                interval=self.get("checkpointInterval"), fingerprint=fp)
        else:
            state = opt.minimize(loss_fn, x0)
        if state.converged_reason == "max iterations reached":
            logger.warning(
                "LogisticRegression did not converge in %d iterations",
                self.get("maxIter"))
        return state

    def _fit(self, frame) -> "LogisticRegressionModel":
        from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
        if isinstance(frame, SparseInstanceDataset):
            # the reference trains transparently on sparse vectors; here
            # the sparse tier has its own fit path (ELL/hybrid aggregators)
            return self._fit_sparse(frame)
        # fp8-capable: the scaled aggregators fold the per-column dequant
        # scales into inv_std, so this fit may ride the e4m3 rung of the
        # data tier (cyclone.data.dtype=auto8/float8)
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"),
            self.get("weightCol") or None, fp8_capable=True)
        return self._fit_dataset(ds)

    # -- stacked (model-axis) fits -------------------------------------------
    def can_fit_stacked(self) -> bool:
        """Param-level eligibility for the stacked (vmapped model-axis)
        fit: binomial objective, pure L2 (``elasticNetParam == 0``), no
        coefficient bounds, no mid-training checkpointing — the same
        preconditions as the chunked device optimizer the stacked engine
        drives. Data-level checks ({0, 1} labels, dense tier) happen inside
        :meth:`fit_stacked`."""
        return (self.get("family") != "multinomial"
                and float(self.get("elasticNetParam")) == 0.0
                and not self._has_bounds()
                and not self.get("checkpointDir"))

    def fit_stacked(self, frame, y_stack=None, reg_params=None):
        """Fit K binomial models over ONE shared design matrix as ONE
        gang-scheduled SPMD program (the sanctioned parallel path — see
        ``mesh.safe_fit_parallelism`` and docs/multi-model.md).

        ``vmap`` pushes a model axis through the staged optimizer step
        mechanically (Frostig et al. 2018; GSPMD, Xu et al. 2021): the K
        fits share one trace + XLA compile, every ``tree_aggregate`` psum
        carries all K gradients, and per-model convergence masks freeze
        early-converged models on device. No cross-program collective
        rendezvous exists, so — unlike thread-pool fan-out (the PR-2
        deadlock) — full model-parallelism is safe on any mesh.

        ``y_stack``: (K, n) per-model {0, 1} label vectors (OneVsRest's
        relabelings); default is the frame's own label column tiled K
        times. ``reg_params``: per-model L2 strength (CrossValidator's
        regParam grid); default is this estimator's ``regParam`` tiled.
        At least one of the two must be given. Returns a list of K
        :class:`LogisticRegressionModel` (summaries carry ``n_models``).
        """
        import jax.numpy as jnp

        from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
        from cycloneml_tpu.ml.optim.device_lbfgs import StackedDeviceLBFGS
        from cycloneml_tpu.ml.optim.loss import (
            StackedDistributedLossFunction, inv_std_vector,
            stacked_l2_scale, validate_binary_labels,
        )

        if not self.can_fit_stacked():
            raise ValueError(
                "fit_stacked requires a binomial, pure-L2, unbounded, "
                "non-checkpointed configuration (can_fit_stacked)")
        if isinstance(frame, SparseInstanceDataset):
            raise ValueError("stacked fits are dense-tier only")
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"),
            self.get("weightCol") or None, fp8_capable=True)
        # streamed stacked fits: ONE double-buffered epoch serves all K
        # models (the K-model grid/OvR fit reads the spill once per
        # optimizer round instead of K times)
        from cycloneml_tpu.oocore import (StreamingDataset, shard_dataset,
                                          streaming_mode)
        if isinstance(ds, StreamingDataset):
            return self._fit_stacked_streamed(ds, y_stack, reg_params)
        if streaming_mode(getattr(ds.ctx, "conf", None)) == "force":
            sds = shard_dataset(ds)
            try:
                return self._fit_stacked_streamed(sds, y_stack, reg_params)
            finally:
                sds.close()
        if y_stack is None and reg_params is None:
            raise ValueError("fit_stacked needs y_stack or reg_params")
        if y_stack is None:
            y = np.asarray(ds.unpad(ds.y_host()), dtype=np.float64)
            y_stack = np.broadcast_to(y, (len(reg_params), len(y)))
        # keep the caller's storage (OvR hands a data-tier bf16 stack — at
        # target scale a full (K, n) f64 clone would be 4x the stack it
        # was narrowed to save); host-side math below converts ONE (n,)
        # model row at a time, which is lossless for {0, 1} labels
        y_stack = np.asarray(y_stack)
        n_models = y_stack.shape[0]
        if y_stack.shape[1] != ds.n_rows:
            raise ValueError(
                f"y_stack has {y_stack.shape[1]} rows per model; dataset "
                f"has {ds.n_rows}")
        for kk in range(n_models):
            validate_binary_labels(
                np.asarray(y_stack[kk], dtype=np.float64), "fit_stacked")
        reg = self.get("regParam")
        if reg_params is None:
            reg_params = np.full(n_models, float(reg))
        reg_params = np.asarray(reg_params, dtype=np.float64)
        if len(reg_params) != n_models:
            raise ValueError("reg_params length != number of stacked models")

        d = ds.n_features
        stats = Summarizer.summarize(ds)
        from cycloneml_tpu.dataset.dataset import resolve_fp8_fit
        ds = resolve_fp8_fit(ds, stats, "LogisticRegression(stacked)")
        fp8_scale = ds.x_scale
        features_std = stats.std
        weight_sum = stats.weight_sum
        fit_intercept = self.get("fitIntercept")
        standardize = self.get("standardization")
        fit_with_mean = fit_intercept  # bounds are excluded by eligibility
        inv_std = inv_std_vector(features_std)
        scaled_mean = stats.mean * inv_std if fit_with_mean else np.zeros(d)
        # fp8: dequant folds into the aggregator-side inv_std (see
        # _fit_dataset); unscaling below keeps the original
        inv_std_agg = inv_std * fp8_scale if fp8_scale is not None \
            else inv_std

        n_coef = d + (1 if fit_intercept else 0)
        x0 = np.zeros((n_models, n_coef))
        if fit_intercept:
            w_real = np.asarray(ds.unpad(ds.w_host()), dtype=np.float64)
            # per-model weighted positive mass, one f64 row at a time
            pos = np.array([np.asarray(y_stack[kk], dtype=np.float64)
                            @ w_real for kk in range(n_models)])
            ok = (pos > 0) & (pos < weight_sum)
            p1 = np.where(ok, pos / weight_sum, 0.5)
            x0[:, d] = np.where(ok, np.log(p1 / (1.0 - p1)), 0.0)

        # the stacked (n_pad, K) label matrix rides the dataset's row
        # sharding in the data-tier dtype ({0, 1} is exact in bf16, and at
        # large K the stack is a real per-sweep byte cost); X itself is
        # SHARED via derive — no second feature copy exists. Under the
        # fp8 tier the stack stays at the bf16 rung: labels mix
        # elementwise with f32 margins, and jax (deliberately) refuses
        # implicit 8-bit float promotion
        xdt = np.dtype(str(ds.x.dtype))
        if fp8_scale is not None:
            import ml_dtypes
            xdt = np.dtype(ml_dtypes.bfloat16)
        y_pad = np.zeros((len(ds.y_host()), n_models), dtype=xdt)
        valid = ds.valid_indices()
        for kk in range(n_models):
            y_pad[valid, kk] = np.asarray(y_stack[kk], dtype=xdt)
        rt = ds.ctx.mesh_runtime
        ds_stacked = ds.derive(y=rt.device_put_sharded_rows(y_pad))

        # stacked fits ride the fused Pallas kernel wherever the serial
        # path would (vmap batches the kernel's row pass mechanically);
        # the vmapped jnp aggregator is the fallback
        from cycloneml_tpu.dataset.instance import compute_dtype
        from cycloneml_tpu.ops.kernels import use_fused_kernels
        base_agg = (aggregators.binary_logistic_pallas_scaled(d, fit_intercept)
                    if use_fused_kernels(ds.ctx)
                    else aggregators.binary_logistic_scaled(d, fit_intercept))
        agg = aggregators.stack_scaled_aggregator(base_agg)
        l2s = stacked_l2_scale(d, n_coef, features_std, standardize)
        adt = compute_dtype()  # standardization vectors: accumulator tier
        loss_fn = StackedDistributedLossFunction(
            ds_stacked, agg, n_models, reg=reg_params, l2_scale=l2s,
            weight_sum=weight_sum,
            extra_args=(jnp.asarray(inv_std_agg.astype(adt)),
                        jnp.asarray(scaled_mean.astype(adt))))

        from cycloneml_tpu.conf import LBFGS_DEVICE_CHUNK
        chunk = int(ds.ctx.conf.get(LBFGS_DEVICE_CHUNK)) \
            if hasattr(ds.ctx, "conf") else 0
        # deviceChunk=0 means "one dispatch per iteration"; the stacked
        # engine has no host loop, so honor it as chunk=1 (per-iteration
        # dispatches) rather than silently running the default chunk
        opt = StackedDeviceLBFGS(max_iter=self.get("maxIter"),
                                 tol=self.get("tol"),
                                 chunk=max(chunk, 1))
        res = opt.minimize(loss_fn, x0)
        if fp8_scale is not None \
                and not np.all(np.isfinite(np.asarray(res.x))):
            from cycloneml_tpu.dataset.dataset import fp8_fallback
            return self.fit_stacked(
                fp8_fallback(ds, "LogisticRegression(stacked)",
                             "non-finite fp8 solution"),
                y_stack=y_stack, reg_params=reg_params)
        n_unconverged = sum(
            1 for r in res.converged_reasons if r == "max iterations reached")
        if n_unconverged:
            logger.warning(
                "stacked LogisticRegression: %d of %d models did not "
                "converge in %d iterations", n_unconverged, n_models,
                self.get("maxIter"))

        models = []
        for kk in range(n_models):
            sol = res.x[kk]
            beta = sol[:d] * inv_std
            icpt = float(sol[d]) if fit_intercept else 0.0
            if fit_with_mean:
                icpt -= float(sol[:d] @ scaled_mean)
            model = LogisticRegressionModel(
                coefficient_matrix=beta[None, :],
                intercept_vector=np.array([icpt]),
                num_classes=2, is_multinomial=False)
            self._copy_values(model)
            model._set_parent(self)
            model.summary = LogisticRegressionTrainingSummary(
                objective_history=list(res.loss_histories[kk]),
                total_iterations=int(res.iterations[kk]),
                total_evals=int(res.evals[kk]),
                total_dispatches=loss_fn.n_dispatches,
                n_models=n_models)
            models.append(model)
        return models

    def _fit_stacked_streamed(self, sds, y_stack=None, reg_params=None):
        """The out-of-core leg of :meth:`fit_stacked`: K binomial models
        over ONE shard set, each optimizer round ONE streamed epoch whose
        per-shard program is the vmapped scaled aggregator
        (``StackedStreamingLossFunction``) — so the spill is read once
        per round, not once per model. The optimizer is
        :class:`StackedHostLBFGS`: K serial L-BFGS coroutines whose
        pending trial points batch into each epoch, every model making
        exactly the decisions its serial streamed fit would (the parity
        test pins rtol 1e-9 under the f64 config)."""
        import jax.numpy as jnp

        from cycloneml_tpu.dataset.instance import compute_dtype
        from cycloneml_tpu.ml.optim.device_lbfgs import StackedHostLBFGS
        from cycloneml_tpu.ml.optim.loss import (inv_std_vector,
                                                 stacked_l2_scale,
                                                 validate_binary_labels)
        from cycloneml_tpu.oocore import StackedStreamingLossFunction

        if y_stack is None and reg_params is None:
            raise ValueError("fit_stacked needs y_stack or reg_params")
        d = sds.n_features
        stats = sds.summary()   # write-pass moments: no stats epoch
        weight_sum = stats.weight_sum
        # the fp8 decision already ran at spill time (the
        # materialization-time envelope probe in shards._finalize_fp8);
        # the dequant scale folds into inv_std exactly like in-core
        fp8_scale = getattr(sds, "x_scale", None)

        if y_stack is None:
            # tiled grid fit over the shard set's own labels: binary-ness
            # comes from the write-pass histogram, positives from the
            # label moments — zero label epochs
            hist = sds.label_histogram()
            if len(hist) > 2:
                raise ValueError(
                    f"fit_stacked requires binary {{0, 1}} labels; the "
                    f"shard set carries {len(hist)} classes")
            n_models = len(reg_params)
            pos = np.full(n_models, sds.y_moments()[0])
        else:
            y_stack = np.asarray(y_stack)
            n_models = y_stack.shape[0]
            if y_stack.shape[1] != sds.n_rows:
                raise ValueError(
                    f"y_stack has {y_stack.shape[1]} rows per model; the "
                    f"shard set has {sds.n_rows}")
            for kk in range(n_models):
                validate_binary_labels(
                    np.asarray(y_stack[kk], dtype=np.float64),
                    "fit_stacked")
            # per-model weighted positive mass from the shards' w members
            # only (npz members load lazily: the packed X bytes stay on
            # disk) — one O(n) host vector, matching the caller's own
            # O(K·n) stack
            w_all = np.concatenate([
                np.asarray(np.load(s.path)["w"], dtype=np.float64)
                for s in sds._shards])
            pos = np.array([
                np.asarray(y_stack[kk], dtype=np.float64) @ w_all
                for kk in range(n_models)])
        reg = self.get("regParam")
        if reg_params is None:
            reg_params = np.full(n_models, float(reg))
        reg_params = np.asarray(reg_params, dtype=np.float64)
        if len(reg_params) != n_models:
            raise ValueError("reg_params length != number of stacked models")

        features_std = stats.std
        fit_intercept = self.get("fitIntercept")
        standardize = self.get("standardization")
        fit_with_mean = fit_intercept  # bounds are excluded by eligibility
        inv_std = inv_std_vector(features_std)
        scaled_mean = stats.mean * inv_std if fit_with_mean else np.zeros(d)
        inv_std_agg = inv_std * fp8_scale if fp8_scale is not None \
            else inv_std

        n_coef = d + (1 if fit_intercept else 0)
        x0 = np.zeros((n_models, n_coef))
        if fit_intercept:
            ok = (pos > 0) & (pos < weight_sum)
            p1 = np.where(ok, pos / weight_sum, 0.5)
            x0[:, d] = np.where(ok, np.log(p1 / (1.0 - p1)), 0.0)

        from cycloneml_tpu.ops.kernels import use_fused_kernels
        base_agg = (aggregators.binary_logistic_pallas_scaled(d,
                                                              fit_intercept)
                    if use_fused_kernels(sds.ctx)
                    else aggregators.binary_logistic_scaled(d, fit_intercept))
        agg = aggregators.stack_scaled_aggregator(base_agg)
        l2s = stacked_l2_scale(d, n_coef, features_std, standardize)
        adt = compute_dtype()
        # the staged (rows, K) label stack: {0, 1} is exact in bf16, and
        # f64 under the x64 parity config keeps streamed-vs-serial
        # summation identical; never fp8 — labels mix with f32 margins
        if adt is np.float64:
            ydt = np.float64
        else:
            import ml_dtypes
            ydt = ml_dtypes.bfloat16
        loss_fn = StackedStreamingLossFunction(
            sds, agg, n_models, reg=reg_params, l2_scale=l2s,
            weight_sum=weight_sum,
            extra_args=(jnp.asarray(inv_std_agg.astype(adt)),
                        jnp.asarray(scaled_mean.astype(adt))),
            y_stack=y_stack, y_dtype=ydt)

        opt = StackedHostLBFGS(max_iter=self.get("maxIter"),
                               tol=self.get("tol"))
        res = opt.minimize(loss_fn, x0)
        if fp8_scale is not None \
                and not np.all(np.isfinite(np.asarray(res.x))):
            # e4m3 has no inf: overflow surfaces as NaN — re-spill the
            # shard set at the bf16 rung (PrecisionFallback event) and
            # refit
            bf16 = sds.to_instance_dataset(fp8_capable=False)
            try:
                return self._fit_stacked_streamed(
                    bf16, y_stack=y_stack, reg_params=reg_params)
            finally:
                bf16.close()
        n_unconverged = sum(
            1 for r in res.converged_reasons if r == "max iterations reached")
        if n_unconverged:
            logger.warning(
                "stacked LogisticRegression (streamed): %d of %d models did "
                "not converge in %d iterations", n_unconverged, n_models,
                self.get("maxIter"))

        models = []
        for kk in range(n_models):
            sol = res.x[kk]
            beta = sol[:d] * inv_std
            icpt = float(sol[d]) if fit_intercept else 0.0
            if fit_with_mean:
                icpt -= float(sol[:d] @ scaled_mean)
            model = LogisticRegressionModel(
                coefficient_matrix=beta[None, :],
                intercept_vector=np.array([icpt]),
                num_classes=2, is_multinomial=False)
            self._copy_values(model)
            model._set_parent(self)
            model.summary = LogisticRegressionTrainingSummary(
                objective_history=list(res.loss_histories[kk]),
                total_iterations=int(res.iterations[kk]),
                total_evals=int(res.evals[kk]),
                total_dispatches=loss_fn.n_dispatches,
                n_models=n_models, streamed=True)
            models.append(model)
        return models

    def _fit_sparse(self, ds) -> "LogisticRegressionModel":
        """Binomial logistic regression over the sparse (ELL / ELL+COO
        hybrid) tier: same statistical semantics as the dense path —
        std-only standardization (sparsity-preserving, as the reference),
        log-odds intercept init, elastic net via OWL-QN/L-BFGS, LBFGS-B
        under bounds — with gather/segment-sum aggregators instead of
        block matmuls."""
        from cycloneml_tpu.dataset.sparse import (sparse_feature_std,
                                                  standardize_sparse_dataset)
        from cycloneml_tpu.ml.optim.sparse_aggregators import (
            binary_logistic_sparse, binary_logistic_sparse_hybrid)

        d = ds.n_features
        w_host = np.asarray(ds.w)
        y_host = np.asarray(ds.y)
        mask = w_host > 0
        num_classes = int(y_host[mask].max()) + 1 if mask.any() else 2
        family = self.get("family")
        if family == "multinomial" or (family == "auto" and num_classes > 2):
            raise NotImplementedError(
                "sparse-tier training is binomial only; hash or densify "
                "for multinomial")
        if num_classes > 2:
            # family="binomial" with >2 label classes: reject exactly as
            # the dense path (and the reference) does
            raise ValueError(
                f"Binomial family requires <= 2 label classes, found "
                f"{num_classes} (the reference rejects this too)")
        histogram = np.bincount(y_host[mask].astype(np.int64),
                                weights=w_host[mask], minlength=2)[:2]
        weight_sum = float(w_host[mask].sum())

        fit_intercept = self.get("fitIntercept")
        standardize = self.get("standardization")
        reg = self.get("regParam")
        alpha = self.get("elasticNetParam")
        l2 = (1.0 - alpha) * reg
        l1 = alpha * reg

        features_std = sparse_feature_std(ds)
        ds_std, inv_std = standardize_sparse_dataset(ds, features_std)

        agg = (binary_logistic_sparse_hybrid(d, fit_intercept)
               if ds.is_hybrid else binary_logistic_sparse(d, fit_intercept))
        n_coef = d + (1 if fit_intercept else 0)
        x0 = np.zeros(n_coef)
        if fit_intercept and 0 < histogram[1] < weight_sum:
            p1 = histogram[1] / weight_sum
            x0[d] = np.log(p1 / (1.0 - p1))
        l2_fn = l2_regularization(
            l2, d, fit_intercept, features_std=features_std,
            standardize=standardize) if l2 > 0 else None
        loss_fn = DistributedLossFunction(ds_std, agg, l2_fn, weight_sum)

        if self._has_bounds():
            if alpha != 0.0:
                raise ValueError(
                    "coefficient bounds are only supported with none or L2 "
                    "regularization (elasticNetParam must be 0, as the "
                    "reference enforces)")
            lo, hi = self._flat_bounds(d, 2, False, fit_intercept, n_coef,
                                       features_std)
            opt = LBFGSB(lo, hi, max_iter=self.get("maxIter"),
                         tol=self.get("tol"))
        elif l1 > 0:
            l1_vec = np.zeros(n_coef)
            per = np.full(d, l1)
            if not standardize:
                per = np.where(features_std > 0,
                               l1 / np.where(features_std > 0,
                                             features_std, 1.0), 0.0)
            l1_vec[:d] = per
            opt = OWLQN(max_iter=self.get("maxIter"), tol=self.get("tol"),
                        l1_reg=l1_vec)
        else:
            opt = LBFGS(max_iter=self.get("maxIter"), tol=self.get("tol"))
        state = self._optimize(opt, loss_fn, x0, (
            ds.n_rows, d, 2, float(weight_sum),
            np.asarray(histogram).round(6).tolist(),
            np.asarray(features_std).round(6).tolist(),
            reg, alpha, self.get("tol"), fit_intercept, standardize,
            "sparse",
        ))

        sol = state.x
        beta = sol[:d] * inv_std
        icpt = float(sol[d]) if fit_intercept else 0.0
        model = LogisticRegressionModel(
            coefficient_matrix=beta[None, :],
            intercept_vector=np.array([icpt]),
            num_classes=2, is_multinomial=False, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.summary = LogisticRegressionTrainingSummary(
            objective_history=list(state.loss_history),
            total_iterations=state.iteration,
            total_evals=loss_fn.n_evals,
            total_dispatches=loss_fn.n_dispatches)
        return model

    def _fit_dataset(self, ds: InstanceDataset) -> "LogisticRegressionModel":
        import jax
        import jax.numpy as jnp

        from cycloneml_tpu.oocore import StreamingDataset, streaming_mode
        streamed = isinstance(ds, StreamingDataset)
        if not streamed and \
                streaming_mode(getattr(ds.ctx, "conf", None)) == "force":
            # explicit streaming mode: spill the in-core dataset to shards
            # and run the same fit over streamed epochs; the spill is owned
            # by THIS fit, so its files are removed once the model is built
            from cycloneml_tpu.oocore import shard_dataset
            sds = shard_dataset(ds)
            try:
                return self._fit_dataset(sds)
            finally:
                sds.close()

        d = ds.n_features
        # streamed datasets carry their Summarizer moments and the label
        # histogram from the shard WRITE pass — no stats epoch is paid
        stats = ds.summary() if streamed else Summarizer.summarize(ds)
        if not streamed:
            # fp8 safety rail: the envelope probe may swap the quantized
            # dataset for its bf16 dequantization (event + profile field)
            from cycloneml_tpu.dataset.dataset import resolve_fp8_fit
            ds = resolve_fp8_fit(ds, stats, "LogisticRegression")
        fp8_scale = getattr(ds, "x_scale", None)
        features_std = stats.std
        weight_sum = stats.weight_sum

        # label histogram via one psum pass (≈ the summary treeAggregate at
        # LogisticRegression.scala:515 area)
        if streamed:
            hist = ds.label_histogram()
            num_classes = max(len(hist), 2) if ds.n_rows else 2
        else:
            y_host = ds.y_host()
            w_host = ds.w_host()
            num_classes = int(y_host.max()) + 1 if ds.n_rows else 2
        family = self.get("family")
        if family == "auto":
            is_multinomial = num_classes > 2
        else:
            is_multinomial = family == "multinomial"
            if not is_multinomial and num_classes > 2:
                raise ValueError(
                    f"Binomial family requires <= 2 label classes, found "
                    f"{num_classes} (the reference rejects this too)")
            num_classes = max(num_classes, 2)
        if streamed:
            histogram = np.zeros(num_classes)
            histogram[:len(hist)] = hist[:num_classes]
        else:
            histogram = np.bincount(y_host.astype(np.int64), weights=w_host,
                                    minlength=num_classes)[:num_classes]

        fit_intercept = self.get("fitIntercept")
        standardize = self.get("standardization")
        reg = self.get("regParam")
        alpha = self.get("elasticNetParam")
        l2 = (1.0 - alpha) * reg
        l1 = alpha * reg

        # fitWithMean (ref LogisticRegression.scala:946-955, SPARK-34448):
        # with a free intercept, train on CENTERED standardized features —
        # decorrelates the intercept from offset features so small-variance
        # columns condition properly. Allowed exactly when the intercept is
        # unbounded; the intercept is mapped back after optimization.
        fit_with_mean = fit_intercept and all(
            self._opt(p) is None for p in ("lowerBoundsOnIntercepts",
                                           "upperBoundsOnIntercepts"))

        rt = ds.ctx.mesh_runtime
        from cycloneml_tpu.ops.kernels import use_fused_kernels
        from cycloneml_tpu.parallel import feature_sharding as fs
        m = fs.model_parallelism(rt)
        tp_active = (not is_multinomial) and m > 1 and d % m == 0 \
            and not streamed
        # fused Pallas kernels are the DEFAULT sweep on natively-lowered
        # backends (usePallasKernels=auto): one VMEM-resident row pass per
        # evaluation, bf16 blocks read at storage width with fp32 in-kernel
        # accumulation; the XLA-fused jnp aggregator stays as the fallback
        # (and the only path on CPU, where the interpreter is for tests)
        use_pallas = (not is_multinomial) and use_fused_kernels(ds.ctx)
        # EVERY fit path folds standardization (and fitWithMean centering)
        # INTO the aggregator read — no standardized copy exists anywhere:
        # replicated binomial/multinomial since r4; the feature-sharded TP
        # program and the Pallas kernel path since r5 (r4 verdict item 3 —
        # the paths that exist for models too big for one chip must not
        # carry 2× the memory they need). The fit's HBM working set is X
        # itself and the pre-fit standardize pass disappears.
        from cycloneml_tpu.ml.optim.loss import inv_std_vector
        inv_std = inv_std_vector(features_std)
        scaled_mean = stats.mean * inv_std if fit_with_mean else None
        # fp8 tier: dequantization folds into the replicated inv_std the
        # aggregators already carry — x̂ = (codes∘scale − μ)/σ =
        # codes∘(scale/σ) − μ/σ, so the AGGREGATOR sees scale∘inv_std
        # while scaled_mean (μ/σ) and the final unscaling (β/σ) keep the
        # original inv_std. The wide X never re-materializes.
        inv_std_agg = inv_std * fp8_scale if fp8_scale is not None \
            else inv_std

        if is_multinomial:
            # always the scaled aggregator: the TP/pallas alternatives are
            # binomial-only, so use_scaled cannot be False here
            agg = aggregators.multinomial_logistic_scaled(
                d, num_classes, fit_intercept)
            n_coef = d * num_classes + (num_classes if fit_intercept else 0)
            x0 = np.zeros(n_coef)
            if fit_intercept and histogram.min() > 0:
                logs = np.log(histogram / histogram.sum())
                x0[d * num_classes:] = logs - logs.mean()
            l2_fn = l2_regularization(
                l2, d * num_classes, fit_intercept,
                features_std=np.tile(features_std, num_classes),
                standardize=standardize) if l2 > 0 else None
        else:
            if use_pallas:
                agg = aggregators.binary_logistic_pallas_scaled(
                    d, fit_intercept)
            else:
                agg = aggregators.binary_logistic_scaled(d, fit_intercept)
            n_coef = d + (1 if fit_intercept else 0)
            x0 = np.zeros(n_coef)
            if fit_intercept and 0 < histogram[1:].sum() < weight_sum:
                p1 = histogram[1:].sum() / weight_sum
                x0[d] = np.log(p1 / (1.0 - p1))
            l2_fn = l2_regularization(
                l2, d, fit_intercept, features_std=features_std,
                standardize=standardize) if l2 > 0 else None

        mu_or_zero = scaled_mean if fit_with_mean else np.zeros(d)
        if tp_active:
            # model axis present: feature-shard the RAW blocks, the
            # coefficients, AND the standardization vectors (SURVEY §5.7a
            # — the path for d beyond one device's HBM; binomial only, the
            # multinomial aggregator stays replicated for now). Narrow
            # data-tier blocks upcast at the TP boundary
            # (fs.accumulator_width — the engine keys optimizer state off
            # X's dtype).
            x_tp = fs.feature_sharded_put(rt, fs.accumulator_width(ds.x))
            loss_fn = fs.FeatureShardedLossFunction(
                rt, x_tp, ds.y, ds.w, d, fit_intercept, l2_fn,
                weight_sum, ctx=ds.ctx, inv_std=inv_std_agg,
                scaled_mean=mu_or_zero)
        else:
            import jax.numpy as jnp
            from cycloneml_tpu.dataset.instance import compute_dtype
            # standardization vectors ride in the ACCUMULATOR tier: (d,)
            # replicated vectors are free next to X, and the fold's
            # corrections (inv_std∘g − μ̂·Σmult) must not round through the
            # bf16 data tier
            adt = compute_dtype()
            extras = (jnp.asarray(inv_std_agg.astype(adt)),
                      jnp.asarray(mu_or_zero.astype(adt)))
            if streamed:
                # the streamed twin: SAME aggregator, same extras, same
                # normalization — one loss/grad evaluation is one
                # double-buffered epoch over the shard set
                from cycloneml_tpu.oocore import StreamingLossFunction
                loss_fn = StreamingLossFunction(
                    ds, agg, l2_fn, weight_sum, extra_args=extras)
            else:
                loss_fn = DistributedLossFunction(
                    ds, agg, l2_fn, weight_sum, extra_args=extras)

        if self._has_bounds():
            # box-constrained path (ref createOptimizer selects BreezeLBFGSB
            # whenever bounds are set, LogisticRegression.scala:788; bounds
            # are only legal with none/L2 regularization there too)
            if alpha != 0.0:
                # the reference rejects ANY nonzero elasticNetParam with
                # bounds, regardless of regParam
                raise ValueError(
                    "coefficient bounds are only supported with none or L2 "
                    "regularization (elasticNetParam must be 0, as the "
                    "reference enforces)")
            lo, hi = self._flat_bounds(d, num_classes, is_multinomial,
                                       fit_intercept, n_coef, features_std)
            opt = LBFGSB(lo, hi, max_iter=self.get("maxIter"),
                         tol=self.get("tol"))
        elif l1 > 0:
            n_feat_coords = d * num_classes if is_multinomial else d
            l1_vec = np.zeros(n_coef)
            per_coord = np.full(n_feat_coords, l1)
            if not standardize:
                stds = np.tile(features_std, num_classes) if is_multinomial else features_std
                per_coord = np.where(stds > 0, l1 / np.where(stds > 0, stds, 1.0), 0.0)
            l1_vec[:n_feat_coords] = per_coord
            opt = OWLQN(max_iter=self.get("maxIter"), tol=self.get("tol"),
                        l1_reg=l1_vec)
        else:
            opt = LBFGS(max_iter=self.get("maxIter"), tol=self.get("tol"))
            # chunked device optimizer: K whole iterations per dispatch
            # (two-loop + Wolfe + convergence all on device). Eligible when
            # the loss is the dense replicated tier with a standardized (or
            # no) L2, and no checkpointing (checkpoints want per-iteration
            # states).
            from cycloneml_tpu.conf import LBFGS_DEVICE_CHUNK
            chunk = int(ds.ctx.conf.get(LBFGS_DEVICE_CHUNK)) \
                if hasattr(ds.ctx, "conf") else 0
            if (chunk > 0 and not self.get("checkpointDir")
                    and isinstance(loss_fn, DistributedLossFunction)
                    and (l2_fn is None or hasattr(l2_fn, "traceable"))):
                from cycloneml_tpu.ml.optim.device_lbfgs import DeviceLBFGS
                opt = DeviceLBFGS(max_iter=self.get("maxIter"),
                                  tol=self.get("tol"), chunk=chunk)
                # this fit HAS a streaming twin: when chunk-halving bottoms
                # out still over budget, degrade to it instead of
                # warn-proceeding toward an OOM (cyclone.oocore.mode=auto)
                opt.oocore_fallback = True

        from cycloneml_tpu.observe.costs import OutOfCoreRequired
        try:
            state = self._optimize(opt, loss_fn, x0, (
                ds.n_rows, d, num_classes, float(weight_sum),
                np.asarray(histogram).round(6).tolist(),
                np.asarray(features_std).round(6).tolist(),
                reg, alpha, self.get("tol"), fit_intercept, standardize,
                fit_with_mean,
            ))
        except OutOfCoreRequired as e:
            # the budget guard's terminal degradation: re-route the whole
            # fit through the streaming epoch engine (same objective, host
            # optimizer, O(shard) peak HBM) instead of OOMing/raising
            logger.warning("LogisticRegression: %s", e)
            from cycloneml_tpu.oocore import shard_dataset
            sds = shard_dataset(ds)
            try:
                return self._fit_dataset(sds)
            finally:
                sds.close()

        if fp8_scale is not None and not np.all(np.isfinite(state.x)):
            # e4m3 has no inf: an overflowing fp8 fit surfaces as NaN in
            # the solution — refit on the bf16 rung (belt to the probe's
            # braces; same event + profile surfacing)
            from cycloneml_tpu.dataset.dataset import fp8_fallback
            return self._fit_dataset(fp8_fallback(
                ds, "LogisticRegression", "non-finite fp8 solution"))

        sol = state.x
        if is_multinomial:
            wmat = sol[: d * num_classes].reshape(num_classes, d) * inv_std[None, :]
            icpt = sol[d * num_classes:] if fit_intercept else np.zeros(num_classes)
            if fit_with_mean:
                # un-adapt: centered-problem intercepts back to original
                # space (ref LogisticRegression.scala:1018-1024 dgemv adapt)
                icpt = icpt - sol[: d * num_classes].reshape(
                    num_classes, d) @ scaled_mean
            if not self._has_bounds():
                if reg == 0.0:
                    # center coefficients for identifiability, as the
                    # reference does when the multinomial problem has no
                    # regularization (LogisticRegression.scala:656-674,
                    # following glmnet)
                    wmat = wmat - wmat.mean(axis=0, keepdims=True)
                # intercepts are NEVER regularized, so their additive
                # constant stays free under ANY regParam — the reference
                # centers them unconditionally for multinomial
                # (LogisticRegression.scala:676-681); without this, L1
                # fits match glmnet in coefficients but drift in
                # intercepts by a shared constant
                if fit_intercept:
                    icpt = icpt - icpt.mean()
            model = LogisticRegressionModel(
                coefficient_matrix=wmat, intercept_vector=icpt,
                num_classes=num_classes, is_multinomial=True, uid=self.uid)
        else:
            beta = sol[:d] * inv_std
            icpt = float(sol[d]) if fit_intercept else 0.0
            if fit_with_mean:
                # ref LogisticRegression.scala:1027-1031: solution(num) -= adapt
                icpt -= float(sol[:d] @ scaled_mean)
            model = LogisticRegressionModel(
                coefficient_matrix=beta[None, :], intercept_vector=np.array([icpt]),
                num_classes=2, is_multinomial=False, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.summary = LogisticRegressionTrainingSummary(
            objective_history=list(state.loss_history),
            total_iterations=state.iteration,
            total_evals=loss_fn.n_evals,
            total_dispatches=loss_fn.n_dispatches,
            streamed=streamed)
        return model

    def copy(self, extra=None) -> "LogisticRegression":
        return super().copy(extra)


class LogisticRegressionModel(ProbabilisticClassificationModel,
                              _LogisticRegressionParams, HasLabelCol,
                              MLWritable, MLReadable):
    """Fitted model (ref LogisticRegressionModel at
    ml/classification/LogisticRegression.scala:1106-ish): margins, sigmoid/
    softmax probabilities, threshold-aware binary prediction."""

    def __init__(self, coefficient_matrix: Optional[np.ndarray] = None,
                 intercept_vector: Optional[np.ndarray] = None,
                 num_classes: int = 2, is_multinomial: bool = False, uid=None):
        super().__init__(uid)
        self._declare_lr_params()
        # the model carries labelCol so evaluate() scores the right column
        # (ref: LogisticRegressionModel extends HasLabelCol via its summary)
        self._p_label_col()
        self._coef = np.asarray(coefficient_matrix) if coefficient_matrix is not None else None
        self._icpt = np.asarray(intercept_vector) if intercept_vector is not None else None
        self._num_classes = num_classes
        self._is_multinomial = is_multinomial
        self.summary: Optional[LogisticRegressionTrainingSummary] = None

    # -- reference accessors ---------------------------------------------------
    @property
    def coefficients(self) -> DenseVector:
        if self._is_multinomial:
            raise ValueError("use coefficientMatrix for multinomial models")
        return Vectors.dense(self._coef[0])

    @property
    def intercept(self) -> float:
        if self._is_multinomial:
            raise ValueError("use interceptVector for multinomial models")
        return float(self._icpt[0])

    @property
    def coefficient_matrix(self) -> DenseMatrix:
        return DenseMatrix.from_array(self._coef)

    @property
    def intercept_vector(self) -> DenseVector:
        return Vectors.dense(self._icpt)

    def evaluate(self, frame: MLFrame) -> "BinaryLogisticRegressionSummary":
        return _lr_evaluate(self, frame)

    @property
    def num_classes(self) -> int:
        return self._num_classes

    @property
    def num_features(self) -> int:
        return self._coef.shape[1]

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        if self._is_multinomial:
            return x @ self._coef.T + self._icpt[None, :]
        m = x @ self._coef[0] + self._icpt[0]
        return np.stack([-m, m], axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        if not self._is_multinomial:
            # binomial raw is (-m, m): probability is sigmoid(m), NOT softmax
            # of the pair (which would be sigmoid(2m)) — matches the
            # reference's raw2probabilityInPlace
            p1 = 1.0 / (1.0 + np.exp(-raw[:, 1]))
            return np.stack([1.0 - p1, p1], axis=1)
        z = raw - raw.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)

    def _raw_to_prediction(self, raw: np.ndarray) -> np.ndarray:
        if not self._is_multinomial:
            t = self.get("threshold")
            prob1 = 1.0 / (1.0 + np.exp(-raw[:, 1]))
            return (prob1 > t).astype(np.float64)
        return np.argmax(raw, axis=1).astype(np.float64)

    def _save_data(self, path: str) -> None:
        save_arrays(path, coef=self._coef, icpt=self._icpt,
                    num_classes=np.array(self._num_classes),
                    is_multinomial=np.array(self._is_multinomial))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._coef = arrs["coef"]
        self._icpt = arrs["icpt"]
        self._num_classes = int(arrs["num_classes"])
        self._is_multinomial = bool(arrs["is_multinomial"])

    def __repr__(self) -> str:
        return (f"LogisticRegressionModel(uid={self.uid}, "
                f"numClasses={self._num_classes}, numFeatures={self.num_features})")


def _lr_evaluate(model, frame: MLFrame) -> "BinaryLogisticRegressionSummary":
    """(ref LogisticRegressionModel.evaluate) — score the frame and return
    the binary metrics summary."""
    if model._is_multinomial:
        raise ValueError("evaluate() summary is binary-only "
                         "(ref BinaryLogisticRegressionSummary)")
    out = model.transform(frame)
    probs = np.asarray(out[model.get("probabilityCol")])
    scores = probs[:, 1] if probs.ndim == 2 else probs
    label_col = model.get("labelCol")
    labels = np.asarray(frame[label_col], dtype=np.float64)
    preds = np.asarray(out[model.get("predictionCol")], dtype=np.float64)
    return BinaryLogisticRegressionSummary(scores, labels, predictions=preds)


class LogisticRegressionTrainingSummary:
    """Objective history + iteration count (ref LogisticRegressionSummary /
    BinaryLogisticRegressionTrainingSummary — the optimizer trace; rich
    binary metrics come from ``model.evaluate(frame)``)."""

    def __init__(self, objective_history, total_iterations,
                 total_evals=None, total_dispatches=None, n_models=1,
                 streamed=False):
        self.objective_history = objective_history
        self.total_iterations = total_iterations
        # optimizer-path telemetry: loss/grad evaluations and host->device
        # round trips (the fused line search makes dispatches ~ iterations,
        # not ~ evals)
        self.total_evals = total_evals
        self.total_dispatches = total_dispatches
        # >1 when this model trained inside a stacked (vmapped model-axis)
        # fit: its compiles AND dispatches were shared by n_models models
        self.n_models = n_models
        # True when the fit ran on the out-of-core streaming engine —
        # explicitly (oocore.mode=force / a StreamingDataset input) or by
        # budget-guard degradation; dispatches then count SHARD dispatches
        self.streamed = streamed


class BinaryLogisticRegressionSummary:
    """Binary metrics over a scored frame (ref:
    BinaryLogisticRegressionSummary — roc/pr curves, areaUnderROC,
    threshold sweeps; computed vectorized from one sorted pass)."""

    def __init__(self, scores: np.ndarray, labels: np.ndarray,
                 predictions: Optional[np.ndarray] = None):
        if len(scores) == 0:
            raise ValueError("cannot summarize an empty frame")
        self._predictions = predictions
        from cycloneml_tpu.ml.evaluation.evaluators import binary_curve_points
        (self._thresholds, self._tps, self._fps,
         self._p, self._n) = binary_curve_points(scores, labels)
        self._total = len(labels)
        self._labels = labels
        self._scores = scores

    @property
    def roc(self) -> np.ndarray:
        """(FPR, TPR) points including the (0,0) and (1,1) endpoints."""
        fpr = np.concatenate([[0.0], self._fps / self._n, [1.0]])
        tpr = np.concatenate([[0.0], self._tps / self._p, [1.0]])
        return np.column_stack([fpr, tpr])

    @property
    def area_under_roc(self) -> float:
        r = self.roc
        return float(np.trapezoid(r[:, 1], r[:, 0]))

    areaUnderROC = area_under_roc

    @property
    def pr(self) -> np.ndarray:
        """(recall, precision) points, starting at recall 0 (ref prepends
        (0, p) with the first point's precision)."""
        recall = self._tps / self._p
        precision = self._tps / np.maximum(self._tps + self._fps, 1e-300)
        return np.column_stack([np.concatenate([[0.0], recall]),
                                np.concatenate([[precision[0]], precision])])

    def precision_by_threshold(self) -> np.ndarray:
        p = self._tps / np.maximum(self._tps + self._fps, 1e-300)
        return np.column_stack([self._thresholds, p])

    def recall_by_threshold(self) -> np.ndarray:
        return np.column_stack([self._thresholds, self._tps / self._p])

    def f_measure_by_threshold(self, beta: float = 1.0) -> np.ndarray:
        p = self._tps / np.maximum(self._tps + self._fps, 1e-300)
        r = self._tps / self._p
        b2 = beta * beta
        f = (1 + b2) * p * r / np.maximum(b2 * p + r, 1e-300)
        return np.column_stack([self._thresholds, f])

    @property
    def accuracy(self) -> float:
        # the model's own predictions (threshold-aware) when available
        pred = (self._predictions if self._predictions is not None
                else (self._scores > 0.5).astype(np.float64))
        return float((pred == self._labels).mean())
