"""Tree-based classifiers: DecisionTree, RandomForest, GBT.

Re-designs of the reference estimators (ref:
ml/classification/DecisionTreeClassifier.scala,
RandomForestClassifier.scala, GBTClassifier.scala; training engine
ml/tree/impl/RandomForest.scala:83 and GradientBoostedTrees.scala) on the
dense histogram engine in ``cycloneml_tpu.ml.tree.impl`` — one vmapped
histogram psum per tree level instead of per-partition bin seqOps merged by
reduceByKey.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Predictor, ProbabilisticClassificationModel
from cycloneml_tpu.ml.tree import (
    BinnedDataset, ForestConfig, ForestData, _DecisionTreeParams, _GBTParams,
    _RandomForestParams, grow_forest,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def _prepare(est, frame: MLFrame):
    ds = frame.to_instance_dataset(
        est.get("featuresCol"), label_col=est.get("labelCol"),
        weight_col=est.get("weightCol") or None)
    _, y, w = ds.to_numpy()
    binned = BinnedDataset.from_instance_dataset(
        ds, est.get("maxBins"), est.get("seed"))
    return binned, y, w


class _TreeClassifierModelBase(ProbabilisticClassificationModel):
    """Shared transform path: raw = ensemble probability votes."""

    _forest: ForestData
    _num_classes: int

    @property
    def num_classes(self) -> int:
        return self._num_classes

    @property
    def num_features(self) -> int:
        return self._forest.num_features

    @property
    def feature_importances(self) -> np.ndarray:
        return self._forest.feature_importances()

    @property
    def total_num_nodes(self) -> int:
        return int(self._forest.n_nodes.sum())

    def to_debug_string(self) -> str:
        return "\n\n".join(self._forest.debug_string(t)
                           for t in range(self._forest.num_trees))

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        return self._forest.predict_raw(x)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        s = np.maximum(raw.sum(axis=1, keepdims=True), 1e-300)
        return raw / s

    def _save_data(self, path: str) -> None:
        save_arrays(path, num_classes=np.array(self._num_classes),
                    **self._forest.to_arrays())

    def _load_data(self, path: str, meta) -> None:
        a = load_arrays(path)
        self._num_classes = int(a["num_classes"])
        self._forest = ForestData.from_arrays(a)


# ---------------------------------------------------------------------------
# DecisionTreeClassifier
# ---------------------------------------------------------------------------

class DecisionTreeClassifier(Predictor, _DecisionTreeParams, MLWritable, MLReadable):
    """ref: ml/classification/DecisionTreeClassifier.scala:45."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_tree_params(["gini", "entropy"], "gini")
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "DecisionTreeClassificationModel":
        binned, y, w = _prepare(self, frame)
        k = int(y.max()) + 1 if len(y) else 2
        cfg = ForestConfig(
            task="classification", num_classes=max(k, 2),
            impurity=self.get("impurity"), max_depth=self.get("maxDepth"),
            min_instances_per_node=self.get("minInstancesPerNode"),
            min_weight_fraction_per_node=self.get("minWeightFractionPerNode"),
            min_info_gain=self.get("minInfoGain"), num_trees=1,
            feature_subset_strategy="all", subsampling_rate=1.0,
            bootstrap=False, seed=self.get("seed"))
        forest = grow_forest(binned, y, w, cfg)
        m = DecisionTreeClassificationModel(forest, max(k, 2))
        self._copy_values(m)
        return m


class DecisionTreeClassificationModel(_TreeClassifierModelBase,
                                      _DecisionTreeParams, MLWritable, MLReadable):
    def __init__(self, forest: Optional[ForestData] = None,
                 num_classes: int = 2, uid=None):
        super().__init__(uid)
        self._declare_tree_params(["gini", "entropy"], "gini")
        self._forest = forest
        self._num_classes = num_classes

    @property
    def depth(self) -> int:
        return self._forest.tree_depth(0)

    @property
    def num_nodes(self) -> int:
        return int(self._forest.n_nodes[0])


# ---------------------------------------------------------------------------
# RandomForestClassifier
# ---------------------------------------------------------------------------

class RandomForestClassifier(Predictor, _RandomForestParams, MLWritable, MLReadable):
    """ref: ml/classification/RandomForestClassifier.scala:48."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_tree_params(["gini", "entropy"], "gini")
        self._declare_rf_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "RandomForestClassificationModel":
        binned, y, w = _prepare(self, frame)
        k = int(y.max()) + 1 if len(y) else 2
        cfg = ForestConfig(
            task="classification", num_classes=max(k, 2),
            impurity=self.get("impurity"), max_depth=self.get("maxDepth"),
            min_instances_per_node=self.get("minInstancesPerNode"),
            min_weight_fraction_per_node=self.get("minWeightFractionPerNode"),
            min_info_gain=self.get("minInfoGain"),
            num_trees=self.get("numTrees"),
            feature_subset_strategy=self.get("featureSubsetStrategy"),
            subsampling_rate=self.get("subsamplingRate"),
            bootstrap=self.get("bootstrap"), seed=self.get("seed"))
        forest = grow_forest(binned, y, w, cfg)
        m = RandomForestClassificationModel(forest, max(k, 2))
        self._copy_values(m)
        return m


class RandomForestClassificationModel(_TreeClassifierModelBase,
                                      _RandomForestParams, MLWritable, MLReadable):
    def __init__(self, forest: Optional[ForestData] = None,
                 num_classes: int = 2, uid=None):
        super().__init__(uid)
        self._declare_tree_params(["gini", "entropy"], "gini")
        self._declare_rf_params()
        self._forest = forest
        self._num_classes = num_classes

    @property
    def num_trees(self) -> int:
        return self._forest.num_trees


# ---------------------------------------------------------------------------
# GBTClassifier
# ---------------------------------------------------------------------------

class GBTClassifier(Predictor, _GBTParams, MLWritable, MLReadable):
    """Gradient-boosted trees for binary classification
    (ref: ml/classification/GBTClassifier.scala:58; boosting loop
    mllib/tree/GradientBoostedTrees via ml/tree/impl/GradientBoostedTrees
    .scala — LogLoss: L = 2·log(1+exp(-2yF)), negative gradient
    4y/(1+exp(2yF)), first tree weight 1.0 then stepSize)."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._declare_gbt_params(["logistic"], "logistic")
        for k, v in kwargs.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "GBTClassificationModel":
        binned, y, w = _prepare(self, frame)
        y_pm = 2.0 * y - 1.0                       # {0,1} → {-1,+1}
        forests, weights = _boost(
            self, binned, w,
            first_target=y_pm,
            neg_gradient=lambda f: 4.0 * y_pm / (1.0 + np.exp(2.0 * y_pm * f)))
        m = GBTClassificationModel(forests, np.array(weights))
        self._copy_values(m)
        return m


def _boost(est, binned: BinnedDataset, w: np.ndarray, first_target: np.ndarray,
           neg_gradient) -> tuple:
    """Shared boosting loop; each round fits a variance-impurity regression
    tree to the pseudo-residual (ref GradientBoostedTrees.boost)."""
    step = est.get("stepSize")
    base_cfg = dict(
        task="regression", impurity="variance",
        max_depth=est.get("maxDepth"),
        min_instances_per_node=est.get("minInstancesPerNode"),
        min_weight_fraction_per_node=est.get("minWeightFractionPerNode"),
        min_info_gain=est.get("minInfoGain"), num_trees=1,
        feature_subset_strategy=est.get("featureSubsetStrategy"),
        subsampling_rate=est.get("subsamplingRate"), bootstrap=False)

    x_for_pred = None
    forests, weights = [], []
    f_pred = np.zeros_like(first_target)
    target = first_target
    for it in range(max(est.get("maxIter"), 1)):
        cfg = ForestConfig(seed=est.get("seed") + it, **base_cfg)
        tree = grow_forest(binned, target, w, cfg)
        tw = 1.0 if it == 0 else step
        forests.append(tree)
        weights.append(tw)
        if it == max(est.get("maxIter"), 1) - 1:
            break
        if x_for_pred is None:
            # one host copy of the raw features for residual updates
            x_for_pred = _unbin(binned)
        f_pred = f_pred + tw * tree.predict_raw(x_for_pred)[:, 0]
        target = neg_gradient(f_pred)
    return forests, weights


def _unbin(binned: BinnedDataset) -> np.ndarray:
    """Representative raw value per bin so tree thresholds (raw-space)
    evaluate identically to bin comparisons: use threshold midpoint proxies.
    Simpler and exact: reconstruct from bins via thresholds — value in bin b
    of feature f satisfies th[b-1] < v <= th[b]; any v in that interval gives
    the same path, so use th[b] (and th[last]+1 for the top bin)."""
    bins = np.asarray(binned.bins)[binned.valid_idx]
    d = binned.n_features
    out = np.empty(bins.shape, dtype=np.float64)
    for f in range(d):
        nb = int(binned.n_bins[f])
        th = binned.thresholds[f, :max(nb - 1, 0)]
        reps = np.concatenate([th, [th[-1] + 1.0 if nb > 1 else 0.0]])
        out[:, f] = reps[np.clip(bins[:, f], 0, nb - 1)]
    return out


class GBTClassificationModel(ProbabilisticClassificationModel, _GBTParams,
                             MLWritable, MLReadable):
    """Prediction = Σ wᵢ·treeᵢ(x); raw = (-F, F), probability via the
    logistic loss link (ref GBTClassificationModel.predictRaw/
    raw2probabilityInPlace: p₁ = 1/(1+exp(-2F)))."""

    def __init__(self, forests=None, tree_weights: Optional[np.ndarray] = None,
                 uid=None):
        super().__init__(uid)
        self._declare_tree_params(["variance"], "variance")
        self._declare_gbt_params(["logistic"], "logistic")
        self._forests = forests or []
        self._tree_weights = (np.asarray(tree_weights)
                              if tree_weights is not None else np.zeros(0))

    @property
    def num_trees(self) -> int:
        return len(self._forests)

    @property
    def tree_weights(self) -> np.ndarray:
        return self._tree_weights

    @property
    def num_features(self) -> int:
        return self._forests[0].num_features

    @property
    def num_classes(self) -> int:
        return 2

    @property
    def feature_importances(self) -> np.ndarray:
        imp = np.zeros(self.num_features)
        for fo in self._forests:
            imp += fo.feature_importances()
        s = imp.sum()
        return imp / s if s > 0 else imp

    def _margin(self, x: np.ndarray) -> np.ndarray:
        f = np.zeros(x.shape[0])
        for fo, tw in zip(self._forests, self._tree_weights):
            f += tw * fo.predict_raw(x)[:, 0]
        return f

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        m = self._margin(np.asarray(x, dtype=np.float64))
        return np.stack([-m, m], axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        p1 = 1.0 / (1.0 + np.exp(-2.0 * raw[:, 1]))
        return np.stack([1.0 - p1, p1], axis=1)

    def _raw_to_prediction(self, raw: np.ndarray) -> np.ndarray:
        return (raw[:, 1] > 0).astype(np.float64)

    def _save_data(self, path: str) -> None:
        arrs = {"gbt_weights": self._tree_weights,
                "gbt_n": np.array(len(self._forests))}
        for i, fo in enumerate(self._forests):
            arrs.update({f"t{i}_{k}": v for k, v in fo.to_arrays().items()})
        save_arrays(path, **arrs)

    def _load_data(self, path: str, meta) -> None:
        a = load_arrays(path)
        self._tree_weights = a["gbt_weights"]
        n = int(a["gbt_n"])
        self._forests = [
            ForestData.from_arrays(
                {k[len(f"t{i}_"):]: v for k, v in a.items()
                 if k.startswith(f"t{i}_")})
            for i in range(n)]
