"""Factorization-machine classifier (ref: ml/classification/FMClassifier.scala
— logistic loss over the shared FM trainImpl)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.matrices import DenseMatrix
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import Predictor, ProbabilisticClassificationModel
from cycloneml_tpu.ml.optim.fm_core import fm_margin_np, split_fm_coef, train_fm
from cycloneml_tpu.ml.optim.loss import validate_binary_labels
from cycloneml_tpu.ml.regression.fm import _FMParams
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class FMClassifier(Predictor, _FMParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_fm_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_factor_size(self, v):
        return self.set("factorSize", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_step_size(self, v):
        return self.set("stepSize", v)

    def _fit(self, frame: MLFrame) -> "FMClassificationModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), self.get("labelCol"), None)
        validate_binary_labels(ds.unpad(np.asarray(ds.y)), "FMClassifier")
        d = ds.n_features
        coef, history = train_fm(
            ds, d, "logistic", self.get("factorSize"),
            self.get("fitIntercept"), self.get("fitLinear"),
            self.get("regParam"), self.get("miniBatchFraction"),
            self.get("initStd"), self.get("maxIter"), self.get("stepSize"),
            self.get("tol"), self.get("solver"), self.get("seed"))
        V_, w, b = split_fm_coef(coef, d, self.get("factorSize"),
                                 self.get("fitIntercept"),
                                 self.get("fitLinear"))
        model = FMClassificationModel(V_, w, b, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.objective_history = history
        return model


class FMClassificationModel(ProbabilisticClassificationModel, _FMParams,
                            MLWritable, MLReadable):
    def __init__(self, factors: Optional[np.ndarray] = None,
                 linear: Optional[np.ndarray] = None,
                 intercept: float = 0.0, uid=None):
        super().__init__(uid)
        self._declare_fm_params()
        self._V = np.asarray(factors) if factors is not None else None
        self._w = np.asarray(linear) if linear is not None else None
        self._b = float(intercept)
        self.objective_history = []

    @property
    def factors(self) -> DenseMatrix:
        return DenseMatrix.from_array(self._V)

    @property
    def linear(self) -> DenseVector:
        return Vectors.dense(self._w)

    @property
    def intercept(self) -> float:
        return self._b

    @property
    def num_classes(self) -> int:
        return 2

    @property
    def num_features(self) -> int:
        return self._V.shape[0]

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        m = fm_margin_np(x, self._V, self._w, self._b)
        return np.stack([-m, m], axis=1)

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        p = 1.0 / (1.0 + np.exp(-raw[:, 1]))
        return np.stack([1.0 - p, p], axis=1)

    def _save_data(self, path: str) -> None:
        save_arrays(path, V=self._V, w=self._w, b=np.array(self._b))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._V, self._w, self._b = arrs["V"], arrs["w"], float(arrs["b"])
