from cycloneml_tpu.ml.classification.logistic_regression import (
    LogisticRegression, LogisticRegressionModel,
)

__all__ = ["LogisticRegression", "LogisticRegressionModel"]
