from cycloneml_tpu.ml.classification.logistic_regression import (
    LogisticRegression, LogisticRegressionModel,
)
from cycloneml_tpu.ml.classification.linear_svc import LinearSVC, LinearSVCModel
from cycloneml_tpu.ml.classification.naive_bayes import NaiveBayes, NaiveBayesModel
from cycloneml_tpu.ml.classification.fm import (
    FMClassificationModel, FMClassifier,
)
from cycloneml_tpu.ml.classification.mlp import (
    MultilayerPerceptronClassificationModel, MultilayerPerceptronClassifier,
)
from cycloneml_tpu.ml.classification.one_vs_rest import OneVsRest, OneVsRestModel
from cycloneml_tpu.ml.classification.trees import (
    DecisionTreeClassificationModel, DecisionTreeClassifier,
    GBTClassificationModel, GBTClassifier,
    RandomForestClassificationModel, RandomForestClassifier,
)

__all__ = [
    "LogisticRegression", "LogisticRegressionModel",
    "LinearSVC", "LinearSVCModel",
    "NaiveBayes", "NaiveBayesModel",
    "FMClassifier", "FMClassificationModel",
    "MultilayerPerceptronClassifier", "MultilayerPerceptronClassificationModel",
    "OneVsRest", "OneVsRestModel",
    "DecisionTreeClassifier", "DecisionTreeClassificationModel",
    "RandomForestClassifier", "RandomForestClassificationModel",
    "GBTClassifier", "GBTClassificationModel",
]
