from cycloneml_tpu.ml.classification.logistic_regression import (
    LogisticRegression, LogisticRegressionModel,
)
from cycloneml_tpu.ml.classification.trees import (
    DecisionTreeClassificationModel, DecisionTreeClassifier,
    GBTClassificationModel, GBTClassifier,
    RandomForestClassificationModel, RandomForestClassifier,
)

__all__ = [
    "LogisticRegression", "LogisticRegressionModel",
    "DecisionTreeClassifier", "DecisionTreeClassificationModel",
    "RandomForestClassifier", "RandomForestClassificationModel",
    "GBTClassifier", "GBTClassificationModel",
]
