"""Bisecting k-means (divisive hierarchical clustering).

Re-design of the reference (ref: mllib/clustering/BisectingKMeans.scala —
level-by-level bisection of divisible clusters, binary-tree node indexing
root=1/children 2i,2i+1, ClusteringTreeNode predict-by-descent; ml wrapper
ml/clustering/BisectingKMeans.scala delegates). TPU-first formulation:

- the per-row cluster assignment lives as a sharded device array alongside X;
  a level's splits ALL train together: child centers stacked (m, 2, d), each
  row competes only between its own node's two children via a node→slot
  lookup table, distances + center sums are two MXU matmuls psum'd over the
  mesh — the reference's per-cluster ``summarize`` aggregation collapsed into
  one SPMD program per inner iteration.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.clustering._util import normalize_rows, pairwise_sq_dists
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import (
    HasFeaturesCol, HasMaxIter, HasPredictionCol, HasSeed, HasWeightCol,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _BKMParams(HasFeaturesCol, HasPredictionCol, HasMaxIter, HasSeed,
                 HasWeightCol):
    def _declare_bkm_params(self):
        self._p_features_col()
        self._p_prediction_col()
        self._p_max_iter(20)
        self._p_seed(17)
        self._p_weight_col()
        self.k = self._param("k", "desired number of leaf clusters (> 1)",
                             V.gt(1), default=4)
        self.minDivisibleClusterSize = self._param(
            "minDivisibleClusterSize",
            "min points (>=1) or fraction (<1) for a divisible cluster",
            V.gt(0.0), default=1.0)
        self.distanceMeasure = self._param(
            "distanceMeasure", "euclidean or cosine",
            V.in_array(["euclidean", "cosine"]), default="euclidean")


class BisectingKMeans(Estimator, _BKMParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_bkm_params()
        for key, v in kwargs.items():
            self.set(key, v)

    def set_k(self, v):
        return self.set("k", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_seed(self, v):
        return self.set("seed", v)

    def _fit(self, frame: MLFrame) -> "BisectingKMeansModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), label_col=None,
            weight_col=self.get("weightCol") or None)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "BisectingKMeansModel":
        import jax
        import jax.numpy as jnp

        k = self.get("k")
        cosine = self.get("distanceMeasure") == "cosine"
        rng = np.random.RandomState(self.get("seed"))
        dtype = ds.w.dtype  # accumulator tier: X may store bf16
        hi = jax.lax.Precision.HIGHEST

        if cosine:
            norm = jax.jit(lambda x: normalize_rows(jnp, x))
            ds = InstanceDataset(ds.ctx, norm(ds.x), ds.y, ds.w,
                                 ds.n_rows, ds.n_features)

        # assignment = binary-tree node index per row (ref node indexing);
        # starts at root=1, sharded like x
        assign = jnp.ones_like(ds.y, dtype=jnp.int32)

        # root stats: weighted mean, row count, and cost about the mean
        def root_stats(x, y, w, center):
            s = jnp.dot(w[None, :], x, precision=hi)[0]
            real = (w > 0).astype(w.dtype)
            d2 = jnp.sum((x - center[None, :]) ** 2, axis=1)
            return {"sum": s, "wsum": jnp.sum(w), "count": jnp.sum(real),
                    "cost": jnp.sum(w * d2)}

        root_agg = ds.tree_aggregate_fn(root_stats)
        # one transfer for all root stats, not one per field (graftlint JX001)
        out = jax.device_get(root_agg(jnp.zeros(ds.n_features, dtype)))
        total_n = float(out["count"])
        root_center = np.asarray(out["sum"], np.float64) / max(
            float(out["wsum"]), 1e-300)
        if cosine:
            root_center /= max(np.linalg.norm(root_center), 1e-12)
        root_cost = float(root_agg(jnp.asarray(root_center, dtype))["cost"])

        # divisibility gates on POINT COUNT like the reference (a cluster of
        # fractional-weight rows is still divisible), plus a nonzero-cost
        # check (ref BisectingKMeans.divisibleLeaves: cost > EPSILON * size)
        min_size = self.get("minDivisibleClusterSize")
        min_n = min_size if min_size >= 1.0 else min_size * total_n

        nodes: Dict[int, np.ndarray] = {1: root_center}
        sizes: Dict[int, float] = {1: total_n}
        costs: Dict[int, float] = {1: root_cost}
        leaves = {1}

        def level_step(x, y, w, assigned, slot_of, child_centers):
            # slot_of: (max_node+1,) node index -> split slot (or -1)
            slot = slot_of[assigned]                               # (b,)
            active = slot >= 0
            cc = child_centers.reshape(-1, x.shape[1])             # (2m, d)
            d2 = pairwise_sq_dists(jnp, x, cc, precision=hi)       # (b, 2m)
            sl = jnp.maximum(slot, 0)
            d_left = jnp.take_along_axis(d2, (2 * sl)[:, None], axis=1)[:, 0]
            d_right = jnp.take_along_axis(d2, (2 * sl + 1)[:, None], axis=1)[:, 0]
            side = (d_right < d_left).astype(jnp.int32)            # 0/1
            cidx = jnp.where(active, 2 * sl + side, 0)
            wm = w * active.astype(w.dtype)
            onehot = jax.nn.one_hot(cidx, cc.shape[0], dtype=w.dtype)
            onehot_w = onehot * wm[:, None]
            real = jnp.logical_and(active, w > 0).astype(w.dtype)
            sums = jnp.dot(onehot_w.T, x, precision=hi)            # (2m, d)
            wsums = jnp.sum(onehot_w, axis=0)
            counts = jnp.sum(onehot * real[:, None], axis=0)       # row counts
            mind = jnp.maximum(jnp.minimum(d_left, d_right), 0.0)
            child_cost = jnp.dot(onehot_w.T, mind, precision=hi)   # (2m,)
            new_assign = jnp.where(active, 2 * assigned + side, assigned)
            return {"sums": sums, "wsums": wsums, "counts": counts,
                    "child_cost": child_cost}, new_assign

        # compiled once per (m, table-size) shape; cache across levels
        agg_cache = {}

        while len(leaves) < k:
            divisible = sorted(
                [n for n in leaves
                 if sizes[n] >= min_n and sizes[n] > 1
                 and costs[n] > 1e-12 * sizes[n]],
                key=lambda n: -sizes[n])
            if not divisible:
                break
            m = min(len(divisible), k - len(leaves))
            splitting = divisible[:m]
            # table must cover EVERY live node index: jnp clamps
            # out-of-bounds gathers, which would alias non-splitting leaves
            # into the last slot
            max_node = max(leaves)
            slot_of = np.full(max_node + 1, -1, np.int32)
            for s, node in enumerate(splitting):
                slot_of[node] = s
            # init children by ± perturbation of parent (ref splitCenter)
            child = np.empty((m, 2, ds.n_features))
            for s, node in enumerate(splitting):
                c = nodes[node]
                level = max(1e-4 * np.linalg.norm(c), 1e-4)
                noise = rng.rand(ds.n_features)
                child[s, 0] = c - level * noise
                child[s, 1] = c + level * noise

            key = (m, max_node + 1)
            if key not in agg_cache:
                agg_cache[key] = _compile_level(ds, level_step)
            run = agg_cache[key]

            new_assign = None
            for _ in range(max(1, self.get("maxIter"))):
                stats, new_assign = run(
                    assign, jnp.asarray(slot_of),
                    jnp.asarray(child, dtype=dtype))
                wsums = np.asarray(stats["wsums"], np.float64)
                sums = np.asarray(stats["sums"], np.float64)
                flat = child.reshape(-1, ds.n_features)
                moved_child = np.where(wsums[:, None] > 0,
                                       sums / np.maximum(wsums[:, None], 1e-300),
                                       flat)
                if cosine:
                    moved_child = moved_child / np.maximum(
                        np.linalg.norm(moved_child, axis=1, keepdims=True), 1e-12)
                moved = np.linalg.norm(moved_child - flat, axis=1).max()
                child = moved_child.reshape(m, 2, ds.n_features)
                if moved < 1e-6:
                    break
            assign = new_assign
            counts = np.asarray(stats["counts"], np.float64)
            child_cost = np.asarray(stats["child_cost"], np.float64)
            for s, node in enumerate(splitting):
                leaves.discard(node)
                for side in (0, 1):
                    ci = 2 * node + side
                    nodes[ci] = child[s, side]
                    sizes[ci] = counts[2 * s + side]
                    costs[ci] = child_cost[2 * s + side]
                    leaves.add(ci)

        leaf_idx = sorted(leaves)
        centers = np.stack([nodes[i] for i in leaf_idx])
        model = BisectingKMeansModel(
            centers,
            node_index=np.asarray(leaf_idx, np.int64),
            tree_nodes=nodes, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model


def _compile_level(ds: InstanceDataset, level_step):
    """Compile the level program: stats psum'd, assignment stays sharded."""
    import jax
    from cycloneml_tpu.parallel import collectives

    rt = ds.ctx.mesh_runtime

    def fn(x, y, w, assigned, slot_of, child_centers):
        return level_step(x, y, w, assigned, slot_of, child_centers)

    # 4 row-sharded leading args (x, y, w, assign); ds.y stands in for the
    # assign slot only to declare its sharding
    compiled = collectives.tree_aggregate_with_state(fn, rt,
                                                     ds.x, ds.y, ds.w, ds.y)

    def run(assign, slot_of, child):
        return compiled(ds.x, ds.y, ds.w, assign, slot_of, child)

    return run


class BisectingKMeansModel(Model, _BKMParams, MLWritable, MLReadable):
    """Prediction descends the tree root→leaf choosing the nearer child
    (ref ClusteringTreeNode.predict)."""

    def __init__(self, centers: Optional[np.ndarray] = None,
                 node_index: Optional[np.ndarray] = None,
                 tree_nodes: Optional[Dict[int, np.ndarray]] = None, uid=None):
        super().__init__(uid)
        self._declare_bkm_params()
        self._centers = np.asarray(centers) if centers is not None else None
        self._node_index = (np.asarray(node_index)
                            if node_index is not None else None)
        self._tree = dict(tree_nodes) if tree_nodes else None

    @property
    def cluster_centers(self):
        return [row for row in self._centers]

    def _assign(self, x: np.ndarray) -> np.ndarray:
        if x.ndim == 1:
            x = x[:, None]
        if self.get("distanceMeasure") == "cosine":
            x = normalize_rows(np, x)
        leaf_set = set(int(i) for i in self._node_index)
        if self._tree:
            out = np.empty(x.shape[0])
            leaf_pos = {int(n): i for i, n in enumerate(self._node_index)}
            for r in range(x.shape[0]):
                node = 1
                while node not in leaf_set:
                    left, right = self._tree.get(2 * node), self._tree.get(2 * node + 1)
                    if left is None or right is None:
                        break
                    dl = np.sum((x[r] - left) ** 2)
                    dr = np.sum((x[r] - right) ** 2)
                    node = 2 * node + (1 if dr < dl else 0)
                out[r] = leaf_pos.get(node, 0)
            return out.astype(np.float64)
        d2 = pairwise_sq_dists(np, x, self._centers)
        return d2.argmin(1).astype(np.float64)

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = frame[self.get("featuresCol")]
        return frame.with_column(self.get("predictionCol"), self._assign(x))

    def predict(self, features) -> int:
        arr = features.to_array() if hasattr(features, "to_array") else np.asarray(features)
        return int(self._assign(arr[None, :])[0])

    def compute_cost(self, frame: MLFrame) -> float:
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        assign = self._assign(x).astype(int)
        if self.get("distanceMeasure") == "cosine":
            # cosine distance 1 - cos(x, c), not squared-euclidean on the
            # normalized vectors (which would double it)
            xn = normalize_rows(np, x)
            cn = normalize_rows(np, self._centers[assign])
            return float(np.sum(1.0 - np.sum(xn * cn, axis=1)))
        return float(np.sum((x - self._centers[assign]) ** 2))

    def _save_data(self, path: str) -> None:
        tree_idx = np.asarray(sorted(self._tree), np.int64) if self._tree else np.zeros(0, np.int64)
        tree_centers = (np.stack([self._tree[i] for i in tree_idx])
                        if len(tree_idx) else np.zeros((0, self._centers.shape[1])))
        save_arrays(path, centers=self._centers, node_index=self._node_index,
                    tree_idx=tree_idx, tree_centers=tree_centers)

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._centers = arrs["centers"]
        self._node_index = arrs["node_index"]
        self._tree = {int(i): c for i, c in
                      zip(arrs["tree_idx"], arrs["tree_centers"])}
