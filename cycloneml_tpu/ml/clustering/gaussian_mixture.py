"""Gaussian mixture model via distributed EM.

Re-design of the reference (ref: ml/clustering/GaussianMixture.scala:
per-partition aggregation of responsibility-weighted sufficient stats with a
``treeAggregate``-style reduce; mllib/clustering/GaussianMixture.scala:43
runs the same EM over RDD[Vector]). TPU-first formulation:

- E-step: all k component log-densities for a row block as ONE batched
  triangular solve + matmul against the stacked Cholesky factors — an
  (n, k) MXU program, not the reference's per-row MultivariateGaussian.pdf.
- M-step sufficient stats (resp sums, resp-weighted mean sums, resp-weighted
  scatter matrices x xᵀ) accumulate per shard and merge with one
  hierarchical psum — this IS the reference's treeAggregate.
- driver updates weights/means/covs (tiny, O(k d²)) and checks the
  log-likelihood delta against tol, exactly the reference loop.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import (
    HasFeaturesCol, HasMaxIter, HasPredictionCol, HasProbabilityCol, HasSeed,
    HasTol, HasWeightCol,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_MIN_COV_EIG = 1e-6  # diagonal jitter keeping Cholesky factorizable


class MultivariateGaussian(NamedTuple):
    """Parity with ref stat/distribution/MultivariateGaussian.scala."""
    mean: np.ndarray
    cov: np.ndarray


class _GMMParams(HasFeaturesCol, HasPredictionCol, HasProbabilityCol,
                 HasMaxIter, HasSeed, HasTol, HasWeightCol):
    def _declare_gmm_params(self):
        self._p_features_col()
        self._p_prediction_col()
        self._p_probability_col()
        self._p_max_iter(100)
        self._p_seed(17)
        self._p_tol(0.01)
        self._p_weight_col()
        self.k = self._param("k", "number of mixture components (> 1)",
                             V.gt(1), default=2)


class GaussianMixture(Estimator, _GMMParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_gmm_params()
        for key, v in kwargs.items():
            self.set(key, v)

    def set_k(self, v):
        return self.set("k", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_seed(self, v):
        return self.set("seed", v)

    def set_tol(self, v):
        return self.set("tol", v)

    def _fit(self, frame: MLFrame) -> "GaussianMixtureModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), label_col=None,
            weight_col=self.get("weightCol") or None)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "GaussianMixtureModel":
        import jax
        import jax.numpy as jnp

        k, d = self.get("k"), ds.n_features
        dtype = ds.w.dtype  # accumulator tier: X may store bf16

        weights, means, covs = self._init_params(ds, k)

        def em_stats(x, y, w, wts, mus, chols):
            # log N(x | mu_j, Sigma_j) for all j via solves against the
            # stacked Cholesky factors: z_j = L_j^{-1} (x - mu_j)
            diff = x[:, None, :] - mus[None, :, :]                  # (b,k,d)
            z = jax.vmap(
                lambda L, dv: jax.scipy.linalg.solve_triangular(
                    L, dv.T, lower=True).T,
                in_axes=(0, 1), out_axes=1)(chols, diff)            # (b,k,d)
            maha = jnp.sum(z * z, axis=2)                           # (b,k)
            logdet = jnp.sum(jnp.log(
                jax.vmap(jnp.diag)(chols)), axis=1)                 # (k,)
            logpdf = (-0.5 * (maha + d * jnp.log(2.0 * jnp.pi))
                      - logdet[None, :])
            logw = jnp.log(jnp.maximum(wts, 1e-300))
            joint = logpdf + logw[None, :]                          # (b,k)
            lse = jax.scipy.special.logsumexp(joint, axis=1)        # (b,)
            resp = jnp.exp(joint - lse[:, None]) * w[:, None]       # (b,k)
            # padding rows (w=0) contribute nothing
            return {
                "loglik": jnp.sum(jnp.where(w > 0, lse * w, 0.0)),
                "resp_sum": jnp.sum(resp, axis=0),                  # (k,)
                "mean_sum": jnp.dot(resp.T, x,
                                    precision=jax.lax.Precision.HIGHEST),
                # scatter: sum_i r_ij x_i x_iᵀ  — one gemm per component
                "scatter": jnp.einsum(
                    "bk,bi,bj->kij", resp, x, x,
                    precision=jax.lax.Precision.HIGHEST),
            }

        step = ds.tree_aggregate_fn(em_stats)
        prev_ll = -np.inf
        ll = -np.inf
        it = 0
        for it in range(1, self.get("maxIter") + 1):
            chols = np.linalg.cholesky(covs + _MIN_COV_EIG * np.eye(d))
            # one transfer for the whole EM stat pytree (graftlint JX001)
            out = jax.device_get(step(weights.astype(dtype),
                                      means.astype(dtype),
                                      chols.astype(dtype)))
            rs = np.asarray(out["resp_sum"], dtype=np.float64)
            ms = np.asarray(out["mean_sum"], dtype=np.float64)
            sc = np.asarray(out["scatter"], dtype=np.float64)
            ll = float(out["loglik"])
            total = rs.sum()
            weights = rs / max(total, 1e-300)
            means = ms / np.maximum(rs[:, None], 1e-300)
            covs = (sc / np.maximum(rs[:, None, None], 1e-300)
                    - means[:, :, None] * means[:, None, :])
            covs = 0.5 * (covs + np.transpose(covs, (0, 2, 1)))
            if abs(ll - prev_ll) < self.get("tol") and it > 1:
                prev_ll = ll
                break
            prev_ll = ll

        model = GaussianMixtureModel(weights, means, covs, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.num_iterations = it
        model.log_likelihood = ll
        return model

    def _init_params(self, ds: InstanceDataset, k: int):
        """Reference init (mllib GaussianMixture.initialize): sample rows,
        split into k slices, empirical mean/cov per slice. Only the sampled
        rows leave the device (gather of ~max(2k,100) indices); the global
        variance fallback comes from a one-pass moment aggregation."""
        import jax.numpy as jnp

        rng = np.random.RandomState(self.get("seed"))
        n, d = ds.n_rows, ds.n_features
        n_sample = min(n, max(2 * k, 100))
        idx = np.sort(rng.choice(n, size=n_sample, replace=False))
        # padding lives past row n_rows, so real-row gathers are safe
        sample = np.array(ds.x[jnp.asarray(idx)], dtype=np.float64)  # writable copy
        rng.shuffle(sample)
        slices = np.array_split(sample, k)

        if all(len(s) > 1 for s in slices):
            # normal case (n_sample >= 2k): no global pass needed
            mean_all = var0 = None
        else:
            # degenerate slices fall back to global moments (one-pass)
            def moments(x, y, w, _z):
                real = (w > 0).astype(w.dtype)
                return {"s1": jnp.sum(x * real[:, None], axis=0),
                        "s2": jnp.sum(x * x * real[:, None], axis=0),
                        "n": jnp.sum(real)}

            mo = ds.tree_aggregate_fn(moments)(jnp.zeros((), ds.w.dtype))
            cnt = max(float(mo["n"]), 1.0)
            mean_all = np.asarray(mo["s1"], np.float64) / cnt
            var0 = np.maximum(np.asarray(mo["s2"], np.float64) / cnt
                              - mean_all ** 2, 0.0) + _MIN_COV_EIG
        means = np.stack([s.mean(axis=0) if len(s) else mean_all
                          for s in slices])
        covs = np.stack([
            np.diag(s.var(axis=0) + _MIN_COV_EIG) if len(s) > 1 else np.diag(var0)
            for s in slices])
        weights = np.full(k, 1.0 / k)
        return weights, means, covs


class GaussianMixtureModel(Model, _GMMParams, MLWritable, MLReadable):
    def __init__(self, weights: Optional[np.ndarray] = None,
                 means: Optional[np.ndarray] = None,
                 covs: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._declare_gmm_params()
        self.weights = np.asarray(weights) if weights is not None else None
        self._means = np.asarray(means) if means is not None else None
        self._covs = np.asarray(covs) if covs is not None else None
        self.num_iterations = 0
        self.log_likelihood = float("nan")

    @property
    def gaussians(self) -> List[MultivariateGaussian]:
        return [MultivariateGaussian(m, c)
                for m, c in zip(self._means, self._covs)]

    def _log_resp(self, x: np.ndarray) -> np.ndarray:
        d = x.shape[1]
        k = len(self.weights)
        from scipy.linalg import solve_triangular

        out = np.empty((x.shape[0], k))
        for j in range(k):
            L = np.linalg.cholesky(self._covs[j] + _MIN_COV_EIG * np.eye(d))
            z = solve_triangular(L, (x - self._means[j]).T, lower=True)
            out[:, j] = (-0.5 * (np.sum(z * z, axis=0) + d * np.log(2 * np.pi))
                         - np.log(np.diag(L)).sum()
                         + np.log(max(self.weights[j], 1e-300)))
        return out

    def _probability(self, x: np.ndarray) -> np.ndarray:
        lr = self._log_resp(x)
        lse = np.logaddexp.reduce(lr, axis=1)
        return np.exp(lr - lse[:, None])

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        if x.ndim == 1:
            x = x[:, None]
        prob = self._probability(x)
        out = frame
        if self.get("probabilityCol"):
            out = out.with_column(self.get("probabilityCol"), prob)
        out = out.with_column(self.get("predictionCol"),
                              prob.argmax(1).astype(np.float64))
        return out

    def predict(self, features) -> int:
        arr = features.to_array() if hasattr(features, "to_array") else np.asarray(features)
        return int(self._probability(np.atleast_2d(arr)).argmax(1)[0])

    def predict_probability(self, features) -> np.ndarray:
        arr = features.to_array() if hasattr(features, "to_array") else np.asarray(features)
        return self._probability(np.atleast_2d(arr))[0]

    def _save_data(self, path: str) -> None:
        save_arrays(path, weights=self.weights, means=self._means,
                    covs=self._covs)

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self.weights = arrs["weights"]
        self._means = arrs["means"]
        self._covs = arrs["covs"]
