from cycloneml_tpu.ml.clustering.kmeans import KMeans, KMeansModel

__all__ = ["KMeans", "KMeansModel"]
