from cycloneml_tpu.ml.clustering.kmeans import KMeans, KMeansModel
from cycloneml_tpu.ml.clustering.gaussian_mixture import (
    GaussianMixture, GaussianMixtureModel, MultivariateGaussian,
)
from cycloneml_tpu.ml.clustering.bisecting_kmeans import (
    BisectingKMeans, BisectingKMeansModel,
)
from cycloneml_tpu.ml.clustering.power_iteration import PowerIterationClustering
from cycloneml_tpu.ml.clustering.lda import LDA, LDAModel

__all__ = [
    "KMeans", "KMeansModel",
    "GaussianMixture", "GaussianMixtureModel", "MultivariateGaussian",
    "BisectingKMeans", "BisectingKMeansModel",
    "PowerIterationClustering",
    "LDA", "LDAModel",
]
