"""K-means clustering.

Re-design of the reference (ref: mllib/clustering/KMeans.scala:41 — Lloyd's
with per-partition center sums then collectAsMap :240-311; the ml wrapper
delegates to it, ml/clustering/KMeans.scala:336; DistanceMeasure.scala:28
with euclidean/cosine). TPU-first formulation:

- distances: ‖x‖² + ‖c‖² − 2x·cᵀ as ONE (n,k) MXU matmul per step — the
  reference's per-row ``findClosest`` with triangle-inequality pruning
  (DistanceMeasure.scala:123) exists to avoid flops on a CPU; the MXU makes
  the dense matmul faster than any pruning.
- center update: one-hot(assign)ᵀ @ X — a second MXU matmul — psum'd over
  the mesh; this IS the per-partition sum + global merge of the reference.
- whole Lloyd iteration = one jit-compiled SPMD program; driver only checks
  movement against tol.
- init: "random" or "k-means||" (Bahmani et al., ref KMeans.scala
  initKMeansParallel) with distributed cost pass + driver-side weighted
  k-means++ refinement, exactly the reference's scheme.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.matrices import DenseMatrix
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.clustering._util import normalize_rows, pairwise_sq_dists
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import (
    HasFeaturesCol, HasMaxIter, HasPredictionCol, HasSeed, HasTol, HasWeightCol,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _KMeansParams(HasFeaturesCol, HasPredictionCol, HasMaxIter, HasSeed,
                    HasTol, HasWeightCol):
    def _declare_kmeans_params(self):
        self._p_features_col()
        self._p_prediction_col()
        self._p_max_iter(20)
        self._p_seed(17)
        self._p_tol(1e-4)
        self._p_weight_col()
        self.k = self._param("k", "number of clusters (> 1)", V.gt(1), default=2)
        self.initMode = self._param(
            "initMode", "initialization: random or k-means||",
            V.in_array(["random", "k-means||"]), default="k-means||")
        self.initSteps = self._param("initSteps", "k-means|| steps (> 0)",
                                     V.gt(0), default=2)
        self.distanceMeasure = self._param(
            "distanceMeasure", "euclidean or cosine",
            V.in_array(["euclidean", "cosine"]), default="euclidean")


class KMeans(Estimator, _KMeansParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_kmeans_params()
        for key, v in kwargs.items():
            self.set(key, v)

    def set_k(self, v):
        return self.set("k", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_seed(self, v):
        return self.set("seed", v)

    def _fit(self, frame: MLFrame) -> "KMeansModel":
        ds = frame.to_instance_dataset(
            self.get("featuresCol"), label_col=None,
            weight_col=self.get("weightCol") or None)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "KMeansModel":
        import jax
        import jax.numpy as jnp

        k = self.get("k")
        cosine = self.get("distanceMeasure") == "cosine"
        # centers are a replicated (k, d) vector set — they ride the
        # ACCUMULATOR tier (f32/f64) even when X stores bf16; distances
        # upcast X per tile inside the kernels, never in HBM
        from cycloneml_tpu.dataset.instance import compute_dtype
        dtype = compute_dtype()

        if cosine:
            # cosine distance clusters on the unit sphere: normalize once
            norm = jax.jit(lambda x: normalize_rows(jnp, x))
            ds = ds.derive(x=norm(ds.x))

        centers = self._init_centers(ds, k)

        hi = jax.lax.Precision.HIGHEST
        from cycloneml_tpu.conf import USE_PALLAS_KERNELS
        # explicit opt-in only: the assignment kernel has no measured win
        # over XLA at any committed shape (PALLAS_AB.md), so 'auto' keeps
        # the XLA path here
        use_pallas = (hasattr(ds.ctx, "conf") and
                      str(ds.ctx.conf.get(USE_PALLAS_KERNELS)).lower()
                      == "true")

        if use_pallas:
            from cycloneml_tpu.ops.kernels import fused_kmeans_assign

            def lloyd_step(x, y, w, c):
                # fused distance+argmin kernel (the (T, k) tile never
                # leaves VMEM; bf16 X read at storage width with f32
                # distance accumulation), then segment-sum center updates —
                # w stays in its accumulator dtype so the sums do too
                best, dist = fused_kmeans_assign(x, c)
                wv = w
                sums = jax.ops.segment_sum(x * wv[:, None], best,
                                           num_segments=k)
                counts = jax.ops.segment_sum(wv, best, num_segments=k)
                cost = jnp.sum(wv * dist.astype(wv.dtype))
                return {"sums": sums, "counts": counts, "cost": cost}
        else:
            def lloyd_step(x, y, w, c):
                # (b,k) squared distances via the MXU
                d2 = pairwise_sq_dists(jnp, x, c, precision=hi)
                assign = jnp.argmin(d2, axis=1)
                onehot = jax.nn.one_hot(assign, k, dtype=w.dtype) * w[:, None]
                sums = jnp.dot(onehot.T, x, precision=hi)    # (k,d) center sums
                counts = jnp.sum(onehot, axis=0)              # (k,)
                cost = jnp.sum(w * jnp.maximum(jnp.min(d2, axis=1), 0.0))
                return {"sums": sums, "counts": counts, "cost": cost}

        step = ds.tree_aggregate_fn(lloyd_step)
        tol = self.get("tol")
        cost = float("inf")
        it = 0
        for it in range(1, self.get("maxIter") + 1):
            # one transfer per Lloyd step, not three (graftlint JX001)
            out = jax.device_get(step(centers.astype(dtype)))
            counts = np.asarray(out["counts"], dtype=np.float64)
            sums = np.asarray(out["sums"], dtype=np.float64)
            cost = float(out["cost"])
            # empty clusters keep their previous center (ref behavior)
            new_centers = np.where(counts[:, None] > 0,
                                   sums / np.maximum(counts[:, None], 1e-300),
                                   centers)
            if cosine:
                norms = np.linalg.norm(new_centers, axis=1, keepdims=True)
                new_centers = new_centers / np.maximum(norms, 1e-12)
            moved = np.linalg.norm(new_centers - centers, axis=1).max()
            centers = new_centers
            if moved < tol:
                break

        model = KMeansModel(centers, training_cost=cost, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.num_iterations = it
        return model

    # -- initialization --------------------------------------------------------
    def _init_centers(self, ds: InstanceDataset, k: int) -> np.ndarray:
        import jax
        import jax.numpy as jnp

        rng = np.random.RandomState(self.get("seed"))
        valid = ds.valid_indices()
        n = len(valid)
        if n <= k:
            x_host = ds.to_numpy()[0]  # tiny by construction
            reps = int(np.ceil(k / max(n, 1)))
            return np.tile(x_host, (reps, 1))[:k]
        if self.get("initMode") == "random":
            idx = rng.choice(valid, size=k, replace=False)
            return ds.gather_rows(idx).astype(np.float64)

        # k-means|| (Bahmani et al.; ref initKMeansParallel): start with one
        # random center; each step samples points w.p. l*d(x)/cost with l=2k,
        # distances computed on device; finish with weighted k-means++ on the
        # (small) candidate set, weights = cluster population. Sampled rows
        # are gathered from the mesh by index — X never lands on the host,
        # so initialization works at out-of-core scale (verdict r2 item 2).
        hi = jax.lax.Precision.HIGHEST

        def min_d2(x, y, w, c):
            d2 = pairwise_sq_dists(jnp, x, c, precision=hi)
            md = jnp.maximum(jnp.min(d2, axis=1), 0.0) * (w > 0)
            return md

        centers = [ds.gather_rows([valid[rng.randint(n)]])[0]]
        l_factor = 2 * k
        # candidate centers ride the accumulator tier (see _fit_dataset)
        from cycloneml_tpu.dataset.instance import compute_dtype
        dtype = np.dtype(compute_dtype())
        for _ in range(self.get("initSteps")):
            c_arr = np.asarray(centers, dtype=dtype)
            d2 = collective_row_values(ds, min_d2, c_arr)  # (n_pad,)
            total = float(d2.sum())  # padding rows contribute 0 via (w > 0)
            if total <= 0:
                break
            probs = np.minimum(l_factor * d2 / total, 1.0)
            picked = np.nonzero(rng.rand(len(d2)) < probs)[0]
            if len(picked):
                centers.extend(ds.gather_rows(picked))
        cand = np.unique(np.asarray(centers, dtype=np.float64), axis=0)
        if cand.shape[0] <= k:
            extra = ds.gather_rows(
                rng.choice(valid, size=k - cand.shape[0], replace=False))
            return np.vstack([cand, extra.astype(np.float64)])[:k]
        # weight candidates by the (weighted) points they attract, computed
        # on device via segment-sum; gated by the (shard x cand) distance
        # buffer each device must hold
        n_pad = int(ds.x.shape[0])
        if n_pad * cand.shape[0] < 5e7:
            m = cand.shape[0]

            def attract_fn(x, y, w, c):
                a = jnp.argmin(pairwise_sq_dists(jnp, x, c, precision=hi), 1)
                return jax.ops.segment_sum(w, a, num_segments=m)

            attract = np.asarray(
                ds.tree_aggregate_fn(attract_fn)(cand.astype(dtype)),
                dtype=np.float64)
            attract = np.maximum(attract, 0.0) + 1e-12
        else:
            attract = np.ones(cand.shape[0])
        return _kmeans_pp(cand, attract, k, rng)


def collective_row_values(ds: InstanceDataset, fn, *extras):
    """Evaluate a per-row fn over the sharded dataset and gather to host."""
    import jax

    @jax.jit
    def run(x, y, w, *e):
        return fn(x, y, w, *e)

    return np.asarray(run(ds.x, ds.y, ds.w, *extras))


def _kmeans_pp(points: np.ndarray, weights: np.ndarray, k: int,
               rng: np.random.RandomState) -> np.ndarray:
    """Weighted k-means++ on a small candidate set (driver-side, ref
    LocalKMeans.kMeansPlusPlus)."""
    n = points.shape[0]
    first = rng.choice(n, p=weights / weights.sum())
    chosen = [first]
    d2 = ((points - points[first]) ** 2).sum(1)
    for _ in range(1, k):
        p = weights * d2
        total = p.sum()
        if total <= 0:
            remaining = [i for i in range(n) if i not in set(chosen)]
            chosen.append(rng.choice(remaining))
        else:
            nxt = rng.choice(n, p=p / total)
            chosen.append(nxt)
            d2 = np.minimum(d2, ((points - points[nxt]) ** 2).sum(1))
    return points[chosen].astype(np.float64)


class KMeansModel(Model, _KMeansParams, MLWritable, MLReadable):
    def __init__(self, centers: Optional[np.ndarray] = None,
                 training_cost: float = 0.0, uid=None):
        super().__init__(uid)
        self._declare_kmeans_params()
        self._centers = np.asarray(centers) if centers is not None else None
        self.training_cost = training_cost
        self.num_iterations = 0

    @property
    def cluster_centers(self):
        return [row for row in self._centers]

    def cluster_centers_matrix(self) -> DenseMatrix:
        return DenseMatrix.from_array(self._centers)

    def _assign(self, x: np.ndarray) -> np.ndarray:
        if x.ndim == 1:
            x = x[:, None]
        if self.get("distanceMeasure") == "cosine":
            x = normalize_rows(np, x)
        d2 = pairwise_sq_dists(np, x, self._centers)
        return d2.argmin(1).astype(np.float64)

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = frame[self.get("featuresCol")]
        return frame.with_column(self.get("predictionCol"), self._assign(x))

    def predict(self, features) -> int:
        arr = features.to_array() if hasattr(features, "to_array") else np.asarray(features)
        return int(self._assign(arr[None, :])[0])

    def compute_cost(self, frame: MLFrame) -> float:
        """Sum of squared distances (deprecated in ref in favor of evaluator,
        kept for parity with mllib KMeansModel.computeCost)."""
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        if self.get("distanceMeasure") == "cosine":
            x = normalize_rows(np, x)
        d2 = pairwise_sq_dists(np, x, self._centers)
        return float(np.maximum(d2.min(1), 0.0).sum())

    def _save_data(self, path: str) -> None:
        save_arrays(path, centers=self._centers,
                    training_cost=np.array(self.training_cost))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._centers = arrs["centers"]
        self.training_cost = float(arrs["training_cost"])
