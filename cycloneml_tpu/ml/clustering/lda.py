"""Latent Dirichlet Allocation.

Re-design of the reference (ref: ml/clustering/LDA.scala; optimizer selection
mllib/clustering/LDA.scala:306 — "online" = OnlineLDAOptimizer
(mllib/clustering/LDAOptimizer.scala:229, Hoffman et al. online variational
Bayes with (tau0 + t)^-kappa step sizes and per-partition sufficient-stat
aggregation), "em" = graph-based EMLDAOptimizer). TPU-first formulation:

- corpus = the row-sharded dense count matrix (docs × vocab) of an
  ``InstanceDataset``; the reference's per-partition "submitMiniBatch"
  nonConvexOpt is ONE SPMD program: a vmapped fixed-point gamma loop
  (``lax.fori_loop``, static iteration count — no data-dependent Python
  control flow) followed by an expElogbeta-weighted sstats matmul on the MXU,
  psum'd over the mesh.
- "em" here is batch variational EM — the same variational family run on the
  full corpus with step size 1 (the reference's EMLDAOptimizer is collapsed
  Gibbs-flavored EM over a GraphX bipartite graph; a vertex-cut graph is the
  wrong shape for a dense systolic array, the batch VB limit of the online
  update optimizes the same ELBO).
- mini-batching ("online") subsamples docs per iteration with an on-device
  bernoulli mask — no host-side shuffling of the corpus.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import HasFeaturesCol, HasMaxIter, HasSeed
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_GAMMA_ITERS = 40  # per-doc variational fixed-point iterations (static)


class _LDAParams(HasFeaturesCol, HasMaxIter, HasSeed):
    def _declare_lda_params(self):
        self._p_features_col()
        self._p_max_iter(20)
        self._p_seed(17)
        self.k = self._param("k", "number of topics (> 1)", V.gt(1), default=10)
        self.optimizer = self._param(
            "optimizer", "online or em",
            V.in_array(["online", "em"]), default="online")
        self.docConcentration = self._param(
            "docConcentration", "alpha prior on doc-topic dist (-1 = auto 1/k)",
            default=-1.0)
        self.topicConcentration = self._param(
            "topicConcentration", "eta prior on topic-term dist (-1 = auto 1/k)",
            default=-1.0)
        self.learningOffset = self._param(
            "learningOffset", "tau0 (>0) downweights early iterations",
            V.gt(0.0), default=1024.0)
        self.learningDecay = self._param(
            "learningDecay", "kappa in (0.5, 1]", V.gt(0.0), default=0.51)
        self.subsamplingRate = self._param(
            "subsamplingRate", "minibatch fraction in (0, 1]",
            V.in_range(0.0, 1.0, lower_inclusive=False), default=0.05)
        self.topicDistributionCol = self._param(
            "topicDistributionCol", "output column for doc-topic mixture",
            default="topicDistribution")


class LDA(Estimator, _LDAParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_lda_params()
        for key, v in kwargs.items():
            self.set(key, v)

    def set_k(self, v):
        return self.set("k", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_optimizer(self, v):
        return self.set("optimizer", v)

    def _alpha_eta(self) -> Tuple[float, float]:
        k = self.get("k")
        a = self.get("docConcentration")
        e = self.get("topicConcentration")
        alpha = (1.0 / k) if a is None or a <= 0 else float(a)
        eta = (1.0 / k) if e is None or e <= 0 else float(e)
        return alpha, eta

    def _fit(self, frame: MLFrame) -> "LDAModel":
        ds = frame.to_instance_dataset(self.get("featuresCol"), label_col=None)
        return self._fit_dataset(ds)

    def _fit_dataset(self, ds: InstanceDataset) -> "LDAModel":
        import jax
        import jax.numpy as jnp

        k, vocab = self.get("k"), ds.n_features
        alpha, eta = self._alpha_eta()
        online = self.get("optimizer") == "online"
        frac = self.get("subsamplingRate") if online else 1.0
        n_docs = ds.n_rows
        tau0 = self.get("learningOffset")
        kappa = self.get("learningDecay")
        dtype = ds.w.dtype  # accumulator tier: X may store bf16

        rng = np.random.RandomState(self.get("seed"))
        # lambda init ~ Gamma(100, 1/100) as in Hoffman et al. / the reference
        lam = rng.gamma(100.0, 1.0 / 100.0, (k, vocab))

        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS

        def e_step(x, y, w, lam_in, subsample_key):
            # doc mask: real rows (w>0), optionally subsampled
            keep = w > 0
            if frac < 1.0:
                # fold the shard's mesh position into the replicated key so
                # each shard draws an INDEPENDENT doc subsample
                shard_key = jax.random.fold_in(
                    jax.random.fold_in(subsample_key,
                                       jax.lax.axis_index(DATA_AXIS)),
                    jax.lax.axis_index(REPLICA_AXIS))
                u = jax.random.uniform(shard_key, w.shape, dtype=w.dtype)
                keep = jnp.logical_and(keep, u < frac)
            keep_f = keep.astype(w.dtype)

            Elogbeta = (jax.scipy.special.digamma(lam_in)
                        - jax.scipy.special.digamma(
                            jnp.sum(lam_in, axis=1, keepdims=True)))
            expElogbeta = jnp.exp(Elogbeta)                        # (k, V)

            cts = x * keep_f[:, None]                              # (b, V)
            gamma0 = jnp.full((x.shape[0], k), 1.0, dtype=w.dtype)

            def gamma_iter(_, gamma):
                Elogtheta = (jax.scipy.special.digamma(gamma)
                             - jax.scipy.special.digamma(
                                 jnp.sum(gamma, axis=1, keepdims=True)))
                expElogtheta = jnp.exp(Elogtheta)                  # (b, k)
                phinorm = jnp.dot(expElogtheta, expElogbeta,
                                  precision=jax.lax.Precision.HIGHEST) + 1e-100
                return alpha + expElogtheta * jnp.dot(
                    cts / phinorm, expElogbeta.T,
                    precision=jax.lax.Precision.HIGHEST)

            gamma = jax.lax.fori_loop(0, _GAMMA_ITERS, gamma_iter, gamma0)
            Elogtheta = (jax.scipy.special.digamma(gamma)
                         - jax.scipy.special.digamma(
                             jnp.sum(gamma, axis=1, keepdims=True)))
            expElogtheta = jnp.exp(Elogtheta)
            phinorm = jnp.dot(expElogtheta, expElogbeta,
                              precision=jax.lax.Precision.HIGHEST) + 1e-100
            # sstats[k, w] = sum_d expElogtheta_dk * cts_dw / phinorm_dw
            sstats = jnp.dot(expElogtheta.T, cts / phinorm,
                             precision=jax.lax.Precision.HIGHEST)
            return {"sstats": sstats, "n_batch": jnp.sum(keep_f),
                    "tokens": jnp.sum(cts)}

        step = ds.tree_aggregate_fn(e_step)

        import jax.random as jrandom
        for t in range(self.get("maxIter")):
            key = jrandom.PRNGKey(self.get("seed") * 100003 + t)
            # one transfer per E-step, not one per stat (graftlint JX001)
            out = jax.device_get(step(jnp.asarray(lam, dtype=dtype), key))
            sstats = np.asarray(out["sstats"], np.float64)
            batch_docs = float(out["n_batch"])
            if batch_docs <= 0:
                continue
            Elogbeta = _dirichlet_expectation(lam)
            lam_new = eta + (n_docs / batch_docs) * sstats * np.exp(Elogbeta)
            rho = (tau0 + t + 1) ** (-kappa) if online else 1.0
            lam = (1.0 - rho) * lam + rho * lam_new

        model = LDAModel(lam, vocab_size=vocab, alpha=alpha, eta=eta,
                         uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model


def _dirichlet_expectation(a: np.ndarray) -> np.ndarray:
    from scipy.special import psi
    return psi(a) - psi(a.sum(axis=1, keepdims=True))


class LDAModel(Model, _LDAParams, MLWritable, MLReadable):
    def __init__(self, lam: Optional[np.ndarray] = None, vocab_size: int = 0,
                 alpha: float = 0.1, eta: float = 0.1, uid=None):
        super().__init__(uid)
        self._declare_lda_params()
        self._lam = np.asarray(lam) if lam is not None else None
        self._vocab_size = vocab_size
        self._alpha = alpha
        self._eta = eta

    @property
    def vocab_size(self) -> int:
        return self._vocab_size

    def topics_matrix(self) -> np.ndarray:
        """(vocab, k) column-normalized topic-term matrix (ref
        LDAModel.topicsMatrix layout)."""
        beta = self._lam / self._lam.sum(axis=1, keepdims=True)
        return beta.T

    def describe_topics(self, max_terms: int = 10) -> List[Tuple[np.ndarray, np.ndarray]]:
        beta = self._lam / self._lam.sum(axis=1, keepdims=True)
        out = []
        for row in beta:
            idx = np.argsort(-row)[:max_terms]
            out.append((idx, row[idx]))
        return out

    def _infer_gamma(self, x: np.ndarray) -> np.ndarray:
        expElogbeta = np.exp(_dirichlet_expectation(self._lam))
        gamma = np.full((x.shape[0], self._lam.shape[0]), 1.0)
        for _ in range(_GAMMA_ITERS):
            expElogtheta = np.exp(_dirichlet_expectation(gamma))
            phinorm = expElogtheta @ expElogbeta + 1e-100
            gamma = self._alpha + expElogtheta * ((x / phinorm) @ expElogbeta.T)
        return gamma

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        if x.ndim == 1:
            x = x[:, None]
        gamma = self._infer_gamma(x)
        theta = gamma / gamma.sum(axis=1, keepdims=True)
        return frame.with_column(self.get("topicDistributionCol"), theta)

    def log_likelihood(self, frame: MLFrame) -> float:
        """Variational lower bound on log p(docs) (ref
        LocalLDAModel.logLikelihood — same ELBO decomposition)."""
        from scipy.special import gammaln
        x = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        if x.ndim == 1:
            x = x[:, None]
        k, vocab = self._lam.shape
        alpha, eta = self._alpha, self._eta
        gamma = self._infer_gamma(x)
        Elogtheta = _dirichlet_expectation(gamma)
        Elogbeta = _dirichlet_expectation(self._lam)
        score = 0.0
        # E[log p(docs | theta, beta)] via the phi-optimal bound:
        # log sum_k exp(Elogtheta_dk + Elogbeta_kw), computed stably
        t = Elogtheta[:, :, None] + Elogbeta[None, :, :]
        tmax = t.max(axis=1)
        lse = tmax + np.log(np.exp(t - tmax[:, None, :]).sum(axis=1))
        score += float((x * lse).sum())
        # E[log p(theta | alpha) - log q(theta | gamma)]
        score += float(((alpha - gamma) * Elogtheta).sum())
        score += float((gammaln(gamma) - gammaln(alpha)).sum())
        score += float((gammaln(alpha * k) - gammaln(gamma.sum(1))).sum())
        # E[log p(beta | eta) - log q(beta | lambda)]
        score += float(((eta - self._lam) * Elogbeta).sum())
        score += float((gammaln(self._lam) - gammaln(eta)).sum())
        score += float((gammaln(eta * vocab)
                        - gammaln(self._lam.sum(1))).sum())
        return score

    def log_perplexity(self, frame: MLFrame) -> float:
        x = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        tokens = float(x.sum())
        return -self.log_likelihood(frame) / max(tokens, 1.0)

    def _save_data(self, path: str) -> None:
        save_arrays(path, lam=self._lam,
                    meta=np.array([self._vocab_size, self._alpha, self._eta]))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._lam = arrs["lam"]
        self._vocab_size = int(arrs["meta"][0])
        self._alpha = float(arrs["meta"][1])
        self._eta = float(arrs["meta"][2])
