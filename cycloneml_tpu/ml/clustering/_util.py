"""Shared distance kernels for the clustering family.

One definition of the ``‖x‖² + ‖c‖² − 2x·cᵀ`` MXU distance expansion and the
unit-row normalization (cosine mode), used by KMeans and BisectingKMeans on
both the device (jnp) and host (np) paths so the clamp/epsilon constants
cannot diverge between call sites.
"""

from __future__ import annotations

_NORM_EPS = 1e-12


def pairwise_sq_dists(xp, x, c, precision=None):
    """(n, k) squared euclidean distances via one matmul; ``xp`` is np or jnp."""
    if precision is None:
        dot = xp.dot(x, c.T)
    else:
        dot = xp.dot(x, c.T, precision=precision)
    return (xp.sum(x * x, axis=1)[:, None]
            + xp.sum(c * c, axis=1)[None, :] - 2.0 * dot)


def normalize_rows(xp, x):
    """Rows scaled to unit L2 norm (cosine-distance preprocessing)."""
    n = xp.sqrt(xp.sum(x * x, axis=1))[:, None]
    return x / xp.maximum(n, _NORM_EPS)
