"""Shared distance kernels for the clustering family.

One definition of the ``‖x‖² + ‖c‖² − 2x·cᵀ`` MXU distance expansion and the
unit-row normalization (cosine mode), used by KMeans and BisectingKMeans on
both the device (jnp) and host (np) paths so the clamp/epsilon constants
cannot diverge between call sites.
"""

from __future__ import annotations

_NORM_EPS = 1e-12


def pairwise_sq_dists(xp, x, c, precision=None):
    """(n, k) squared euclidean distances via one matmul; ``xp`` is np or
    jnp. Narrow (bf16 data-tier) operands keep storage width as the dot's
    multiplicands but ACCUMULATE in f32 (preferred_element_type) — both
    the matmul and the norms; distances rounded at 8 mantissa bits would
    swamp the near-tie argmins. numpy has no mixed-precision dot, so the
    (host-side, rare) narrow case upcasts there instead."""
    narrow = x.dtype.itemsize < 4 or c.dtype.itemsize < 4
    if narrow and xp.__name__.startswith("numpy"):
        x, c = x.astype(xp.float32), c.astype(xp.float32)
        narrow = False
    kw = {} if precision is None else {"precision": precision}
    if narrow:
        kw["preferred_element_type"] = xp.float32
    dot = xp.dot(x, c.T, **kw)
    xw = x if x.dtype == dot.dtype else x.astype(dot.dtype)
    cw = c if c.dtype == dot.dtype else c.astype(dot.dtype)
    return (xp.sum(xw * xw, axis=1)[:, None]
            + xp.sum(cw * cw, axis=1)[None, :] - 2.0 * dot)


def normalize_rows(xp, x):
    """Rows scaled to unit L2 norm (cosine-distance preprocessing). Norms
    square/reduce at accumulator width (f32) for narrow (bf16) rows —
    same discipline as pairwise_sq_dists — and the result returns to the
    input's storage tier (the normalized copy must not silently widen)."""
    xw = x if x.dtype.itemsize >= 4 else x.astype(xp.float32)
    out = xw / xp.maximum(xp.sqrt(xp.sum(xw * xw, axis=1))[:, None],
                          _NORM_EPS)
    return out if out.dtype == x.dtype else out.astype(x.dtype)
