"""Power iteration clustering (Lin & Cohen 2010).

Re-design of the reference (ref: ml/clustering/PowerIterationClustering.scala
— ``assignClusters`` over a (src, dst, weight) affinity DataFrame; the
mllib impl mllib/clustering/PowerIterationClustering.scala:41 runs the
power iteration with GraphX materializing W v per superstep). TPU-first:
the graph lives as flat edge arrays on device; one power-iteration step is a
``segment_sum`` of w·v[dst] into src (a gather + scatter-add the XLA
compiler vectorizes) inside a ``lax.fori_loop`` — no per-superstep host
round-trip. The final 1-D embedding is clustered with weighted k-means on
the driver (it is k scalars per point).
"""

from __future__ import annotations

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.param import ParamValidators as V, Params
from cycloneml_tpu.ml.shared import HasMaxIter, HasSeed, HasWeightCol
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class PowerIterationClustering(HasMaxIter, HasSeed, HasWeightCol):
    """Not an Estimator (matches the reference): call
    :meth:`assign_clusters` on a frame of (src, dst, weight) edges."""

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._p_max_iter(20)
        self._p_seed(17)
        self._p_weight_col()
        self.k = self._param("k", "number of clusters (> 1)", V.gt(1), default=2)
        self.initMode = self._param(
            "initMode", "random or degree",
            V.in_array(["random", "degree"]), default="random")
        self.srcCol = self._param("srcCol", "source vertex id column",
                                  default="src")
        self.dstCol = self._param("dstCol", "destination vertex id column",
                                  default="dst")
        for key, v in kwargs.items():
            self.set(key, v)

    def set_k(self, v):
        return self.set("k", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def assign_clusters(self, frame: MLFrame) -> MLFrame:
        import jax
        import jax.numpy as jnp

        src = np.asarray(frame[self.get("srcCol")], dtype=np.int64)
        dst = np.asarray(frame[self.get("dstCol")], dtype=np.int64)
        wcol = self.get("weightCol") or None
        w = (np.asarray(frame[wcol], dtype=np.float64) if wcol
             else np.ones(len(src)))
        if np.any(w < 0):
            raise ValueError("affinity weights must be non-negative")

        # relabel arbitrary ids to [0, n)
        ids = np.unique(np.concatenate([src, dst]))
        lookup = {int(v): i for i, v in enumerate(ids)}
        si = np.fromiter((lookup[int(v)] for v in src), np.int32, len(src))
        di = np.fromiter((lookup[int(v)] for v in dst), np.int32, len(dst))
        n = len(ids)

        # symmetrize (ref requires a symmetric affinity; tolerate one-sided
        # input by mirroring edges)
        s2 = np.concatenate([si, di])
        d2 = np.concatenate([di, si])
        w2 = np.concatenate([w, w])

        deg = np.bincount(s2, weights=w2, minlength=n)
        if np.any(deg <= 0):
            raise ValueError("every vertex needs positive degree")

        rng = np.random.RandomState(self.get("seed"))
        if self.get("initMode") == "degree":
            v0 = deg / deg.sum()
        else:
            v0 = rng.rand(n) / n
        v0 = v0 / np.abs(v0).sum()

        sj = jnp.asarray(s2)
        dj = jnp.asarray(d2)
        wj = jnp.asarray(w2 / deg[s2])  # row-normalized: W = D^-1 A

        # the reference stops on acceleration |delta_t - delta_{t-1}| <
        # 1e-5/n (mllib PowerIterationClustering.powerIter) — running to
        # convergence would flatten v into the stationary distribution and
        # erase the cluster structure
        eps = 1e-5 / n
        max_iter = self.get("maxIter")

        @jax.jit
        def iterate(v):
            def cond(state):
                _, _, diff, i = state
                return jnp.logical_and(i < max_iter, diff >= eps)

            def body(state):
                v, prev_delta, _, i = state
                nv = jax.ops.segment_sum(wj * v[dj], sj, num_segments=n)
                nv = nv / jnp.maximum(jnp.sum(jnp.abs(nv)), 1e-300)
                delta = jnp.sum(jnp.abs(nv - v))
                return nv, delta, jnp.abs(delta - prev_delta), i + 1

            out, _, _, _ = jax.lax.while_loop(
                cond, body, (v, jnp.inf, jnp.inf, 0))
            return out

        embedding = np.asarray(iterate(jnp.asarray(v0)), dtype=np.float64)

        labels = _kmeans_1d(embedding, self.get("k"), rng)
        return MLFrame(frame.ctx, {
            "id": ids.astype(np.float64),
            "cluster": labels.astype(np.float64),
        })


def _kmeans_1d(v: np.ndarray, k: int, rng: np.random.RandomState) -> np.ndarray:
    """Driver-side k-means on the 1-D embedding (k scalars ≪ data size)."""
    uniq = np.unique(v)
    if len(uniq) <= k:
        lut = {val: i for i, val in enumerate(uniq)}
        return np.fromiter((lut[x] for x in v), np.int64, len(v))
    # k-means++ seeding
    centers = [v[rng.randint(len(v))]]
    d2 = (v - centers[0]) ** 2
    for _ in range(1, k):
        p = d2 / d2.sum()
        centers.append(v[rng.choice(len(v), p=p)])
        d2 = np.minimum(d2, (v - centers[-1]) ** 2)
    c = np.asarray(centers)
    for _ in range(50):
        a = np.abs(v[:, None] - c[None, :]).argmin(1)
        newc = np.array([v[a == j].mean() if np.any(a == j) else c[j]
                         for j in range(k)])
        if np.allclose(newc, c):
            break
        c = newc
    return np.abs(v[:, None] - c[None, :]).argmin(1)
