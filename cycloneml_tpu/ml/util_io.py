"""Model persistence.

Mirrors the reference's MLWriter/MLReader layout (ref: ml/util/ReadWrite.scala
— MLWriter:157, MLReader:323, MLWritable:274, DefaultParamsWriter/Reader):
a model directory containing ``metadata/part-00000`` with
{class, timestamp, uid, paramMap, defaultParamMap} JSON, and a ``data/``
directory for learned state (npz here instead of Parquet). Pipelines persist
stages under ``stages/<idx>_<uid>/`` exactly like the reference.
"""

from __future__ import annotations

import importlib
import json
import os
import shutil
import time
from typing import Any, Dict, Optional

import numpy as np

VERSION = "0.1.0"


def _metadata_path(path: str) -> str:
    return os.path.join(path, "metadata", "part-00000")


def save_metadata(instance, path: str, extra: Optional[Dict[str, Any]] = None) -> None:
    os.makedirs(os.path.join(path, "metadata"), exist_ok=True)
    meta = {
        "class": f"{type(instance).__module__}.{type(instance).__qualname__}",
        "timestamp": int(time.time() * 1000),
        "cycloneVersion": VERSION,
        "uid": instance.uid,
        "paramMap": instance._params_to_json(),
        "defaultParamMap": instance._default_params_to_json(),
    }
    if extra:
        meta.update(extra)
    with open(_metadata_path(path), "w", encoding="utf-8") as fh:
        json.dump(meta, fh)


def load_metadata(path: str) -> Dict[str, Any]:
    with open(_metadata_path(path), encoding="utf-8") as fh:
        return json.load(fh)


def instantiate_from_metadata(meta: Dict[str, Any]):
    module, _, name = meta["class"].rpartition(".")
    cls = getattr(importlib.import_module(module), name)
    obj = cls.__new__(cls)
    cls.__init__(obj, uid=meta["uid"]) if _init_takes_uid(cls) else cls.__init__(obj)
    obj._set_params_from_json(meta.get("defaultParamMap", {}), default=True)
    obj._set_params_from_json(meta.get("paramMap", {}))
    return obj


def _init_takes_uid(cls) -> bool:
    import inspect
    try:
        return "uid" in inspect.signature(cls.__init__).parameters
    except (TypeError, ValueError):
        return False


def save_arrays(path: str, **arrays) -> None:
    os.makedirs(os.path.join(path, "data"), exist_ok=True)
    np.savez(os.path.join(path, "data", "data.npz"), **arrays)


def load_arrays(path: str) -> Dict[str, np.ndarray]:
    z = np.load(os.path.join(path, "data", "data.npz"), allow_pickle=False)
    return {k: z[k] for k in z.files}


class MLWritable:
    """Mixin giving ``save(path)`` (ref MLWritable:274). Subclasses override
    ``_save_data(path)`` to write learned state."""

    def save(self, path: str, overwrite: bool = False) -> None:
        if os.path.exists(path):
            if not overwrite:
                raise IOError(f"Path exists: {path}; use overwrite=True")
            shutil.rmtree(path)
        os.makedirs(path)
        save_metadata(self, path)
        self._save_data(path)

    def write(self) -> "_Writer":
        return _Writer(self)

    def _save_data(self, path: str) -> None:
        pass


class _Writer:
    """Fluent writer (ref MLWriter:157)."""

    def __init__(self, instance):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "_Writer":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        self._instance.save(path, overwrite=self._overwrite)


class MLReadable:
    """Mixin giving ``load(path)`` (ref MLReadable/MLReader:323)."""

    @classmethod
    def load(cls, path: str):
        meta = load_metadata(path)
        obj = instantiate_from_metadata(meta)
        if not isinstance(obj, cls):
            raise TypeError(f"{path} holds {type(obj).__name__}, expected {cls.__name__}")
        obj._load_data(path, meta)
        return obj

    @classmethod
    def read(cls) -> "_Reader":
        return _Reader(cls)

    def _load_data(self, path: str, meta: Dict[str, Any]) -> None:
        pass


class _Reader:
    def __init__(self, cls):
        self._cls = cls

    def load(self, path: str):
        return self._cls.load(path)


def save_pipeline_stages(stages, path: str) -> None:
    os.makedirs(os.path.join(path, "stages"), exist_ok=True)
    for i, stage in enumerate(stages):
        stage.save(os.path.join(path, "stages", f"{i}_{stage.uid}"), overwrite=True)


def load_pipeline_stages(path: str):
    sdir = os.path.join(path, "stages")
    entries = sorted(os.listdir(sdir), key=lambda s: int(s.split("_", 1)[0]))
    out = []
    for e in entries:
        spath = os.path.join(sdir, e)
        meta = load_metadata(spath)
        obj = instantiate_from_metadata(meta)
        obj._load_data(spath, meta)
        out.append(obj)
    return out
