"""Estimator/Transformer/Pipeline abstractions.

Mirrors the reference's pipeline API (ref: mllib/src/main/scala/org/apache/
spark/ml/Pipeline.scala:93 Pipeline, :296 PipelineModel; Predictor.scala;
classification/Classifier.scala, ProbabilisticClassifier.scala) over
``MLFrame`` instead of SQL DataFrames.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.param import ParamMap, Params
from cycloneml_tpu.ml.shared import (
    HasFeaturesCol, HasLabelCol, HasPredictionCol, HasProbabilityCol,
    HasRawPredictionCol, HasWeightCol,
)
from cycloneml_tpu.ml.util_io import (
    MLReadable, MLWritable, load_pipeline_stages, save_pipeline_stages,
)


class PipelineStage(Params):
    """Base for Estimator and Transformer (ref Pipeline.scala PipelineStage)."""


class Transformer(PipelineStage):
    def transform(self, frame: MLFrame, params: Optional[ParamMap] = None) -> MLFrame:
        if params is not None:
            return self.copy(params).transform(frame)
        return self._transform(frame)

    def _transform(self, frame: MLFrame) -> MLFrame:
        raise NotImplementedError


class Estimator(PipelineStage):
    def fit(self, frame: MLFrame, params: Optional[ParamMap] = None):
        if params is not None:
            return self.copy(params).fit(frame)
        ctx = getattr(frame, "ctx", None)
        if ctx is not None and hasattr(ctx, "run_job"):
            # every fit is a tracked job in the status store / event journal
            return ctx.run_job(f"{type(self).__name__}.fit",
                               lambda: self._fit(frame))
        return self._fit(frame)

    def _fit(self, frame: MLFrame):
        raise NotImplementedError


class Model(Transformer):
    """A fitted Transformer with a parent estimator reference."""

    parent: Optional[Estimator] = None

    def _set_parent(self, parent: Estimator) -> "Model":
        self.parent = parent
        return self


class Pipeline(Estimator, MLWritable, MLReadable):
    """Chain of stages (ref Pipeline.scala:93): fit runs estimators in order,
    transforming the frame through each fitted model."""

    def __init__(self, stages: Optional[Sequence[PipelineStage]] = None, uid=None):
        super().__init__(uid)
        self.stagesParam = self._param("stages", "pipeline stages")
        if stages is not None:
            self.set_stages(list(stages))

    def set_stages(self, stages: List[PipelineStage]) -> "Pipeline":
        self._stages = list(stages)
        return self

    def get_stages(self) -> List[PipelineStage]:
        return list(getattr(self, "_stages", []))

    def _fit(self, frame: MLFrame) -> "PipelineModel":
        cur = frame
        fitted: List[Transformer] = []
        stages = self.get_stages()
        # find last estimator; transformers after it need not be applied to data
        last_est = -1
        for i, s in enumerate(stages):
            if isinstance(s, Estimator):
                last_est = i
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                model = stage.fit(cur)
                fitted.append(model)
                if i < last_est:
                    cur = model.transform(cur)
            elif isinstance(stage, Transformer):
                fitted.append(stage)
                if i < last_est:
                    cur = stage.transform(cur)
            else:
                raise TypeError(f"stage {stage} is neither Estimator nor Transformer")
        return PipelineModel(fitted, uid=self.uid)._set_parent(self)

    def copy(self, extra: Optional[ParamMap] = None) -> "Pipeline":
        that = super().copy(extra)
        that._stages = [s.copy(extra) for s in self.get_stages()]
        return that

    def _save_data(self, path: str) -> None:
        save_pipeline_stages(self.get_stages(), path)

    def _load_data(self, path: str, meta) -> None:
        self._stages = load_pipeline_stages(path)


class PipelineModel(Model, MLWritable, MLReadable):
    """Fitted pipeline (ref Pipeline.scala:296)."""

    def __init__(self, stages: Optional[List[Transformer]] = None, uid=None):
        super().__init__(uid)
        self.stages = list(stages or [])

    def _transform(self, frame: MLFrame) -> MLFrame:
        cur = frame
        for stage in self.stages:
            cur = stage.transform(cur)
        return cur

    def copy(self, extra: Optional[ParamMap] = None) -> "PipelineModel":
        that = super().copy(extra)
        that.stages = [s.copy(extra) for s in self.stages]
        return that

    def _save_data(self, path: str) -> None:
        save_pipeline_stages(self.stages, path)

    def _load_data(self, path: str, meta) -> None:
        self.stages = load_pipeline_stages(path)


# ---------------------------------------------------------------------------
# Predictor hierarchy (ref: ml/Predictor.scala, classification/Classifier.scala)
# ---------------------------------------------------------------------------

class Predictor(Estimator, HasFeaturesCol, HasLabelCol, HasPredictionCol,
                HasWeightCol):
    def __init__(self, uid=None):
        super().__init__(uid)
        self._p_features_col()
        self._p_label_col()
        self._p_prediction_col()
        self._p_weight_col()

    def set_features_col(self, v: str):
        return self.set("featuresCol", v)

    def set_label_col(self, v: str):
        return self.set("labelCol", v)

    def set_prediction_col(self, v: str):
        return self.set("predictionCol", v)

    def set_weight_col(self, v: str):
        return self.set("weightCol", v)


class PredictionModel(Model, HasFeaturesCol, HasPredictionCol):
    def __init__(self, uid=None):
        super().__init__(uid)
        self._p_features_col()
        self._p_prediction_col()

    @property
    def num_features(self) -> int:
        raise NotImplementedError

    def predict(self, features) -> float:
        """Single-vector prediction."""
        arr = features.to_array() if hasattr(features, "to_array") else np.asarray(features)
        return float(self._predict_batch(arr[None, :])[0])

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        return frame.with_column(self.get("predictionCol"), self._predict_batch(x))


class ClassificationModel(PredictionModel, HasRawPredictionCol):
    def __init__(self, uid=None):
        super().__init__(uid)
        self._p_raw_prediction_col()

    @property
    def num_classes(self) -> int:
        raise NotImplementedError

    def _raw_prediction(self, x: np.ndarray) -> np.ndarray:
        """(n, num_classes) margins."""
        raise NotImplementedError

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        # route through _raw_to_prediction so threshold-aware subclasses keep
        # predict() consistent with transform()
        return self._raw_to_prediction(self._raw_prediction(x))

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        raw = self._raw_prediction(x)
        out = frame
        if self.get("rawPredictionCol"):
            out = out.with_column(self.get("rawPredictionCol"), raw)
        if self.get("predictionCol"):
            out = out.with_column(self.get("predictionCol"),
                                  self._raw_to_prediction(raw))
        return out

    def _raw_to_prediction(self, raw: np.ndarray) -> np.ndarray:
        return np.argmax(raw, axis=1).astype(np.float64)


class ProbabilisticClassificationModel(ClassificationModel, HasProbabilityCol):
    def __init__(self, uid=None):
        super().__init__(uid)
        self._p_probability_col()

    def _raw_to_probability(self, raw: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        raw = self._raw_prediction(x)  # computed once for all three columns
        out = frame
        if self.get("rawPredictionCol"):
            out = out.with_column(self.get("rawPredictionCol"), raw)
        if self.get("probabilityCol"):
            out = out.with_column(self.get("probabilityCol"),
                                  self._raw_to_probability(raw))
        if self.get("predictionCol"):
            out = out.with_column(self.get("predictionCol"),
                                  self._raw_to_prediction(raw))
        return out
