"""Shared param mixins (ref: ml/param/shared/sharedParams.scala — HasMaxIter,
HasRegParam, HasTol, HasFeaturesCol, ... generated traits). Each mixin
declares its param in ``_declare_shared`` which subclasses call in __init__.
"""

from __future__ import annotations

from cycloneml_tpu.ml.param import Params, ParamValidators as V


class HasFeaturesCol(Params):
    def _p_features_col(self):
        self.featuresCol = self._param("featuresCol", "features column name",
                                       default="features")


class HasLabelCol(Params):
    def _p_label_col(self):
        self.labelCol = self._param("labelCol", "label column name", default="label")


class HasWeightCol(Params):
    def _p_weight_col(self):
        self.weightCol = self._param("weightCol", "instance weight column", default="")


class HasPredictionCol(Params):
    def _p_prediction_col(self):
        self.predictionCol = self._param("predictionCol", "prediction column name",
                                         default="prediction")


class HasProbabilityCol(Params):
    def _p_probability_col(self):
        self.probabilityCol = self._param("probabilityCol",
                                          "class probabilities column",
                                          default="probability")


class HasRawPredictionCol(Params):
    def _p_raw_prediction_col(self):
        self.rawPredictionCol = self._param("rawPredictionCol",
                                            "raw prediction (margin) column",
                                            default="rawPrediction")


class HasMaxIter(Params):
    def _p_max_iter(self, default=100):
        self.maxIter = self._param("maxIter", "maximum iterations (>= 0)",
                                   V.gt_eq(0), default=default)


class HasRegParam(Params):
    def _p_reg_param(self, default=0.0):
        self.regParam = self._param("regParam", "regularization parameter (>= 0)",
                                    V.gt_eq(0.0), default=default)


class HasElasticNetParam(Params):
    def _p_elastic_net(self, default=0.0):
        self.elasticNetParam = self._param(
            "elasticNetParam", "ElasticNet mixing in [0,1]: 0=L2, 1=L1",
            V.in_range(0.0, 1.0), default=default)


class HasTol(Params):
    def _p_tol(self, default=1e-6):
        self.tol = self._param("tol", "convergence tolerance (>= 0)",
                               V.gt_eq(0.0), default=default)


class HasFitIntercept(Params):
    def _p_fit_intercept(self, default=True):
        self.fitIntercept = self._param("fitIntercept", "whether to fit intercept",
                                        default=default)


class HasStandardization(Params):
    def _p_standardization(self, default=True):
        self.standardization = self._param(
            "standardization", "standardize features before fitting",
            default=default)


class HasThreshold(Params):
    def _p_threshold(self, default=0.5):
        self.threshold = self._param("threshold", "binary prediction threshold",
                                     V.in_range(0.0, 1.0), default=default)


class HasSeed(Params):
    def _p_seed(self, default=17):
        self.seed = self._param("seed", "random seed", default=default)


class HasAggregationDepth(Params):
    def _p_aggregation_depth(self, default=2):
        self.aggregationDepth = self._param(
            "aggregationDepth", "treeAggregate depth (>= 1); on the mesh this "
            "selects hierarchical ICI/DCN reduction and is honoured for API "
            "parity", V.gt_eq(1), default=default)


class HasSolver(Params):
    def _p_solver(self, allowed, default):
        self.solver = self._param("solver", f"solver, one of {allowed}",
                                  V.in_array(allowed), default=default)


class HasMaxBlockSizeInMB(Params):
    def _p_max_block_size(self, default=0.0):
        self.maxBlockSizeInMB = self._param(
            "maxBlockSizeInMB", "max block memory in MB (0 = auto); on the "
            "mesh the shard layout supersedes this, kept for API parity",
            V.gt_eq(0.0), default=default)
