"""Evaluators.

Parity with ref ml/evaluation: Evaluator.scala, BinaryClassificationEvaluator
(areaUnderROC/areaUnderPR via the mllib BinaryClassificationMetrics curves),
MulticlassClassificationEvaluator (accuracy, f1, precision/recall variants,
logLoss, hammingLoss), RegressionEvaluator (rmse/mse/mae/r2/var),
ClusteringEvaluator (silhouette), RankingEvaluator (MAP/NDCG/precision@k).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.param import Params, ParamValidators as V
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable


class Evaluator(Params, MLWritable, MLReadable):
    """Base (ref Evaluator.scala): evaluate + isLargerBetter."""

    def evaluate(self, frame: MLFrame) -> float:
        raise NotImplementedError

    @property
    def is_larger_better(self) -> bool:
        return True


def binary_curve_points(score: np.ndarray, y: np.ndarray,
                        w: Optional[np.ndarray] = None):
    """Shared sorted-pass curve machinery (≈ mllib
    BinaryClassificationMetrics): descending-score cumulative TP/FP with
    tied scores collapsed to one point (each tie-group's LAST cumulative —
    else the metric depends on row order within ties). Returns
    (thresholds, tps, fps, tp_total, fp_total) with totals floored at
    1e-300 for safe division."""
    if w is None:
        w = np.ones(len(y))
    order = np.argsort(-score, kind="stable")
    y, w, s = y[order], w[order], score[order]
    tps = np.cumsum(w * y)
    fps = np.cumsum(w * (1 - y))
    last_of_group = np.append(s[1:] != s[:-1], True)
    tps, fps, thresholds = tps[last_of_group], fps[last_of_group], s[last_of_group]
    return (thresholds, tps, fps,
            max(float(tps[-1]), 1e-300), max(float(fps[-1]), 1e-300))


class BinaryClassificationEvaluator(Evaluator):
    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.rawPredictionCol = self._param("rawPredictionCol",
                                            "raw prediction/score column",
                                            default="rawPrediction")
        self.labelCol = self._param("labelCol", "label column", default="label")
        self.weightCol = self._param("weightCol", "weight column", default="")
        self.metricName = self._param(
            "metricName", "areaUnderROC|areaUnderPR",
            V.in_array(["areaUnderROC", "areaUnderPR"]), default="areaUnderROC")
        for k, v in kw.items():
            self.set(k, v)

    def evaluate(self, frame: MLFrame) -> float:
        raw = frame[self.get("rawPredictionCol")]
        score = raw[:, 1] if raw.ndim == 2 else np.asarray(raw, dtype=np.float64)
        y = np.asarray(frame[self.get("labelCol")], dtype=np.float64)
        wcol = self.get("weightCol")
        w = np.asarray(frame[wcol], dtype=np.float64) if wcol else np.ones(len(y))
        _, tps, fps, tp_tot, fp_tot = binary_curve_points(score, y, w)
        if self.get("metricName") == "areaUnderROC":
            tpr = np.concatenate([[0.0], tps / tp_tot])
            fpr = np.concatenate([[0.0], fps / fp_tot])
            return float(np.trapezoid(tpr, fpr))
        precision = tps / np.maximum(tps + fps, 1e-300)
        recall = tps / tp_tot
        recall = np.concatenate([[0.0], recall])
        precision = np.concatenate([[1.0], precision])
        return float(np.trapezoid(precision, recall))


class MulticlassClassificationEvaluator(Evaluator):
    _METRICS = ["f1", "accuracy", "weightedPrecision", "weightedRecall",
                "weightedFMeasure", "weightedTruePositiveRate",
                "weightedFalsePositiveRate", "logLoss", "hammingLoss"]

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.predictionCol = self._param("predictionCol", "prediction column",
                                         default="prediction")
        self.labelCol = self._param("labelCol", "label column", default="label")
        self.probabilityCol = self._param("probabilityCol",
                                          "probability column (for logLoss)",
                                          default="probability")
        self.metricName = self._param("metricName", "metric",
                                      V.in_array(self._METRICS), default="f1")
        self.beta = self._param("beta", "F-beta", V.gt(0.0), default=1.0)
        for k, v in kw.items():
            self.set(k, v)

    @property
    def is_larger_better(self) -> bool:
        return self.get("metricName") not in ("logLoss", "hammingLoss")

    def evaluate(self, frame: MLFrame) -> float:
        metric = self.get("metricName")
        y = np.asarray(frame[self.get("labelCol")], dtype=np.int64)
        if metric == "logLoss":
            probs = frame[self.get("probabilityCol")]
            p = np.clip(probs[np.arange(len(y)), y], 1e-15, 1.0)
            return float(-np.log(p).mean())
        pred = np.asarray(frame[self.get("predictionCol")], dtype=np.int64)
        if metric == "accuracy":
            return float((pred == y).mean())
        if metric == "hammingLoss":
            return float((pred != y).mean())
        classes = np.unique(np.concatenate([y, pred]))
        n = len(y)
        weights = np.array([(y == c).sum() / n for c in classes])
        prec, rec, tpr, fpr = [], [], [], []
        for c in classes:
            tp = float(((pred == c) & (y == c)).sum())
            fp = float(((pred == c) & (y != c)).sum())
            fn = float(((pred != c) & (y == c)).sum())
            tn = n - tp - fp - fn
            prec.append(tp / max(tp + fp, 1e-300))
            rec.append(tp / max(tp + fn, 1e-300))
            tpr.append(tp / max(tp + fn, 1e-300))
            fpr.append(fp / max(fp + tn, 1e-300))
        prec, rec = np.array(prec), np.array(rec)
        if metric == "weightedPrecision":
            return float((weights * prec).sum())
        if metric in ("weightedRecall", "weightedTruePositiveRate"):
            return float((weights * rec).sum())
        if metric == "weightedFalsePositiveRate":
            return float((weights * np.array(fpr)).sum())
        # 'f1' is always beta=1 (as the reference); 'weightedFMeasure' honours beta
        beta2 = (self.get("beta") if metric == "weightedFMeasure" else 1.0) ** 2
        f = (1 + beta2) * prec * rec / np.maximum(beta2 * prec + rec, 1e-300)
        return float((weights * f).sum())


class RegressionEvaluator(Evaluator):
    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.predictionCol = self._param("predictionCol", "prediction column",
                                         default="prediction")
        self.labelCol = self._param("labelCol", "label column", default="label")
        self.metricName = self._param(
            "metricName", "rmse|mse|mae|r2|var",
            V.in_array(["rmse", "mse", "mae", "r2", "var"]), default="rmse")
        for k, v in kw.items():
            self.set(k, v)

    @property
    def is_larger_better(self) -> bool:
        return self.get("metricName") in ("r2", "var")

    def evaluate(self, frame: MLFrame) -> float:
        y = np.asarray(frame[self.get("labelCol")], dtype=np.float64)
        pred = np.asarray(frame[self.get("predictionCol")], dtype=np.float64)
        resid = y - pred
        m = self.get("metricName")
        if m == "rmse":
            return float(np.sqrt((resid ** 2).mean()))
        if m == "mse":
            return float((resid ** 2).mean())
        if m == "mae":
            return float(np.abs(resid).mean())
        if m == "var":
            return float(pred.var())
        sst = ((y - y.mean()) ** 2).sum()
        return float(1.0 - (resid ** 2).sum() / max(sst, 1e-300))


class ClusteringEvaluator(Evaluator):
    """Silhouette with squared euclidean distance (ref
    ClusteringEvaluator.scala — same default metric)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.predictionCol = self._param("predictionCol", "cluster column",
                                         default="prediction")
        self.featuresCol = self._param("featuresCol", "features column",
                                       default="features")
        self.metricName = self._param("metricName", "silhouette",
                                      V.in_array(["silhouette"]),
                                      default="silhouette")
        self.distanceMeasure = self._param(
            "distanceMeasure", "squaredEuclidean|cosine",
            V.in_array(["squaredEuclidean", "cosine"]),
            default="squaredEuclidean")
        for k, v in kw.items():
            self.set(k, v)

    def evaluate(self, frame: MLFrame) -> float:
        x = frame[self.get("featuresCol")].astype(np.float64)
        if self.get("distanceMeasure") == "cosine":
            x = x / np.maximum(np.linalg.norm(x, axis=1, keepdims=True), 1e-12)
        labels = np.asarray(frame[self.get("predictionCol")]).astype(int)
        classes = np.unique(labels)
        if len(classes) < 2:
            return 1.0
        # squared-euclidean silhouette via the cluster-moment trick the
        # reference uses (O(n·k) not O(n²)): ||x-y||² summed over cluster C =
        # |C|·||x||² - 2 x·S_C + Q_C
        sums = {c: x[labels == c].sum(axis=0) for c in classes}
        sqs = {c: (x[labels == c] ** 2).sum() for c in classes}
        cnt = {c: int((labels == c).sum()) for c in classes}
        sil = np.zeros(len(x))
        for i in range(len(x)):
            xi = x[i]
            xi_sq = float(xi @ xi)
            own = labels[i]
            def mean_d(c, exclude_self):
                n_c = cnt[c] - (1 if exclude_self else 0)
                if n_c == 0:
                    return 0.0
                s = sums[c] - (xi if exclude_self else 0.0)
                q = sqs[c] - (xi_sq if exclude_self else 0.0)
                return (n_c * xi_sq - 2.0 * float(xi @ s) + q) / n_c
            a = mean_d(own, True)
            b = min(mean_d(c, False) for c in classes if c != own)
            denom = max(a, b)
            sil[i] = (b - a) / denom if denom > 0 else 0.0
        return float(sil.mean())


class RankingEvaluator(Evaluator):
    """(ref RankingEvaluator.scala / mllib RankingMetrics): label and
    prediction columns hold arrays of ids (object columns)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.predictionCol = self._param("predictionCol", "predicted id arrays",
                                         default="prediction")
        self.labelCol = self._param("labelCol", "relevant id arrays",
                                    default="label")
        self.metricName = self._param(
            "metricName", "ranking metric",
            V.in_array(["meanAveragePrecision", "meanAveragePrecisionAtK",
                        "precisionAtK", "ndcgAtK", "recallAtK"]),
            default="meanAveragePrecision")
        self.k = self._param("k", "cutoff (> 0)", V.gt(0), default=10)
        for k_, v in kw.items():
            self.set(k_, v)

    def evaluate(self, frame: MLFrame) -> float:
        preds = frame[self.get("predictionCol")]
        labels = frame[self.get("labelCol")]
        metric = self.get("metricName")
        k = self.get("k")
        vals = []
        for p, l in zip(preds, labels):
            rel = set(l)
            p = list(p)
            if metric in ("meanAveragePrecision", "meanAveragePrecisionAtK"):
                cut = k if metric.endswith("AtK") else len(p)
                hits, score = 0, 0.0
                for rank, item in enumerate(p[:cut]):
                    if item in rel:
                        hits += 1
                        score += hits / (rank + 1)
                # ref RankingMetrics: MAP divides by labSet.size; only the
                # AtK variant divides by min(labSet.size, k)
                denom = min(len(rel), cut) if metric.endswith("AtK") else len(rel)
                vals.append(score / max(denom, 1))
            elif metric == "precisionAtK":
                vals.append(sum(1 for i in p[:k] if i in rel) / k)
            elif metric == "recallAtK":
                vals.append(sum(1 for i in p[:k] if i in rel) / max(len(rel), 1))
            else:  # ndcgAtK
                dcg = sum(1.0 / np.log2(r + 2) for r, item in enumerate(p[:k])
                          if item in rel)
                idcg = sum(1.0 / np.log2(r + 2)
                           for r in range(min(len(rel), k)))
                vals.append(dcg / max(idcg, 1e-300))
        return float(np.mean(vals)) if vals else 0.0


class MultilabelClassificationEvaluator(Evaluator):
    """(ref MultilabelClassificationEvaluator.scala:35 / mllib
    MultilabelMetrics): label and prediction columns hold per-row ARRAYS of
    label ids (object columns). Document-based metrics average per-row set
    statistics; micro metrics pool TP/FP/FN over all rows; the ByLabel
    variants restrict to ``metricLabel``.
    """

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.predictionCol = self._param(
            "predictionCol", "predicted label-id arrays", default="prediction")
        self.labelCol = self._param("labelCol", "true label-id arrays",
                                    default="label")
        self.metricName = self._param(
            "metricName", "multilabel metric",
            V.in_array(["subsetAccuracy", "accuracy", "hammingLoss",
                        "precision", "recall", "f1Measure",
                        "precisionByLabel", "recallByLabel",
                        "f1MeasureByLabel", "microPrecision", "microRecall",
                        "microF1Measure"]),
            default="f1Measure")
        self.metricLabel = self._param(
            "metricLabel", "label for the ByLabel metrics (>= 0)",
            V.gt_eq(0.0), default=0.0)
        for k_, v in kw.items():
            self.set(k_, v)

    @property
    def is_larger_better(self) -> bool:
        return self.get("metricName") != "hammingLoss"

    def evaluate(self, frame: MLFrame) -> float:
        preds = [set(p) for p in frame[self.get("predictionCol")]]
        labels = [set(l) for l in frame[self.get("labelCol")]]
        n = len(labels)
        if n == 0:
            return 0.0
        metric = self.get("metricName")
        inter = [len(p & l) for p, l in zip(preds, labels)]

        if metric == "subsetAccuracy":
            return float(np.mean([p == l for p, l in zip(preds, labels)]))
        if metric == "accuracy":
            return float(np.mean([
                i / max(len(p | l), 1)
                for i, p, l in zip(inter, preds, labels)]))
        if metric == "hammingLoss":
            # reference MultilabelMetrics.numLabels counts distinct ids from
            # the TRUE labels only (predicted-only ids do not widen the
            # denominator)
            num_labels = len(set().union(*labels))
            wrong = sum(len(p) + len(l) - 2 * i
                        for i, p, l in zip(inter, preds, labels))
            return wrong / (n * max(num_labels, 1))
        if metric == "precision":
            return float(np.mean([i / max(len(p), 1)
                                  for i, p in zip(inter, preds)]))
        if metric == "recall":
            return float(np.mean([i / max(len(l), 1)
                                  for i, l in zip(inter, labels)]))
        if metric == "f1Measure":
            return float(np.mean([
                2.0 * i / max(len(p) + len(l), 1)
                for i, p, l in zip(inter, preds, labels)]))

        if metric.startswith("micro"):
            tp = sum(inter)
            fp = sum(len(p) - i for i, p in zip(inter, preds))
            fn = sum(len(l) - i for i, l in zip(inter, labels))
            if metric == "microPrecision":
                return tp / max(tp + fp, 1)
            if metric == "microRecall":
                return tp / max(tp + fn, 1)
            return 2.0 * tp / max(2 * tp + fp + fn, 1)

        # ByLabel family
        lab = self.get("metricLabel")
        tp = sum(1 for p, l in zip(preds, labels) if lab in p and lab in l)
        fp = sum(1 for p, l in zip(preds, labels) if lab in p and lab not in l)
        fn = sum(1 for p, l in zip(preds, labels) if lab not in p and lab in l)
        if metric == "precisionByLabel":
            return tp / max(tp + fp, 1)
        if metric == "recallByLabel":
            return tp / max(tp + fn, 1)
        return 2.0 * tp / max(2 * tp + fp + fn, 1)  # f1MeasureByLabel
