from cycloneml_tpu.ml.evaluation.evaluators import (
    Evaluator, BinaryClassificationEvaluator, MulticlassClassificationEvaluator,
    MultilabelClassificationEvaluator,
    RegressionEvaluator, ClusteringEvaluator, RankingEvaluator,
)

__all__ = ["Evaluator", "BinaryClassificationEvaluator",
           "MulticlassClassificationEvaluator",
           "MultilabelClassificationEvaluator", "RegressionEvaluator",
           "ClusteringEvaluator", "RankingEvaluator"]
