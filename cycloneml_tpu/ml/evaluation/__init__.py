from cycloneml_tpu.ml.evaluation.evaluators import (
    Evaluator, BinaryClassificationEvaluator, MulticlassClassificationEvaluator,
    RegressionEvaluator, ClusteringEvaluator, RankingEvaluator,
)

__all__ = ["Evaluator", "BinaryClassificationEvaluator",
           "MulticlassClassificationEvaluator", "RegressionEvaluator",
           "ClusteringEvaluator", "RankingEvaluator"]
