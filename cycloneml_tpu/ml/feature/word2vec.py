"""Word2Vec — skip-gram embeddings.

Re-design of the reference's distributed skip-gram
(ref: mllib/feature/Word2Vec.scala:73, wrapped by ml/feature/Word2Vec.scala).
The reference uses hierarchical softmax with per-partition weight updates
merged by averaging; that scheme exists because a JVM cluster cannot batch a
softmax over the MXU. Here training is skip-gram with NEGATIVE SAMPLING
(Mikolov et al. 2013b — same embedding quality class) as one jit-compiled
step over device-resident (center, context, negatives) batches: the batched
sigmoid dot-products are MXU matmuls. API parity: vectorSize, windowSize,
minCount, maxIter, find_synonyms, getVectors, transform = average of word
vectors (exactly the reference's transform semantics).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import HasMaxIter, HasSeed
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class _W2VParams(_InOutCol, HasMaxIter, HasSeed):
    def _p_w2v(self):
        self._p_in_out(in_default="tokens", out_default="vector")
        self._p_max_iter(1)
        self._p_seed(17)
        self.vectorSize = self._param("vectorSize", "embedding size (> 0)",
                                      V.gt(0), default=100)
        self.windowSize = self._param("windowSize", "context window (> 0)",
                                      V.gt(0), default=5)
        self.minCount = self._param("minCount", "min word frequency",
                                    V.gt_eq(0), default=5)
        self.stepSize = self._param("stepSize", "learning rate (> 0)",
                                    V.gt(0.0), default=0.025)
        self.negative = self._param("negative", "negative samples per pair",
                                    V.gt(0), default=5)
        self.maxSentenceLength = self._param("maxSentenceLength",
                                             "sentence truncation", V.gt(0),
                                             default=1000)
        # "ns" (default, negative sampling — the TPU-native batched form) or
        # "hs" (hierarchical softmax over a Huffman tree, the reference's
        # exact objective, Word2Vec.scala:73 createBinaryTree — loss curves
        # become comparable with the reference/word2vec.c)
        self.solver = self._param("solver", "ns | hs",
                                  V.in_array(["ns", "hs"]), default="ns")


class Word2Vec(Estimator, _W2VParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_w2v()
        for k, v in kw.items():
            self.set(k, v)

    def set_vector_size(self, v):
        return self.set("vectorSize", v)

    def _fit(self, frame) -> "Word2VecModel":
        import jax
        import jax.numpy as jnp

        sentences = [list(map(str, s))[: self.get("maxSentenceLength")]
                     for s in frame[self.get("inputCol")]]
        min_count = self.get("minCount")
        counts: dict = {}
        for s in sentences:
            for w in s:
                counts[w] = counts.get(w, 0) + 1
        vocab = sorted((w for w, c in counts.items() if c >= min_count),
                       key=lambda w: (-counts[w], w))
        if not vocab:
            raise ValueError(f"no words with count >= {min_count}")
        index = {w: i for i, w in enumerate(vocab)}
        n_vocab = len(vocab)
        dim = self.get("vectorSize")
        window = self.get("windowSize")

        # build (center, context) pairs on host
        centers, contexts = [], []
        for s in sentences:
            ids = [index[w] for w in s if w in index]
            for i, c in enumerate(ids):
                for j in range(max(0, i - window), min(len(ids), i + window + 1)):
                    if j != i:
                        centers.append(c)
                        contexts.append(ids[j])
        if not centers:
            raise ValueError("no training pairs (sentences too short?)")
        centers = np.asarray(centers, np.int32)
        contexts = np.asarray(contexts, np.int32)

        rng = np.random.RandomState(self.get("seed"))
        w_in = jnp.asarray(
            (rng.rand(n_vocab, dim) - 0.5) / dim, dtype=jnp.float32)
        if self.get("solver") == "hs":
            return self._fit_hs(vocab, counts, centers, contexts, w_in, rng)

        # unigram^(3/4) negative-sampling table
        freq = np.array([counts[w] for w in vocab], dtype=np.float64) ** 0.75
        neg_probs = jnp.asarray(freq / freq.sum(), dtype=jnp.float32)

        w_out = jnp.zeros((n_vocab, dim), dtype=jnp.float32)
        n_neg = self.get("negative")
        lr = self.get("stepSize")

        @jax.jit
        def step(w_in, w_out, c_idx, ctx_idx, neg_idx):
            vc = w_in[c_idx]                                   # (b, dim)
            vo = w_out[ctx_idx]                                # (b, dim)
            vn = w_out[neg_idx]                                # (b, k, dim)
            pos_score = jax.nn.sigmoid(jnp.sum(vc * vo, axis=1))
            neg_score = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", vc, vn))
            g_pos = (pos_score - 1.0)[:, None]                 # d/dvc of -log σ
            g_neg = neg_score[:, :, None]
            d_vc = g_pos * vo + jnp.sum(g_neg * vn, axis=1)
            d_vo = g_pos * vc
            d_vn = g_neg * vc[:, None, :]
            w_in = w_in.at[c_idx].add(-lr * d_vc)
            w_out = w_out.at[ctx_idx].add(-lr * d_vo)
            w_out = w_out.at[neg_idx.reshape(-1)].add(
                -lr * d_vn.reshape(-1, vc.shape[1]))
            return w_in, w_out

        batch = 8192
        n_pairs = len(centers)
        key = jax.random.PRNGKey(self.get("seed"))
        for _epoch in range(self.get("maxIter")):
            perm = rng.permutation(n_pairs)
            for s0 in range(0, n_pairs, batch):
                sel = perm[s0: s0 + batch]
                key, sub = jax.random.split(key)
                negs = jax.random.choice(sub, n_vocab,
                                         shape=(len(sel), n_neg), p=neg_probs)
                w_in, w_out = step(w_in, w_out,
                                   jnp.asarray(centers[sel]),
                                   jnp.asarray(contexts[sel]), negs)

        vectors = np.asarray(w_in, dtype=np.float64)
        m = Word2VecModel(vocab, vectors, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)

    def _fit_hs(self, vocab, counts, centers, contexts, w_in, rng):
        """Hierarchical-softmax skip-gram (the reference's exact objective,
        Word2Vec.scala:73): a Huffman tree over word frequencies gives each
        word a root path of inner nodes + branch bits; each (center,
        context) pair updates the CONTEXT word's input vector against the
        CENTER word's path (word2vec.c / the reference's orientation). All
        path updates for a batch run as one jitted gather/scatter program —
        the per-pair inner loop of the reference becomes an (b, L, dim)
        einsum. Per-epoch mean loss is recorded on the model
        (``training_loss_``) so curves are comparable with word2vec.c/
        gensim hs runs."""
        import jax
        import jax.numpy as jnp

        n_vocab = len(vocab)
        dim = self.get("vectorSize")
        lr = self.get("stepSize")
        points, codes, lengths = _huffman_paths(
            np.array([counts[w] for w in vocab], dtype=np.int64))
        L = points.shape[1]
        pts = jnp.asarray(points)               # (V, L) inner-node ids
        cds = jnp.asarray(codes, jnp.float32)   # (V, L) branch bits
        msk = jnp.asarray(
            np.arange(L)[None, :] < lengths[:, None], jnp.float32)
        w_node = jnp.zeros((max(n_vocab - 1, 1), dim), jnp.float32)

        @jax.jit
        def step(w_in, w_node, c_idx, ctx_idx):
            vin = w_in[ctx_idx]                        # (b, dim)
            nodes = pts[c_idx]                         # (b, L)
            code = cds[c_idx]
            mask = msk[c_idx]
            vn = w_node[nodes]                         # (b, L, dim)
            dot = jnp.einsum("bd,bld->bl", vin, vn)
            score = jax.nn.sigmoid(dot)
            # word2vec.c: g = (1 - code - sigmoid(dot)); here as gradient of
            # -log sigma((1-2*code) * dot)
            g = (score - (1.0 - code)) * mask          # (b, L)
            d_vin = jnp.einsum("bl,bld->bd", g, vn)
            d_vn = g[:, :, None] * vin[:, None, :]
            w_in = w_in.at[ctx_idx].add(-lr * d_vin)
            w_node = w_node.at[nodes.reshape(-1)].add(
                -lr * d_vn.reshape(-1, vin.shape[1]))
            sign = 1.0 - 2.0 * code
            loss = -jnp.sum(mask * jax.nn.log_sigmoid(sign * dot))
            return w_in, w_node, loss

        batch = 8192
        n_pairs = len(centers)
        loss_history = []
        for _epoch in range(self.get("maxIter")):
            perm = rng.permutation(n_pairs)
            total = 0.0
            for s0 in range(0, n_pairs, batch):
                sel = perm[s0: s0 + batch]
                w_in, w_node, loss = step(w_in, w_node,
                                          jnp.asarray(centers[sel]),
                                          jnp.asarray(contexts[sel]))
                total += float(loss)
            loss_history.append(total / n_pairs)

        m = Word2VecModel(vocab, np.asarray(w_in, dtype=np.float64),
                          uid=self.uid)
        m.training_loss_ = loss_history
        self._copy_values(m)
        return m._set_parent(self)


def _huffman_paths(freqs: np.ndarray):
    """Huffman tree over word frequencies (ref createBinaryTree,
    Word2Vec.scala / word2vec.c CreateBinaryTree): returns
    ``(points (V, L) int32, codes (V, L) int8, lengths (V,))`` — for word w,
    ``points[w, :len]`` are the inner-node ids on the root→leaf path and
    ``codes[w, :len]`` the branch bits taken. Unused slots point at node 0
    with mask 0 (neutral under the masked update)."""
    import heapq
    v = len(freqs)
    if v == 1:
        return (np.zeros((1, 1), np.int32), np.zeros((1, 1), np.int8),
                np.ones(1, np.int64))
    # nodes 0..v-1 = leaves; v..2v-2 = inner nodes in creation order
    heap = [(int(f), i) for i, f in enumerate(freqs)]
    heapq.heapify(heap)
    parent = np.zeros(2 * v - 1, np.int64)
    branch = np.zeros(2 * v - 1, np.int8)
    nxt = v
    while len(heap) > 1:
        f1, n1 = heapq.heappop(heap)
        f2, n2 = heapq.heappop(heap)
        parent[n1], parent[n2] = nxt, nxt
        branch[n2] = 1  # the heavier/second pop takes the 1-branch
        heapq.heappush(heap, (f1 + f2, nxt))
        nxt += 1
    root = nxt - 1
    lengths = np.zeros(v, np.int64)
    paths, codes_l = [], []
    for w in range(v):
        path, code = [], []
        node = w
        while node != root:
            code.append(int(branch[node]))
            node = parent[node]
            path.append(node - v)  # inner-node id in [0, v-1)
        path.reverse()
        code.reverse()
        paths.append(path)
        codes_l.append(code)
        lengths[w] = len(path)
    L = int(lengths.max())
    points = np.zeros((v, L), np.int32)
    codes = np.zeros((v, L), np.int8)
    for w in range(v):
        points[w, :lengths[w]] = paths[w]
        codes[w, :lengths[w]] = codes_l[w]
    return points, codes, lengths


class Word2VecModel(Model, _W2VParams, MLWritable, MLReadable):
    def __init__(self, vocabulary: Optional[List[str]] = None,
                 vectors: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._p_w2v()
        self.vocabulary = list(vocabulary or [])
        self.vectors = np.asarray(vectors) if vectors is not None else None
        self._index = {w: i for i, w in enumerate(self.vocabulary)}

    def get_vectors(self) -> MLFrame:
        from cycloneml_tpu.context import CycloneContext
        return MLFrame(CycloneContext.get_or_create(), {
            "word": np.asarray(self.vocabulary, dtype=object),
            "vector": self.vectors})

    def _transform(self, frame):
        """Document vector = mean of word vectors (ref Word2VecModel.transform)."""
        dim = self.vectors.shape[1]
        col = frame[self.get("inputCol")]
        out = np.zeros((len(col), dim))
        for i, toks in enumerate(col):
            idxs = [self._index[str(t)] for t in toks if str(t) in self._index]
            if idxs:
                out[i] = self.vectors[idxs].mean(axis=0)
        return frame.with_column(self.get("outputCol"), out)

    def find_synonyms(self, word: str, num: int) -> List[Tuple[str, float]]:
        if word not in self._index:
            raise KeyError(f"word {word!r} not in vocabulary")
        v = self.vectors[self._index[word]]
        return self._find_by_vector(v, num, exclude=word)

    def find_synonyms_by_vector(self, vector: np.ndarray, num: int):
        return self._find_by_vector(np.asarray(vector), num)

    def _find_by_vector(self, v, num, exclude=None):
        norms = np.linalg.norm(self.vectors, axis=1) * max(np.linalg.norm(v), 1e-12)
        sims = self.vectors @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocabulary[i]
            if w != exclude:
                out.append((w, float(sims[i])))
            if len(out) >= num:
                break
        return out

    def _save_data(self, path):
        import json
        import os
        save_arrays(path, vectors=self.vectors)
        with open(os.path.join(path, "vocabulary.json"), "w") as fh:
            json.dump(list(self.vocabulary), fh)

    def _load_data(self, path, meta):
        import json
        import os
        self.vectors = load_arrays(path)["vectors"]
        with open(os.path.join(path, "vocabulary.json")) as fh:
            self.vocabulary = json.load(fh)
        self._index = {w: i for i, w in enumerate(self.vocabulary)}
