from cycloneml_tpu.ml.feature.scalers import (
    StandardScaler, StandardScalerModel, MinMaxScaler, MinMaxScalerModel,
    MaxAbsScaler, MaxAbsScalerModel, RobustScaler, RobustScalerModel,
    Normalizer,
)
from cycloneml_tpu.ml.feature.transforms import (
    Binarizer, Bucketizer, ElementwiseProduct, PolynomialExpansion, DCT,
    VectorAssembler, VectorSlicer, VectorSizeHint, Interaction,
    QuantileDiscretizer, Imputer, ImputerModel,
)
from cycloneml_tpu.ml.feature.text import (
    Tokenizer, RegexTokenizer, StopWordsRemover, NGram, HashingTF, IDF,
    IDFModel, CountVectorizer, CountVectorizerModel, FeatureHasher,
)
from cycloneml_tpu.ml.feature.indexers import (
    StringIndexer, StringIndexerModel, IndexToString, OneHotEncoder,
    OneHotEncoderModel, VectorIndexer, VectorIndexerModel,
)
from cycloneml_tpu.ml.feature.selectors import (
    ChiSqSelector, ChiSqSelectorModel, VarianceThresholdSelector,
    VarianceThresholdSelectorModel, UnivariateFeatureSelector,
    UnivariateFeatureSelectorModel,
)
from cycloneml_tpu.ml.feature.pca import PCA, PCAModel
from cycloneml_tpu.ml.feature.lsh import (
    MinHashLSH, MinHashLSHModel, BucketedRandomProjectionLSH,
    BucketedRandomProjectionLSHModel,
)
from cycloneml_tpu.ml.feature.word2vec import Word2Vec, Word2VecModel
from cycloneml_tpu.ml.feature.formula import (RFormula, RFormulaModel,
                                              SQLTransformer)

__all__ = [
    "StandardScaler", "StandardScalerModel", "MinMaxScaler", "MinMaxScalerModel",
    "MaxAbsScaler", "MaxAbsScalerModel", "RobustScaler", "RobustScalerModel",
    "Normalizer", "Binarizer", "Bucketizer", "ElementwiseProduct",
    "PolynomialExpansion", "DCT", "VectorAssembler", "VectorSlicer",
    "VectorSizeHint", "Interaction", "QuantileDiscretizer", "Imputer",
    "ImputerModel", "Tokenizer", "RegexTokenizer", "StopWordsRemover", "NGram",
    "HashingTF", "IDF", "IDFModel", "CountVectorizer", "CountVectorizerModel",
    "FeatureHasher", "StringIndexer", "StringIndexerModel", "IndexToString",
    "OneHotEncoder", "OneHotEncoderModel", "VectorIndexer", "VectorIndexerModel",
    "ChiSqSelector", "ChiSqSelectorModel", "VarianceThresholdSelector",
    "VarianceThresholdSelectorModel", "UnivariateFeatureSelector",
    "UnivariateFeatureSelectorModel", "PCA", "PCAModel", "MinHashLSH",
    "MinHashLSHModel", "BucketedRandomProjectionLSH",
    "BucketedRandomProjectionLSHModel", "Word2Vec", "Word2VecModel",
    "RFormula", "RFormulaModel", "SQLTransformer",
]
