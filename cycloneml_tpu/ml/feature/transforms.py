"""Stateless & simple fitted vector transforms.

Parity with ref ml/feature: Binarizer.scala, Bucketizer.scala,
ElementwiseProduct.scala, PolynomialExpansion.scala, DCT.scala,
VectorAssembler.scala, VectorSlicer.scala, VectorSizeHint.scala,
Interaction.scala, QuantileDiscretizer.scala, Imputer.scala.
"""

from __future__ import annotations

from itertools import combinations_with_replacement
from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model, Transformer
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class Binarizer(Transformer, _InOutCol, MLWritable, MLReadable):
    """x > threshold → 1.0 else 0.0 (ref Binarizer.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="binarized")
        self.threshold = self._param("threshold", "binarization threshold",
                                     default=0.0)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        x = frame[self.get("inputCol")]
        return frame.with_column(self.get("outputCol"),
                                 (x > self.get("threshold")).astype(np.float64))


class Bucketizer(Transformer, _InOutCol, MLWritable, MLReadable):
    """Map continuous values to bucket indices by split points
    (ref Bucketizer.scala): splits define [s_i, s_{i+1}) buckets, last bucket
    closed; values outside raise unless handleInvalid=keep (extra bucket)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="bucketed")
        self.splits = self._param("splits", "bucket split points (ascending)",
                                  V.array_length_gt(2))
        self.handleInvalid = self._param(
            "handleInvalid", "error|keep|skip for out-of-range",
            V.in_array(["error", "keep", "skip"]), default="error")
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        splits = np.asarray(self.get("splits"), dtype=np.float64)
        x = np.asarray(frame[self.get("inputCol")], dtype=np.float64)
        idx = np.searchsorted(splits, x, side="right") - 1
        idx = np.where(x == splits[-1], len(splits) - 2, idx)  # closed last
        invalid = (x < splits[0]) | (x > splits[-1]) | np.isnan(x)
        mode = self.get("handleInvalid")
        if mode == "error":
            if invalid.any():
                raise ValueError("values outside bucketizer splits; set "
                                 "handleInvalid to keep or skip")
        elif mode == "keep":
            idx = np.where(invalid, len(splits) - 1, idx)
        out = frame.with_column(self.get("outputCol"), idx.astype(np.float64))
        if mode == "skip":
            out = out.filter_rows(~invalid)
        return out


class ElementwiseProduct(Transformer, _InOutCol, MLWritable, MLReadable):
    """Hadamard product with a fixed vector (ref ElementwiseProduct.scala)."""

    def __init__(self, uid=None, scaling_vec=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="product")
        self.scalingVec = self._param("scalingVec", "the multiplier vector")
        if scaling_vec is not None:
            self.set("scalingVec", list(np.asarray(scaling_vec, dtype=np.float64)))
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        v = np.asarray(self.get("scalingVec"), dtype=np.float64)
        return frame.with_column(self.get("outputCol"),
                                 self._in(frame) * v[None, :])


class PolynomialExpansion(Transformer, _InOutCol, MLWritable, MLReadable):
    """Degree-d polynomial feature expansion (ref PolynomialExpansion.scala:
    same term set — all monomials of total degree 1..d, no bias term)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="poly")
        self.degree = self._param("degree", "polynomial degree (> 0)", V.gt(0),
                                  default=2)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        x = self._in(frame).astype(np.float64)
        d = x.shape[1]
        deg = self.get("degree")
        cols = []
        for total in range(1, deg + 1):
            for combo in combinations_with_replacement(range(d), total):
                term = np.ones(x.shape[0])
                for j in combo:
                    term = term * x[:, j]
                cols.append(term)
        return frame.with_column(self.get("outputCol"), np.stack(cols, axis=1))


class DCT(Transformer, _InOutCol, MLWritable, MLReadable):
    """DCT-II per row (ref DCT.scala, which wraps the same scaled transform)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="dct")
        self.inverse = self._param("inverse", "apply inverse DCT", default=False)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        from scipy.fft import dct, idct
        x = self._in(frame).astype(np.float64)
        fn = idct if self.get("inverse") else dct
        return frame.with_column(self.get("outputCol"),
                                 fn(x, type=2, norm="ortho", axis=1))


class VectorAssembler(Transformer, MLWritable, MLReadable):
    """Concatenate columns into one vector column (ref VectorAssembler.scala)."""

    def __init__(self, uid=None, input_cols: Optional[List[str]] = None,
                 output_col: str = "features", **kw):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "columns to assemble")
        self.outputCol = self._param("outputCol", "output column",
                                     default="features")
        if input_cols is not None:
            self.set("inputCols", list(input_cols))
        if output_col != "features":
            self.set("outputCol", output_col)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        parts = []
        for c in self.get("inputCols"):
            col = frame[c]
            parts.append(col[:, None] if col.ndim == 1 else col)
        return frame.with_column(self.get("outputCol"),
                                 np.hstack(parts).astype(np.float64))


class VectorSlicer(Transformer, _InOutCol, MLWritable, MLReadable):
    """Select sub-vector by indices (ref VectorSlicer.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="sliced")
        self.indices = self._param("indices", "indices to keep")
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        idx = np.asarray(self.get("indices"), dtype=np.int64)
        return frame.with_column(self.get("outputCol"), self._in(frame)[:, idx])


class VectorSizeHint(Transformer, _InOutCol, MLWritable, MLReadable):
    """Validate/declare vector size (ref VectorSizeHint.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out()
        self.size = self._param("size", "expected vector size (> 0)", V.gt(0))
        self.handleInvalid = self._param(
            "handleInvalid", "error|skip", V.in_array(["error", "skip"]),
            default="error")
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        x = self._in(frame)
        if x.shape[1] != self.get("size"):
            if self.get("handleInvalid") == "error":
                raise ValueError(
                    f"column {self.get('inputCol')!r} has size {x.shape[1]}, "
                    f"expected {self.get('size')}")
            return frame.filter_rows(np.zeros(frame.n_rows, dtype=bool))
        return frame


class Interaction(Transformer, MLWritable, MLReadable):
    """Pairwise products across columns (ref Interaction.scala: the output is
    the flattened outer product of the input vectors)."""

    def __init__(self, uid=None, input_cols: Optional[List[str]] = None, **kw):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "columns to interact")
        self.outputCol = self._param("outputCol", "output column",
                                     default="interacted")
        if input_cols is not None:
            self.set("inputCols", list(input_cols))
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        cols = []
        for c in self.get("inputCols"):
            col = frame[c]
            cols.append(col[:, None] if col.ndim == 1 else col)
        out = cols[0]
        for c in cols[1:]:
            out = (out[:, :, None] * c[:, None, :]).reshape(out.shape[0], -1)
        return frame.with_column(self.get("outputCol"), out)


class QuantileDiscretizer(Estimator, _InOutCol, MLWritable, MLReadable):
    """Fit bucket splits at quantiles, producing a Bucketizer
    (ref QuantileDiscretizer.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="bucketed")
        self.numBuckets = self._param("numBuckets", "number of buckets (> 1)",
                                      V.gt(1), default=2)
        self.handleInvalid = self._param(
            "handleInvalid", "error|keep|skip", V.in_array(["error", "keep", "skip"]),
            default="error")
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> Bucketizer:
        x = np.asarray(frame[self.get("inputCol")], dtype=np.float64)
        qs = np.linspace(0, 1, self.get("numBuckets") + 1)
        splits = np.unique(np.quantile(x, qs))
        splits[0], splits[-1] = -np.inf, np.inf
        if len(splits) < 3:
            splits = np.array([-np.inf, np.median(x), np.inf])
        b = Bucketizer(uid=self.uid)
        b.set("splits", splits.tolist())
        b.set("inputCol", self.get("inputCol"))
        b.set("outputCol", self.get("outputCol"))
        b.set("handleInvalid", self.get("handleInvalid"))
        return b


class Imputer(Estimator, MLWritable, MLReadable):
    """Fill missing values with mean/median/mode (ref Imputer.scala)."""

    def __init__(self, uid=None, input_cols=None, output_cols=None, **kw):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "columns to impute")
        self.outputCols = self._param("outputCols", "imputed output columns")
        self.strategy = self._param("strategy", "mean|median|mode",
                                    V.in_array(["mean", "median", "mode"]),
                                    default="mean")
        self.missingValue = self._param("missingValue",
                                        "placeholder for missing (besides NaN)",
                                        default=float("nan"))
        if input_cols is not None:
            self.set("inputCols", list(input_cols))
        if output_cols is not None:
            self.set("outputCols", list(output_cols))
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "ImputerModel":
        strat = self.get("strategy")
        mv = self.get("missingValue")
        fills = []
        for c in self.get("inputCols"):
            col = np.asarray(frame[c], dtype=np.float64)
            mask = ~(np.isnan(col) | (col == mv))
            vals = col[mask]
            if len(vals) == 0:
                raise ValueError(f"all values missing in column {c!r}")
            if strat == "mean":
                fills.append(float(vals.mean()))
            elif strat == "median":
                fills.append(float(np.median(vals)))
            else:
                uniq, cnt = np.unique(vals, return_counts=True)
                fills.append(float(uniq[np.argmax(cnt)]))
        m = ImputerModel(np.asarray(fills), uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class ImputerModel(Model, MLWritable, MLReadable):
    def __init__(self, fill_values=None, uid=None):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "columns to impute")
        self.outputCols = self._param("outputCols", "imputed output columns")
        self.strategy = self._param("strategy", "mean|median|mode",
                                    default="mean")
        self.missingValue = self._param("missingValue", "missing placeholder",
                                        default=float("nan"))
        self.fill_values = np.asarray(fill_values) if fill_values is not None else None

    def _transform(self, frame):
        out = frame
        mv = self.get("missingValue")
        for c_in, c_out, fill in zip(self.get("inputCols"),
                                     self.get("outputCols"), self.fill_values):
            col = np.asarray(frame[c_in], dtype=np.float64).copy()
            mask = np.isnan(col) | (col == mv)
            col[mask] = fill
            out = out.with_column(c_out, col)
        return out

    def _save_data(self, path):
        save_arrays(path, fills=self.fill_values)

    def _load_data(self, path, meta):
        self.fill_values = load_arrays(path)["fills"]
