"""Feature scalers.

Parity with the reference's scaler family (ref: ml/feature/StandardScaler.scala,
MinMaxScaler.scala, MaxAbsScaler.scala, RobustScaler.scala, Normalizer.scala).
Fit statistics come from the one-pass device Summarizer (psum); transform is
vectorized numpy on the frame columns (host-side — scaling a column the user
will immediately re-blockify does not warrant a device round-trip).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model, Transformer
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import Params
from cycloneml_tpu.ml.stat import Summarizer
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class _InOutCol(Params):
    def _p_in_out(self, in_default="features", out_default="scaled"):
        self.inputCol = self._param("inputCol", "input column", default=in_default)
        self.outputCol = self._param("outputCol", "output column", default=out_default)

    def set_input_col(self, v):
        return self.set("inputCol", v)

    def set_output_col(self, v):
        return self.set("outputCol", v)

    def _in(self, frame: MLFrame) -> np.ndarray:
        x = frame[self.get("inputCol")]
        return x[:, None] if x.ndim == 1 else x


class StandardScaler(Estimator, _InOutCol, MLWritable, MLReadable):
    """(ref StandardScaler.scala): withMean (centering) default False,
    withStd default True; std uses the unbiased formula."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out()
        self.withMean = self._param("withMean", "center before scaling", default=False)
        self.withStd = self._param("withStd", "scale to unit std", default=True)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "StandardScalerModel":
        ds = frame.to_instance_dataset(self.get("inputCol"), label_col=None)
        s = Summarizer.summarize(ds)
        m = StandardScalerModel(s.mean, s.std, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class StandardScalerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, mean: Optional[np.ndarray] = None,
                 std: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._p_in_out()
        self.withMean = self._param("withMean", "center before scaling", default=False)
        self.withStd = self._param("withStd", "scale to unit std", default=True)
        self.mean = np.asarray(mean) if mean is not None else None
        self.std = np.asarray(std) if std is not None else None

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = self._in(frame).astype(np.float64)
        if self.get("withMean"):
            x = x - self.mean[None, :]
        if self.get("withStd"):
            safe = np.where(self.std > 0, self.std, 1.0)
            x = x / safe[None, :]
        return frame.with_column(self.get("outputCol"), x)

    def _save_data(self, path):
        save_arrays(path, mean=self.mean, std=self.std)

    def _load_data(self, path, meta):
        a = load_arrays(path)
        self.mean, self.std = a["mean"], a["std"]


class MinMaxScaler(Estimator, _InOutCol, MLWritable, MLReadable):
    """(ref MinMaxScaler.scala): rescale to [min,max]; constant features map
    to the range midpoint, as the reference does."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out()
        self.minParam = self._param("min", "lower range bound", default=0.0)
        self.maxParam = self._param("max", "upper range bound", default=1.0)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "MinMaxScalerModel":
        ds = frame.to_instance_dataset(self.get("inputCol"), label_col=None)
        s = Summarizer.summarize(ds)
        m = MinMaxScalerModel(s.min, s.max, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class MinMaxScalerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, data_min=None, data_max=None, uid=None):
        super().__init__(uid)
        self._p_in_out()
        self.minParam = self._param("min", "lower range bound", default=0.0)
        self.maxParam = self._param("max", "upper range bound", default=1.0)
        self.data_min = np.asarray(data_min) if data_min is not None else None
        self.data_max = np.asarray(data_max) if data_max is not None else None

    def _transform(self, frame: MLFrame) -> MLFrame:
        lo, hi = self.get("min"), self.get("max")
        x = self._in(frame).astype(np.float64)
        rng = self.data_max - self.data_min
        const = rng == 0
        scale = np.where(const, 0.0, (hi - lo) / np.where(const, 1.0, rng))
        out = (x - self.data_min[None, :]) * scale[None, :] + lo
        out[:, const] = 0.5 * (hi + lo)
        return frame.with_column(self.get("outputCol"), out)

    def _save_data(self, path):
        save_arrays(path, mn=self.data_min, mx=self.data_max)

    def _load_data(self, path, meta):
        a = load_arrays(path)
        self.data_min, self.data_max = a["mn"], a["mx"]


class MaxAbsScaler(Estimator, _InOutCol, MLWritable, MLReadable):
    """(ref MaxAbsScaler.scala): divide by per-feature max |x|."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out()
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "MaxAbsScalerModel":
        ds = frame.to_instance_dataset(self.get("inputCol"), label_col=None)
        s = Summarizer.summarize(ds)
        max_abs = np.maximum(np.abs(s.max), np.abs(s.min))
        m = MaxAbsScalerModel(max_abs, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class MaxAbsScalerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, max_abs=None, uid=None):
        super().__init__(uid)
        self._p_in_out()
        self.max_abs = np.asarray(max_abs) if max_abs is not None else None

    def _transform(self, frame: MLFrame) -> MLFrame:
        safe = np.where(self.max_abs > 0, self.max_abs, 1.0)
        return frame.with_column(self.get("outputCol"),
                                 self._in(frame) / safe[None, :])

    def _save_data(self, path):
        save_arrays(path, ma=self.max_abs)

    def _load_data(self, path, meta):
        self.max_abs = load_arrays(path)["ma"]


class RobustScaler(Estimator, _InOutCol, MLWritable, MLReadable):
    """(ref RobustScaler.scala): center by median, scale by IQR (quantiles via
    host percentile on the gathered column — the reference uses approximate
    QuantileSummaries; exact is affordable here and strictly more accurate)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out()
        self.withCentering = self._param("withCentering", "subtract median",
                                         default=False)
        self.withScaling = self._param("withScaling", "divide by IQR", default=True)
        self.lower = self._param("lower", "lower quantile",
                                 V.in_range(0, 1, False, False), default=0.25)
        self.upper = self._param("upper", "upper quantile",
                                 V.in_range(0, 1, False, False), default=0.75)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "RobustScalerModel":
        x = self._in(frame)
        med = np.median(x, axis=0)
        q_lo = np.quantile(x, self.get("lower"), axis=0)
        q_hi = np.quantile(x, self.get("upper"), axis=0)
        m = RobustScalerModel(med, q_hi - q_lo, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class RobustScalerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, median=None, iqr=None, uid=None):
        super().__init__(uid)
        self._p_in_out()
        self.withCentering = self._param("withCentering", "subtract median",
                                         default=False)
        self.withScaling = self._param("withScaling", "divide by IQR", default=True)
        self.median = np.asarray(median) if median is not None else None
        self.iqr = np.asarray(iqr) if iqr is not None else None

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = self._in(frame).astype(np.float64)
        if self.get("withCentering"):
            x = x - self.median[None, :]
        if self.get("withScaling"):
            safe = np.where(self.iqr > 0, self.iqr, 1.0)
            x = x / safe[None, :]
        return frame.with_column(self.get("outputCol"), x)

    def _save_data(self, path):
        save_arrays(path, med=self.median, iqr=self.iqr)

    def _load_data(self, path, meta):
        a = load_arrays(path)
        self.median, self.iqr = a["med"], a["iqr"]


class Normalizer(Transformer, _InOutCol, MLWritable, MLReadable):
    """Row p-norm normalization (ref Normalizer.scala), stateless."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out()
        self.p = self._param("p", "norm order (>= 1)", V.gt_eq(1.0), default=2.0)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame: MLFrame) -> MLFrame:
        x = self._in(frame).astype(np.float64)
        p = self.get("p")
        if np.isinf(p):
            norms = np.abs(x).max(axis=1)
        else:
            norms = (np.abs(x) ** p).sum(axis=1) ** (1.0 / p)
        safe = np.where(norms > 0, norms, 1.0)
        return frame.with_column(self.get("outputCol"), x / safe[:, None])
