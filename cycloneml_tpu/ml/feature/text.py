"""Text feature transformers.

Parity with ref ml/feature: Tokenizer.scala, RegexTokenizer.scala,
StopWordsRemover.scala, NGram.scala, HashingTF.scala, IDF.scala,
CountVectorizer.scala, FeatureHasher.scala. Text columns are object arrays of
python lists/strings; term-frequency outputs are dense (n, numFeatures) —
sparse rows densify at the frame boundary by design (SURVEY §7 sparse note).
"""

from __future__ import annotations

import re
from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model, Transformer
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays

# the reference's default english stop words (ref StopWordsRemover loads
# from its resource file; this is the standard english list)
ENGLISH_STOP_WORDS = frozenset("""a about above after again against all am an and any are aren't as at be
because been before being below between both but by can't cannot could couldn't did didn't do does
doesn't doing don't down during each few for from further had hadn't has hasn't have haven't having he
he'd he'll he's her here here's hers herself him himself his how how's i i'd i'll i'm i've if in into
is isn't it it's its itself let's me more most mustn't my myself no nor not of off on once only or
other ought our ours ourselves out over own same shan't she she'd she'll she's should shouldn't so
some such than that that's the their theirs them themselves then there there's these they they'd
they'll they're they've this those through to too under until up very was wasn't we we'd we'll we're
we've were weren't what what's when when's where where's which while who who's whom why why's with
won't would wouldn't you you'd you'll you're you've your yours yourself yourselves""".split())


def _hash_token(token: str, num_features: int) -> int:
    """Deterministic non-cryptographic hash (murmur-style mixing of utf-8
    bytes; the reference uses murmur3_32 — deterministic across runs is the
    contract that matters)."""
    h = 0
    for b in token.encode("utf-8"):
        h = (h * 31 + b) & 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    return h % num_features


class Tokenizer(Transformer, _InOutCol, MLWritable, MLReadable):
    """Lowercase whitespace tokenizer (ref Tokenizer.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="text", out_default="tokens")
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        col = frame[self.get("inputCol")]
        toks = np.empty(len(col), dtype=object)
        for i, s in enumerate(col):
            toks[i] = str(s).lower().split()
        return frame.with_column(self.get("outputCol"), toks)


class RegexTokenizer(Transformer, _InOutCol, MLWritable, MLReadable):
    """Regex tokenizer (ref RegexTokenizer.scala): pattern is the split
    regex when gaps=True (default), else the match regex."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="text", out_default="tokens")
        self.pattern = self._param("pattern", "regex pattern", default=r"\s+")
        self.gaps = self._param("gaps", "pattern matches gaps vs tokens",
                                default=True)
        self.minTokenLength = self._param("minTokenLength",
                                          "minimum token length", V.gt_eq(0),
                                          default=1)
        self.toLowercase = self._param("toLowercase", "lowercase first",
                                       default=True)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        pat = re.compile(self.get("pattern"))
        gaps = self.get("gaps")
        min_len = self.get("minTokenLength")
        lower = self.get("toLowercase")
        col = frame[self.get("inputCol")]
        toks = np.empty(len(col), dtype=object)
        for i, s in enumerate(col):
            s = str(s).lower() if lower else str(s)
            parts = pat.split(s) if gaps else pat.findall(s)
            toks[i] = [t for t in parts if len(t) >= min_len]
        return frame.with_column(self.get("outputCol"), toks)


class StopWordsRemover(Transformer, _InOutCol, MLWritable, MLReadable):
    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="tokens", out_default="filtered")
        self.stopWords = self._param("stopWords", "words to remove",
                                     default=sorted(ENGLISH_STOP_WORDS))
        self.caseSensitive = self._param("caseSensitive", "case sensitive match",
                                         default=False)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        cs = self.get("caseSensitive")
        stops = set(self.get("stopWords")) if cs else \
            {w.lower() for w in self.get("stopWords")}
        col = frame[self.get("inputCol")]
        out = np.empty(len(col), dtype=object)
        for i, toks in enumerate(col):
            out[i] = [t for t in toks
                      if (t if cs else t.lower()) not in stops]
        return frame.with_column(self.get("outputCol"), out)


class NGram(Transformer, _InOutCol, MLWritable, MLReadable):
    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="tokens", out_default="ngrams")
        self.n = self._param("n", "ngram length (> 0)", V.gt(0), default=2)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        n = self.get("n")
        col = frame[self.get("inputCol")]
        out = np.empty(len(col), dtype=object)
        for i, toks in enumerate(col):
            out[i] = [" ".join(toks[j:j + n]) for j in range(len(toks) - n + 1)]
        return frame.with_column(self.get("outputCol"), out)


class HashingTF(Transformer, _InOutCol, MLWritable, MLReadable):
    """Hashed term frequencies (ref HashingTF.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="tokens", out_default="tf")
        # the reference defaults to 2^18 with SPARSE output; ours is dense,
        # so the default is 2^10 — set numFeatures explicitly for big vocabs
        self.numFeatures = self._param("numFeatures", "hash buckets (> 0)",
                                       V.gt(0), default=1 << 10)
        self.binary = self._param("binary", "binary term counts", default=False)
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        nf = self.get("numFeatures")
        binary = self.get("binary")
        col = frame[self.get("inputCol")]
        out = np.zeros((len(col), nf))
        for i, toks in enumerate(col):
            for t in toks:
                j = _hash_token(str(t), nf)
                if binary:
                    out[i, j] = 1.0
                else:
                    out[i, j] += 1.0
        return frame.with_column(self.get("outputCol"), out)


class IDF(Estimator, _InOutCol, MLWritable, MLReadable):
    """Inverse document frequency (ref IDF.scala): idf = log((m+1)/(df+1))."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="tf", out_default="tfidf")
        self.minDocFreq = self._param("minDocFreq", "minimum document frequency",
                                      V.gt_eq(0), default=0)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "IDFModel":
        x = self._in(frame)
        m = x.shape[0]
        df = (x > 0).sum(axis=0).astype(np.float64)
        idf = np.log((m + 1.0) / (df + 1.0))
        idf[df < self.get("minDocFreq")] = 0.0
        model = IDFModel(idf, df, m, uid=self.uid)
        self._copy_values(model)
        return model._set_parent(self)


class IDFModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, idf=None, doc_freq=None, num_docs=0, uid=None):
        super().__init__(uid)
        self._p_in_out(in_default="tf", out_default="tfidf")
        self.minDocFreq = self._param("minDocFreq", "minimum document frequency",
                                      default=0)
        self.idf = np.asarray(idf) if idf is not None else None
        self.doc_freq = np.asarray(doc_freq) if doc_freq is not None else None
        self.num_docs = num_docs

    def _transform(self, frame):
        return frame.with_column(self.get("outputCol"),
                                 self._in(frame) * self.idf[None, :])

    def _save_data(self, path):
        save_arrays(path, idf=self.idf, df=self.doc_freq,
                    nd=np.array(self.num_docs))

    def _load_data(self, path, meta):
        a = load_arrays(path)
        self.idf, self.doc_freq, self.num_docs = a["idf"], a["df"], int(a["nd"])


class CountVectorizer(Estimator, _InOutCol, MLWritable, MLReadable):
    """Vocabulary-based term counts (ref CountVectorizer.scala): vocab ordered
    by descending corpus frequency, capped at vocabSize, filtered by minDF."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="tokens", out_default="counts")
        self.vocabSize = self._param("vocabSize", "max vocabulary size (> 0)",
                                     V.gt(0), default=1 << 18)
        self.minDF = self._param("minDF", "min documents a term appears in",
                                 V.gt_eq(0.0), default=1.0)
        self.minTF = self._param("minTF", "min in-document frequency",
                                 V.gt_eq(0.0), default=1.0)
        self.binary = self._param("binary", "binary counts", default=False)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "CountVectorizerModel":
        col = frame[self.get("inputCol")]
        n_docs = len(col)
        min_df = self.get("minDF")
        if min_df < 1.0:
            min_df = min_df * n_docs
        df: dict = {}
        tf: dict = {}
        for toks in col:
            seen = set()
            for t in toks:
                t = str(t)
                tf[t] = tf.get(t, 0) + 1
                if t not in seen:
                    seen.add(t)
                    df[t] = df.get(t, 0) + 1
        terms = [t for t in tf if df[t] >= min_df]
        terms.sort(key=lambda t: (-tf[t], t))
        vocab = terms[: self.get("vocabSize")]
        m = CountVectorizerModel(vocab, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class CountVectorizerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, vocabulary: Optional[List[str]] = None, uid=None):
        super().__init__(uid)
        self._p_in_out(in_default="tokens", out_default="counts")
        self.vocabSize = self._param("vocabSize", "max vocabulary size",
                                     default=1 << 18)
        self.minDF = self._param("minDF", "min document frequency", default=1.0)
        self.minTF = self._param("minTF", "min in-document term frequency",
                                 default=1.0)
        self.binary = self._param("binary", "binary counts", default=False)
        self.vocabulary = list(vocabulary or [])
        self._index = {t: i for i, t in enumerate(self.vocabulary)}

    def _transform(self, frame):
        col = frame[self.get("inputCol")]
        out = np.zeros((len(col), len(self.vocabulary)))
        min_tf = self.get("minTF")
        binary = self.get("binary")
        for i, toks in enumerate(col):
            counts: dict = {}
            for t in toks:
                j = self._index.get(str(t))
                if j is not None:
                    counts[j] = counts.get(j, 0) + 1
            thresh = min_tf if min_tf >= 1.0 else min_tf * len(toks)
            for j, c in counts.items():
                if c >= thresh:
                    out[i, j] = 1.0 if binary else c
        return frame.with_column(self.get("outputCol"), out)

    def _save_data(self, path):
        import json
        import os
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "vocabulary.json"), "w") as fh:
            json.dump(list(self.vocabulary), fh)

    def _load_data(self, path, meta):
        import json
        import os
        with open(os.path.join(path, "vocabulary.json")) as fh:
            self.vocabulary = json.load(fh)
        self._index = {t: i for i, t in enumerate(self.vocabulary)}


class FeatureHasher(Transformer, MLWritable, MLReadable):
    """Hash arbitrary columns into one feature vector (ref FeatureHasher.scala):
    numeric columns hash their NAME with the value as weight; string columns
    hash name=value with weight 1."""

    def __init__(self, uid=None, input_cols=None, **kw):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "columns to hash")
        self.outputCol = self._param("outputCol", "output column",
                                     default="features")
        self.numFeatures = self._param("numFeatures", "hash buckets (> 0)",
                                       V.gt(0), default=1 << 10)  # dense output
        if input_cols is not None:
            self.set("inputCols", list(input_cols))
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        nf = self.get("numFeatures")
        cols = self.get("inputCols")
        out = np.zeros((frame.n_rows, nf))
        for c in cols:
            col = frame[c]
            numeric = np.issubdtype(np.asarray(col).dtype, np.number)
            if numeric:
                j = _hash_token(c, nf)
                out[:, j] += np.asarray(col, dtype=np.float64)
            else:
                for i, v in enumerate(col):
                    out[i, _hash_token(f"{c}={v}", nf)] += 1.0
        return frame.with_column(self.get("outputCol"), out)
