"""Categorical indexers & encoders.

Parity with ref ml/feature: StringIndexer.scala, IndexToString,
OneHotEncoder.scala, VectorIndexer.scala.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model, Transformer
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


def ordered_labels(col, order: str = "frequencyDesc"):
    """Category ordering shared by StringIndexer and RFormula (ref:
    StringIndexer.scala stringOrderType — frequencyDesc ties break
    lexicographically)."""
    uniq, counts = np.unique(col, return_counts=True)
    if order == "frequencyDesc":
        idx = np.lexsort((uniq, -counts))
    elif order == "frequencyAsc":
        idx = np.lexsort((uniq, counts))
    elif order == "alphabetAsc":
        idx = np.argsort(uniq)
    else:
        idx = np.argsort(uniq)[::-1]
    return [str(u) for u in uniq[idx]]


class StringIndexer(Estimator, _InOutCol, MLWritable, MLReadable):
    """Map strings to indices by descending frequency (ref StringIndexer.scala;
    orderType variants supported)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="category", out_default="categoryIndex")
        self.handleInvalid = self._param(
            "handleInvalid", "error|skip|keep for unseen labels",
            V.in_array(["error", "skip", "keep"]), default="error")
        self.stringOrderType = self._param(
            "stringOrderType", "label ordering",
            V.in_array(["frequencyDesc", "frequencyAsc", "alphabetDesc",
                        "alphabetAsc"]), default="frequencyDesc")
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "StringIndexerModel":
        col = [str(v) for v in frame[self.get("inputCol")]]
        labels = ordered_labels(col, self.get("stringOrderType"))
        m = StringIndexerModel(labels, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class StringIndexerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, labels: Optional[List[str]] = None, uid=None):
        super().__init__(uid)
        self._p_in_out(in_default="category", out_default="categoryIndex")
        self.handleInvalid = self._param("handleInvalid", "error|skip|keep",
                                         default="error")
        self.labels = list(labels or [])
        self._index = {l: i for i, l in enumerate(self.labels)}

    def _transform(self, frame):
        col = frame[self.get("inputCol")]
        mode = self.get("handleInvalid")
        out = np.empty(len(col))
        invalid = np.zeros(len(col), dtype=bool)
        for i, v in enumerate(col):
            j = self._index.get(str(v))
            if j is None:
                invalid[i] = True
                out[i] = len(self.labels)  # 'keep' bucket
            else:
                out[i] = j
        if invalid.any():
            if mode == "error":
                bad = sorted({str(col[i]) for i in np.nonzero(invalid)[0]})
                raise ValueError(f"unseen labels {bad[:5]}; set handleInvalid")
            if mode == "skip":
                return frame.filter_rows(~invalid).with_column(
                    self.get("outputCol"), out[~invalid])
        return frame.with_column(self.get("outputCol"), out)

    def _save_data(self, path):
        with open(os.path.join(path, "labels.json"), "w") as fh:
            json.dump(self.labels, fh)

    def _load_data(self, path, meta):
        with open(os.path.join(path, "labels.json")) as fh:
            self.labels = json.load(fh)
        self._index = {l: i for i, l in enumerate(self.labels)}


class IndexToString(Transformer, _InOutCol, MLWritable, MLReadable):
    """Inverse of StringIndexer (ref StringIndexer.scala IndexToString)."""

    def __init__(self, uid=None, labels: Optional[List[str]] = None, **kw):
        super().__init__(uid)
        self._p_in_out(in_default="categoryIndex", out_default="category")
        self.labelsParam = self._param("labels", "index → label mapping")
        if labels is not None:
            self.set("labels", list(labels))
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame):
        labels = self.get("labels")
        col = np.asarray(frame[self.get("inputCol")]).astype(int)
        out = np.array([labels[i] for i in col], dtype=object)
        return frame.with_column(self.get("outputCol"), out)


class OneHotEncoder(Estimator, MLWritable, MLReadable):
    """Index → one-hot vector (ref OneHotEncoder.scala): dropLast=True by
    default, so the last category maps to the zero vector."""

    def __init__(self, uid=None, input_cols=None, output_cols=None, **kw):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "index columns")
        self.outputCols = self._param("outputCols", "encoded columns")
        self.dropLast = self._param("dropLast", "drop last category", default=True)
        self.handleInvalid = self._param(
            "handleInvalid", "error|keep", V.in_array(["error", "keep"]),
            default="error")
        if input_cols is not None:
            self.set("inputCols", list(input_cols))
        if output_cols is not None:
            self.set("outputCols", list(output_cols))
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "OneHotEncoderModel":
        sizes = []
        for c in self.get("inputCols"):
            col = np.asarray(frame[c]).astype(int)
            sizes.append(int(col.max()) + 1 if len(col) else 0)
        m = OneHotEncoderModel(sizes, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class OneHotEncoderModel(Model, MLWritable, MLReadable):
    def __init__(self, category_sizes: Optional[List[int]] = None, uid=None):
        super().__init__(uid)
        self.inputCols = self._param("inputCols", "index columns")
        self.outputCols = self._param("outputCols", "encoded columns")
        self.dropLast = self._param("dropLast", "drop last category", default=True)
        self.handleInvalid = self._param("handleInvalid", "error|keep",
                                         default="error")
        self.category_sizes = list(category_sizes or [])

    def _transform(self, frame):
        out = frame
        drop = self.get("dropLast")
        keep = self.get("handleInvalid") == "keep"
        for c_in, c_out, size in zip(self.get("inputCols"),
                                     self.get("outputCols"),
                                     self.category_sizes):
            col = np.asarray(frame[c_in]).astype(int)
            # ref OneHotEncoderModel.configedCategorySize: with keep, an
            # extra "invalid" category at index `size`; dropLast removes it
            # (keep) or the true last category (error)
            if keep:
                width = size + 1 if not drop else size
            else:
                width = size - 1 if drop else size
            invalid = (col < 0) | (col >= size)
            if invalid.any() and not keep:
                raise ValueError(f"index out of range in {c_in!r}")
            eff = np.where(invalid, size, col)
            enc = np.zeros((len(col), max(width, 0)))
            valid = eff < width
            enc[np.nonzero(valid)[0], eff[valid]] = 1.0
            out = out.with_column(c_out, enc)
        return out

    def _save_data(self, path):
        save_arrays(path, sizes=np.asarray(self.category_sizes))

    def _load_data(self, path, meta):
        self.category_sizes = [int(s) for s in load_arrays(path)["sizes"]]


class VectorIndexer(Estimator, _InOutCol, MLWritable, MLReadable):
    """Detect categorical vector slots (≤ maxCategories distinct values) and
    re-index them to [0, k) (ref VectorIndexer.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="indexed")
        self.maxCategories = self._param("maxCategories",
                                         "max distinct values to treat as "
                                         "categorical (> 1)", V.gt(1), default=20)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "VectorIndexerModel":
        x = self._in(frame)
        max_cat = self.get("maxCategories")
        category_maps = {}
        for j in range(x.shape[1]):
            uniq = np.unique(x[:, j])
            if len(uniq) <= max_cat:
                category_maps[j] = {float(v): i for i, v in enumerate(sorted(uniq))}
        m = VectorIndexerModel(x.shape[1], category_maps, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class VectorIndexerModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, num_features: int = 0, category_maps=None, uid=None):
        super().__init__(uid)
        self._p_in_out(out_default="indexed")
        self.maxCategories = self._param("maxCategories", "max categories",
                                         default=20)
        self.num_features = num_features
        self.category_maps = category_maps or {}

    @property
    def category_feature_indices(self):
        return sorted(self.category_maps)

    def _transform(self, frame):
        x = self._in(frame).astype(np.float64).copy()
        for j, mapping in self.category_maps.items():
            col = x[:, j]
            x[:, j] = np.array([mapping.get(float(v), -1.0) for v in col])
        return frame.with_column(self.get("outputCol"), x)

    def _save_data(self, path):
        payload = {str(j): {str(k): v for k, v in m.items()}
                   for j, m in self.category_maps.items()}
        with open(os.path.join(path, "maps.json"), "w") as fh:
            json.dump({"num_features": self.num_features, "maps": payload}, fh)

    def _load_data(self, path, meta):
        with open(os.path.join(path, "maps.json")) as fh:
            d = json.load(fh)
        self.num_features = d["num_features"]
        self.category_maps = {int(j): {float(k): v for k, v in m.items()}
                              for j, m in d["maps"].items()}
