"""PCA transformer (ref ml/feature/PCA.scala — delegates to RowMatrix
computePrincipalComponents, as does this)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.linalg.distributed import RowMatrix
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class PCA(Estimator, _InOutCol, MLWritable, MLReadable):
    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="pca")
        self.k = self._param("k", "number of components (> 0)", V.gt(0))
        for key, v in kw.items():
            self.set(key, v)

    def set_k(self, v):
        return self.set("k", v)

    def _fit(self, frame) -> "PCAModel":
        ds = frame.to_instance_dataset(self.get("inputCol"), label_col=None)
        rm = RowMatrix(ds)
        pcs, var = rm.compute_principal_components_and_variance(self.get("k"))
        m = PCAModel(pcs.to_array(), var.to_array(), uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class PCAModel(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, pc: Optional[np.ndarray] = None,
                 explained_variance: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._p_in_out(out_default="pca")
        self.k = self._param("k", "number of components", default=1)
        self.pc = np.asarray(pc) if pc is not None else None
        self.explained_variance = (np.asarray(explained_variance)
                                   if explained_variance is not None else None)

    def _transform(self, frame):
        return frame.with_column(self.get("outputCol"),
                                 self._in(frame) @ self.pc)

    def _save_data(self, path):
        save_arrays(path, pc=self.pc, ev=self.explained_variance)

    def _load_data(self, path, meta):
        a = load_arrays(path)
        self.pc, self.explained_variance = a["pc"], a["ev"]
