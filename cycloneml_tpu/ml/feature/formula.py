"""RFormula and SQLTransformer — the last two reference feature transformers.

RFormula (ref: ml/feature/RFormula.scala + RFormulaParser.scala): an R-style
model formula ``label ~ term + term`` compiled into a feature-assembly
pipeline. Supported grammar (the subset the reference's own docs
illustrate): ``y ~ a + b``, ``y ~ .`` (all non-label columns), ``a:b``
interaction terms, ``y ~ . - c`` exclusion. String columns one-hot encode
with the last category dropped (R's dummy coding, exactly the reference's
behavior); the label string-indexes when categorical.

SQLTransformer (ref: ml/feature/SQLTransformer.scala): runs a SQL statement
with the ``__THIS__`` placeholder bound to the input frame — powered by this
framework's own SQL engine.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable


def _is_string_col(arr: np.ndarray) -> bool:
    return arr.dtype == object or arr.dtype.kind in "US"


def _parse_formula(formula: str) -> Tuple[str, List[str], List[str]]:
    """Returns (label, include_terms, exclude_terms); '.' may appear in
    include_terms; interactions are 'a:b' strings."""
    if "~" not in formula:
        raise ValueError(f"formula needs '~': {formula!r}")
    lhs, rhs = formula.split("~", 1)
    label = lhs.strip()
    include: List[str] = []
    exclude: List[str] = []
    # strict scanner: term, then (+|- term)* — anything else (R operators
    # like '*', '^', '(', or two terms with no operator) must be REJECTED:
    # R's a*b means a + b + a:b, and silently reinterpreting would train on
    # the wrong design matrix
    term_re = r"[\w.]+(?::[\w.]+)*"
    pos = 0
    first = True
    while pos < len(rhs):
        pat = (rf"\s*(?:([+-])\s*)?({term_re})" if first
               else rf"\s*([+-])\s*({term_re})")
        m = re.match(pat, rhs[pos:])
        if m is None:
            break
        sign, term = m.group(1) or "+", m.group(2)
        (exclude if sign == "-" else include).append(term)
        pos += m.end()
        first = False
    residue = rhs[pos:].strip()
    if residue:
        raise ValueError(
            f"unsupported formula syntax at {residue!r} in {formula!r} "
            "(supported: terms joined by '+' or '-', interactions 'a:b', "
            "and '.')")
    if not include:
        raise ValueError(f"formula has no terms: {formula!r}")
    return label, include, exclude


class RFormula(Estimator, MLWritable, MLReadable):
    """(ref RFormula.scala) — fit() resolves '.', indexes string columns,
    and returns an RFormulaModel producing featuresCol (+ labelCol)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.formula = self._param("formula", "R model formula", default="")
        self.featuresCol = self._param("featuresCol", "output features",
                                       default="features")
        self.labelCol = self._param("labelCol", "output label",
                                    default="label")
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "RFormulaModel":
        label, include, exclude = _parse_formula(self.get("formula"))
        cols = [c for c in frame.columns if c != label]
        terms: List[str] = []
        for t in include:
            if t == ".":
                terms.extend(c for c in cols if c not in terms)
            elif t not in terms:
                terms.append(t)
        terms = [t for t in terms if t not in exclude]

        # category dictionaries for string columns (ref: StringIndexer order
        # = descending frequency, ties lexicographic)
        categories: Dict[str, List] = {}
        for t in terms:
            for c in t.split(":"):
                if c in frame.columns and _is_string_col(frame[c]) \
                        and c not in categories:
                    categories[c] = _freq_order(frame[c])
        label_categories: Optional[List] = None
        if label in frame.columns and _is_string_col(frame[label]):
            label_categories = _freq_order(frame[label])

        m = RFormulaModel(terms=terms, label=label, categories=categories,
                          label_categories=label_categories, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


def _freq_order(arr: np.ndarray) -> List[str]:
    # categories are ALWAYS str labels (same rule pre/post persistence),
    # ordered by StringIndexer's shared frequencyDesc logic
    from cycloneml_tpu.ml.feature.indexers import ordered_labels
    return ordered_labels([str(v) for v in arr])


class RFormulaModel(Model, MLWritable, MLReadable):
    def __init__(self, terms: Optional[List[str]] = None, label: str = "",
                 categories: Optional[Dict[str, List]] = None,
                 label_categories: Optional[List] = None, uid=None):
        super().__init__(uid)
        self.formula = self._param("formula", "R model formula", default="")
        self.featuresCol = self._param("featuresCol", "output features",
                                       default="features")
        self.labelCol = self._param("labelCol", "output label",
                                    default="label")
        self.terms = terms or []
        self.label = label
        self.categories = categories or {}
        self.label_categories = label_categories

    @staticmethod
    def _code(lookup: Dict[str, int], v, col: str) -> int:
        try:
            return lookup[str(v)]
        except KeyError:
            raise ValueError(
                f"column {col!r} has category {v!r} unseen at fit time "
                "(ref RFormula handleInvalid='error')") from None

    def _encode_col(self, frame: MLFrame, c: str) -> np.ndarray:
        arr = frame[c]
        if c in self.categories:
            cats = self.categories[c]
            lookup = {v: i for i, v in enumerate(cats)}
            codes = np.array([self._code(lookup, v, c) for v in arr])
            # dummy coding: k-1 columns, last category dropped (ref/R)
            out = np.zeros((len(arr), max(len(cats) - 1, 1)))
            mask = codes < len(cats) - 1
            out[np.arange(len(arr))[mask], codes[mask]] = 1.0
            return out if len(cats) > 1 else out[:, :0]
        a = np.asarray(arr, dtype=np.float64)
        return a[:, None] if a.ndim == 1 else a

    def _transform(self, frame: MLFrame) -> MLFrame:
        parts = []
        for t in self.terms:
            factors = [self._encode_col(frame, c) for c in t.split(":")]
            block = factors[0]
            for f in factors[1:]:  # interaction = pairwise products
                block = (block[:, :, None] * f[:, None, :]).reshape(
                    len(f), -1)
            parts.append(block)
        feats = (np.concatenate(parts, axis=1) if parts
                 else np.zeros((frame.n_rows, 0)))
        out = frame.with_column(self.get("featuresCol"), feats)
        if self.label in frame.columns:
            y = frame[self.label]
            if self.label_categories is not None:
                lookup = {v: i for i, v in enumerate(self.label_categories)}
                y = np.array([float(self._code(lookup, v, self.label))
                              for v in y])
            else:
                y = np.asarray(y, dtype=np.float64)
            out = out.with_column(self.get("labelCol"), y)
        return out

    def _save_data(self, path):
        import json
        import os
        with open(os.path.join(path, "formula.json"), "w") as fh:
            # categories are already str labels (see _freq_order), so JSON
            # round-trips them without changing lookup behavior
            json.dump({"terms": self.terms, "label": self.label,
                       "categories": self.categories,
                       "label_categories": self.label_categories}, fh)

    def _load_data(self, path, meta):
        import json
        import os
        with open(os.path.join(path, "formula.json")) as fh:
            d = json.load(fh)
        self.terms = d["terms"]
        self.label = d["label"]
        self.categories = d["categories"]
        self.label_categories = d["label_categories"]


from cycloneml_tpu.ml.base import Transformer  # noqa: E402 — after Estimator


class SQLTransformer(Transformer, MLWritable, MLReadable):
    """(ref SQLTransformer.scala — extends Transformer so it composes in
    pipelines and persists) — ``SELECT ... FROM __THIS__`` over the frame
    via the built-in SQL engine. Vector (2-D) columns ride through
    projections (aliased or not) as object rows re-stacked on the way out;
    SQL expressions apply to scalar columns."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self.statement = self._param("statement", "SQL statement with the "
                                     "__THIS__ placeholder", default="")
        for k, v in kw.items():
            self.set(k, v)

    def _transform(self, frame: MLFrame) -> MLFrame:
        from cycloneml_tpu.sql.session import CycloneSession
        session = CycloneSession()
        batch = {}
        vector_widths = {}
        for c in frame.columns:
            arr = frame[c]
            if arr.ndim == 2:  # vector column → opaque object rows
                obj = np.empty(arr.shape[0], dtype=object)
                for i in range(arr.shape[0]):
                    obj[i] = arr[i]
                batch[c] = obj
                vector_widths[c] = arr.shape[1]
            else:
                batch[c] = arr
        df = session.create_data_frame(batch)
        # the placeholder IS the temp-view name — no textual substitution
        session.register_temp_view("__THIS__", df)
        out_df = session.sql(self.get("statement"))
        # map OUTPUT names (including aliases of plain vector projections)
        # back to source widths so empty results keep their (0, k) shape
        out_widths = dict(vector_widths)
        for name, src in _projection_sources(out_df.plan).items():
            if src in vector_widths:
                out_widths[name] = vector_widths[src]
        result = out_df.to_dict()
        cols: Dict[str, np.ndarray] = {}
        for name, arr in result.items():
            if arr.dtype == object and len(arr) \
                    and isinstance(arr[0], np.ndarray):
                cols[name] = np.stack(arr)  # any vector projection, aliased too
            elif len(arr) == 0 and name in out_widths:
                cols[name] = np.zeros((0, out_widths[name]))
            else:
                cols[name] = arr
        return MLFrame(frame.ctx, cols)


def _projection_sources(plan) -> Dict[str, str]:
    """output column name → source column name for plain (possibly aliased)
    column projections anywhere in the plan tree."""
    from cycloneml_tpu.sql.column import Alias, ColumnRef
    out: Dict[str, str] = {}
    for e in getattr(plan, "exprs", []) or []:
        base = e.children[0] if isinstance(e, Alias) else e
        if isinstance(base, ColumnRef):
            out[e.name_hint()] = base.name
    for c in plan.children:
        for name, src in _projection_sources(c).items():
            out.setdefault(name, src)
    return out
