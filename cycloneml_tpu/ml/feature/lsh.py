"""Locality-sensitive hashing.

Parity with ref ml/feature/LSH.scala, MinHashLSH.scala,
BucketedRandomProjectionLSH.scala: hash tables, approxNearestNeighbors and
approxSimilarityJoin. Hash evaluation is one vectorized pass (matmul for the
random-projection family — MXU-friendly by construction).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import HasSeed
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays

MINHASH_PRIME = 2038074743  # the reference's prime (MinHashLSH.scala)


class _LSHParams(_InOutCol, HasSeed):
    def _p_lsh(self):
        self._p_in_out(out_default="hashes")
        self._p_seed(17)
        self.numHashTables = self._param("numHashTables", "hash tables (> 0)",
                                         V.gt(0), default=1)


class _LSHModelBase(Model, _LSHParams, MLWritable, MLReadable):
    def _hash_batch(self, x: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _key_distance(self, a: np.ndarray, b: np.ndarray) -> float:
        raise NotImplementedError

    def _transform(self, frame):
        return frame.with_column(self.get("outputCol"),
                                 self._hash_batch(self._in(frame)))

    def approx_nearest_neighbors(self, frame: MLFrame, key: np.ndarray,
                                 num_nearest: int,
                                 dist_col: str = "distCol") -> MLFrame:
        x = self._in(frame)
        hx = self._hash_batch(x)
        hk = self._hash_batch(np.asarray(key, dtype=np.float64)[None, :])[0]
        # candidate filter: any matching hash table, then exact re-rank
        cand = (hx == hk[None, :]).any(axis=1)
        if cand.sum() < num_nearest:
            cand = np.ones(len(x), dtype=bool)
        cand_idx = np.nonzero(cand)[0]
        # exact re-rank over the candidate set only — that's the LSH payoff
        cand_d = np.array([self._key_distance(x[i], key) for i in cand_idx])
        top = np.argsort(cand_d)[:num_nearest]
        keep = np.sort(cand_idx[top])
        dists = np.full(len(x), np.inf)
        dists[cand_idx] = cand_d
        mask = np.isin(np.arange(len(x)), keep)
        return frame.filter_rows(mask).with_column(dist_col, dists[keep])

    def approx_similarity_join(self, a: MLFrame, b: MLFrame, threshold: float,
                               dist_col: str = "distCol"):
        xa, xb = self._in(a), self._in(b)
        ha, hb = self._hash_batch(xa), self._hash_batch(xb)
        pairs = []
        for i in range(len(xa)):
            match = (hb == ha[i][None, :]).any(axis=1)
            for j in np.nonzero(match)[0]:
                d = self._key_distance(xa[i], xb[j])
                if d < threshold:
                    pairs.append((i, j, d))
        ctx = a.ctx
        if not pairs:
            return MLFrame(ctx, {"idA": np.array([], dtype=int),
                                 "idB": np.array([], dtype=int),
                                 dist_col: np.array([])})
        arr = np.array(pairs)
        return MLFrame(ctx, {"idA": arr[:, 0].astype(int),
                             "idB": arr[:, 1].astype(int),
                             dist_col: arr[:, 2]})


class BucketedRandomProjectionLSH(Estimator, _LSHParams, MLWritable, MLReadable):
    """Euclidean LSH: floor(x·v / bucketLength) (ref
    BucketedRandomProjectionLSH.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_lsh()
        self.bucketLength = self._param("bucketLength", "bucket width (> 0)",
                                        V.gt(0.0))
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "BucketedRandomProjectionLSHModel":
        d = self._in(frame).shape[1]
        rng = np.random.RandomState(self.get("seed"))
        dirs = rng.randn(self.get("numHashTables"), d)
        dirs /= np.linalg.norm(dirs, axis=1, keepdims=True)
        m = BucketedRandomProjectionLSHModel(dirs, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class BucketedRandomProjectionLSHModel(_LSHModelBase):
    def __init__(self, directions: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._p_lsh()
        self.bucketLength = self._param("bucketLength", "bucket width",
                                        default=1.0)
        self.directions = np.asarray(directions) if directions is not None else None

    def _hash_batch(self, x):
        proj = x @ self.directions.T / self.get("bucketLength")
        return np.floor(proj)

    def _key_distance(self, a, b):
        return float(np.linalg.norm(a - b))

    def _save_data(self, path):
        save_arrays(path, dirs=self.directions)

    def _load_data(self, path, meta):
        self.directions = load_arrays(path)["dirs"]


class MinHashLSH(Estimator, _LSHParams, MLWritable, MLReadable):
    """Jaccard LSH over binary vectors (ref MinHashLSH.scala): h(x) =
    min over nonzero indices of ((a·i + b) mod prime) per table."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_lsh()
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "MinHashLSHModel":
        rng = np.random.RandomState(self.get("seed"))
        nt = self.get("numHashTables")
        coeff_a = rng.randint(1, MINHASH_PRIME, nt)
        coeff_b = rng.randint(0, MINHASH_PRIME, nt)
        m = MinHashLSHModel(coeff_a, coeff_b, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class MinHashLSHModel(_LSHModelBase):
    def __init__(self, coeff_a=None, coeff_b=None, uid=None):
        super().__init__(uid)
        self._p_lsh()
        self.coeff_a = np.asarray(coeff_a) if coeff_a is not None else None
        self.coeff_b = np.asarray(coeff_b) if coeff_b is not None else None

    def _hash_batch(self, x):
        out = np.empty((x.shape[0], len(self.coeff_a)))
        for i in range(x.shape[0]):
            nz = np.nonzero(x[i])[0]
            if len(nz) == 0:
                raise ValueError("MinHash requires at least one nonzero entry")
            vals = ((np.add.outer(self.coeff_b, (nz + 1) * 0) +
                     np.outer(self.coeff_a, nz + 1)) % MINHASH_PRIME)
            out[i] = vals.min(axis=1)
        return out

    def _key_distance(self, a, b):
        sa, sb = set(np.nonzero(a)[0]), set(np.nonzero(b)[0])
        union = len(sa | sb)
        return 1.0 - (len(sa & sb) / union if union else 0.0)

    def _save_data(self, path):
        save_arrays(path, a=self.coeff_a, b=self.coeff_b)

    def _load_data(self, path, meta):
        arrs = load_arrays(path)
        self.coeff_a, self.coeff_b = arrs["a"], arrs["b"]
