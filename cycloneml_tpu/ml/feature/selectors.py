"""Feature selectors.

Parity with ref ml/feature: ChiSqSelector.scala, VarianceThresholdSelector,
UnivariateFeatureSelector.scala.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.feature.scalers import _InOutCol
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.stat.tests import ANOVATest, ChiSquareTest, FValueTest
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays


class _SelectorModelBase(Model, _InOutCol, MLWritable, MLReadable):
    def __init__(self, selected: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._p_in_out(out_default="selected")
        self.selected = np.asarray(selected, dtype=np.int64) \
            if selected is not None else None

    @property
    def selected_features(self) -> List[int]:
        return [int(i) for i in self.selected]

    def _transform(self, frame):
        return frame.with_column(self.get("outputCol"),
                                 self._in(frame)[:, self.selected])

    def _save_data(self, path):
        save_arrays(path, selected=self.selected)

    def _load_data(self, path, meta):
        self.selected = load_arrays(path)["selected"]


def _select_by_mode(scores: np.ndarray, pvals: np.ndarray, mode: str,
                    param: float) -> np.ndarray:
    d = len(scores)
    order = np.argsort(-scores, kind="stable")
    if mode == "numTopFeatures":
        sel = order[: int(param)]
    elif mode == "percentile":
        sel = order[: max(int(d * param), 1)]
    elif mode == "fpr":
        sel = np.nonzero(pvals < param)[0]
    elif mode == "fdr":
        # Benjamini-Hochberg (ref ChiSqSelector fdr mode)
        ps = np.sort(pvals)
        thresh = param * (np.arange(1, d + 1) / d)
        ok = np.nonzero(ps <= thresh)[0]
        cut = ps[ok[-1]] if len(ok) else -1.0
        sel = np.nonzero(pvals <= cut)[0]
    elif mode == "fwe":
        sel = np.nonzero(pvals < param / d)[0]
    else:
        raise ValueError(f"unknown selector mode {mode}")
    return np.sort(sel)


class _SelectorParams(_InOutCol):
    def _p_selector(self):
        self._p_in_out(out_default="selected")
        self.labelCol = self._param("labelCol", "label column", default="label")
        self.selectorType = self._param(
            "selectorType", "selection mode",
            V.in_array(["numTopFeatures", "percentile", "fpr", "fdr", "fwe"]),
            default="numTopFeatures")
        self.numTopFeatures = self._param("numTopFeatures", "top features",
                                          V.gt(0), default=50)
        self.percentile = self._param("percentile", "fraction to keep",
                                      V.in_range(0, 1), default=0.1)
        self.fpr = self._param("fpr", "false positive rate",
                               V.in_range(0, 1, False, True), default=0.05)
        self.fdr = self._param("fdr", "false discovery rate",
                               V.in_range(0, 1, False, True), default=0.05)
        self.fwe = self._param("fwe", "family-wise error rate",
                               V.in_range(0, 1, False, True), default=0.05)

    def _mode_param(self):
        mode = self.get("selectorType")
        return mode, {
            "numTopFeatures": lambda: self.get("numTopFeatures"),
            "percentile": lambda: self.get("percentile"),
            "fpr": lambda: self.get("fpr"),
            "fdr": lambda: self.get("fdr"),
            "fwe": lambda: self.get("fwe"),
        }[mode]()


class ChiSqSelector(Estimator, _SelectorParams, MLWritable, MLReadable):
    """Chi-squared feature selection (ref ChiSqSelector.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_selector()
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "ChiSqSelectorModel":
        res = ChiSquareTest.test(frame, self.get("inputCol"), self.get("labelCol"))
        mode, param = self._mode_param()
        sel = _select_by_mode(res["statistics"], res["pValues"], mode, param)
        m = ChiSqSelectorModel(sel, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class ChiSqSelectorModel(_SelectorModelBase):
    pass


class VarianceThresholdSelector(Estimator, _InOutCol, MLWritable, MLReadable):
    """Drop features with variance <= threshold (ref
    VarianceThresholdSelector.scala)."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_in_out(out_default="selected")
        self.varianceThreshold = self._param("varianceThreshold",
                                             "variance cutoff", V.gt_eq(0.0),
                                             default=0.0)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "VarianceThresholdSelectorModel":
        x = self._in(frame)
        var = x.var(axis=0, ddof=1)
        sel = np.nonzero(var > self.get("varianceThreshold"))[0]
        m = VarianceThresholdSelectorModel(sel, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class VarianceThresholdSelectorModel(_SelectorModelBase):
    pass


class UnivariateFeatureSelector(Estimator, _SelectorParams, MLWritable, MLReadable):
    """Selector choosing the test by feature/label types
    (ref UnivariateFeatureSelector.scala): categorical/categorical → chi2,
    continuous/categorical → ANOVA F, continuous/continuous → F-value."""

    def __init__(self, uid=None, **kw):
        super().__init__(uid)
        self._p_selector()
        self.featureType = self._param("featureType", "categorical|continuous",
                                       V.in_array(["categorical", "continuous"]),
                                       default="continuous")
        self.labelType = self._param("labelType", "categorical|continuous",
                                     V.in_array(["categorical", "continuous"]),
                                     default="categorical")
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame) -> "UnivariateFeatureSelectorModel":
        ft, lt = self.get("featureType"), self.get("labelType")
        fcol, lcol = self.get("inputCol"), self.get("labelCol")
        if ft == "categorical" and lt == "categorical":
            res = ChiSquareTest.test(frame, fcol, lcol)
            scores = res["statistics"]
        elif ft == "continuous" and lt == "categorical":
            res = ANOVATest.test(frame, fcol, lcol)
            scores = res["fValues"]
        elif ft == "continuous" and lt == "continuous":
            res = FValueTest.test(frame, fcol, lcol)
            scores = res["fValues"]
        else:
            raise ValueError("categorical features with continuous label "
                             "is unsupported (as the reference)")
        mode, param = self._mode_param()
        sel = _select_by_mode(scores, res["pValues"], mode, param)
        m = UnivariateFeatureSelectorModel(sel, uid=self.uid)
        self._copy_values(m)
        return m._set_parent(self)


class UnivariateFeatureSelectorModel(_SelectorModelBase):
    pass
