from cycloneml_tpu.ml.stat.summarizer import Summarizer, SummaryStats
from cycloneml_tpu.ml.stat.tests import (
    ANOVATest, ChiSquareTest, Correlation, FValueTest, KolmogorovSmirnovTest,
)

__all__ = ["Summarizer", "SummaryStats", "ChiSquareTest", "Correlation",
           "KolmogorovSmirnovTest", "ANOVATest", "FValueTest"]
