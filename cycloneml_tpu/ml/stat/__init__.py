from cycloneml_tpu.ml.stat.summarizer import Summarizer, SummaryStats

__all__ = ["Summarizer", "SummaryStats"]
