"""Single-pass multivariate summary statistics.

Replaces ``SummarizerBuffer``/``Summarizer`` (ref: ml/stat/Summarizer.scala:42
metrics list :84, treeAggregate paths :214,232; also
mllib/stat/MultivariateOnlineSummarizer): one jit-compiled psum pass computes
all weighted moments simultaneously — mean, variance (unbiased, weighted, the
reference's formula), count, numNonzeros, max, min, normL1, normL2, sum,
weightSum. Padding rows (w=0) are neutral in every statistic, including
max/min which mask by weight.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset


@dataclass
class SummaryStats:
    mean: np.ndarray
    variance: np.ndarray
    count: int
    num_nonzeros: np.ndarray
    max: np.ndarray
    min: np.ndarray
    norm_l1: np.ndarray
    norm_l2: np.ndarray
    sum: np.ndarray
    weight_sum: float

    @property
    def std(self) -> np.ndarray:
        return np.sqrt(self.variance)


class Summarizer:
    """``Summarizer.metrics("mean","variance",...)`` equivalent; the whole
    moment set always comes from one pass, so no metric selection machinery
    is needed — slice what you want from SummaryStats."""

    @staticmethod
    def summarize(dataset: InstanceDataset) -> SummaryStats:
        # datasets are immutable (transformations derive NEW datasets), so
        # the moment set is a property of the object: cache it, and a
        # re-fit on the same frame-cached dataset (grid search, warmed
        # benchmarks) skips the whole pass — and, through the TPU relay,
        # one ~0.1-0.6 s dispatch round-trip
        cached = getattr(dataset, "_summary_cache", None)
        if cached is not None:
            return cached
        # the aggregation fn is a module-level singleton so the compiled
        # program is shared across calls/fits (collectives program cache)
        agg = dataset.tree_aggregate_fn(_get_moments_fn(), auto_psum=False)
        out = _finalize(agg(), dataset)
        dataset._summary_cache = out
        return out

    @staticmethod
    def mean_std(dataset: InstanceDataset):
        s = Summarizer.summarize(dataset)
        return s.mean, s.std


def _moments(x, y, w):
    import jax.numpy as jnp
    wcol = w[:, None]
    present = (wcol > 0)
    # w carries the ACCUMULATOR dtype (f32/f64 — dataset.blockify keeps
    # y/w at full width even when X stores bf16), so every sum below
    # promotes to it; counts accumulated in a bf16 X's dtype would stop
    # being exact integers at 256 (8 mantissa bits)
    acc = w.dtype
    if str(x.dtype).startswith("float8"):
        # fp8 codes refuse implicit promotion (by design — jax makes the
        # 8-bit cast explicit); the one-shot stats pass upcasts in-graph
        # and _finalize rescales by the stored per-column scales
        x = x.astype(acc)
    s1 = jnp.sum(wcol * x, axis=0)
    s2 = jnp.sum(wcol * x * x, axis=0)
    # sentinels live at ACCUMULATOR width: the fp8 storage tier has no
    # inf (e4m3fn overflows to NaN), and the promoted where/max is exact
    # for every narrower tier anyway
    neg_inf = jnp.asarray(-jnp.inf, acc)
    pos_inf = jnp.asarray(jnp.inf, acc)
    return {
        "s1": s1,
        "s2": s2,
        "w": jnp.sum(w),
        "w2": jnp.sum(w * w),
        "cnt": jnp.sum(present.astype(acc)),
        "nnz": jnp.sum((present & (x != 0)).astype(acc), axis=0),
        "mx": jnp.max(jnp.where(present, x, neg_inf), axis=0),
        "mn": jnp.min(jnp.where(present, x, pos_inf), axis=0),
        "l1": jnp.sum(wcol * jnp.abs(x), axis=0),
    }


_moments_fn = None


def _get_moments_fn():
    global _moments_fn
    if _moments_fn is None:
        _moments_fn = _psum_parts(_moments)
    return _moments_fn


def _psum_parts(moments):
    """Wrap the moment fn so sum-like stats use psum and max/min use pmax/pmin
    (a psum of per-shard maxima would be wrong)."""
    import jax
    import jax.numpy as jnp
    from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS

    def fn(x, y, w):
        parts = moments(x, y, w)
        summed = {}
        for k, v in parts.items():
            if k == "mx":
                r = v
                for ax in (DATA_AXIS, REPLICA_AXIS):
                    r = jax.lax.pmax(r, ax)
            elif k == "mn":
                r = v
                for ax in (DATA_AXIS, REPLICA_AXIS):
                    r = jax.lax.pmin(r, ax)
            else:
                r = v
                for ax in (DATA_AXIS, REPLICA_AXIS):
                    r = jax.lax.psum(r, ax)
            summed[k] = r
        return summed

    return fn


def _finalize(out, dataset: InstanceDataset) -> SummaryStats:
    w = float(out["w"])
    s1 = np.asarray(out["s1"], dtype=np.float64)
    s2 = np.asarray(out["s2"], dtype=np.float64)
    mx = np.asarray(out["mx"], dtype=np.float64)
    mn = np.asarray(out["mn"], dtype=np.float64)
    l1 = np.asarray(out["l1"], dtype=np.float64)
    scale = getattr(dataset, "x_scale", None)
    if scale is not None:
        # fp8 storage tier: the device pass summed e4m3 CODES; every
        # per-column statistic dequantizes by the stored scale on the
        # host — an O(d) rescale, no second data pass. Moments are then
        # the moments OF the quantized values (x8 * scale), which is the
        # self-consistent tier the fit actually trains on. nnz is exact
        # on codes (quantized-to-zero == zero). Scales are positive, so
        # max/min keep their order.
        s1 = s1 * scale
        s2 = s2 * scale * scale
        mx = mx * scale
        mn = mn * scale
        l1 = l1 * scale
    mean = s1 / w
    # unbiased weighted variance — the reference's formula
    # (MultivariateOnlineSummarizer.variance): (s2 - w*mean^2) * w/(w - w2/w)
    denom = w - float(out["w2"]) / w
    if denom > 0:
        variance = np.maximum((s2 - w * mean * mean) / denom, 0.0)
    else:
        variance = np.zeros_like(mean)
    return SummaryStats(
        mean=mean,
        variance=variance,
        count=int(round(float(out["cnt"]))),
        num_nonzeros=np.asarray(out["nnz"], dtype=np.float64),
        max=mx,
        min=mn,
        norm_l1=l1,
        norm_l2=np.sqrt(s2),
        sum=s1,
        weight_sum=w,
    )
