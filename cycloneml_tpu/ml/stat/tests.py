"""Hypothesis tests & correlation.

Parity with ref: ml/stat/ChiSquareTest.scala, KolmogorovSmirnovTest.scala,
ANOVATest.scala, FValueTest.scala, Correlation.scala (pearson/spearman,
mllib/stat/correlation/). Contingency/moment accumulation is vectorized;
p-values from scipy distributions (the reference uses commons-math).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.matrices import DenseMatrix


class ChiSquareTest:
    @staticmethod
    def test(frame: MLFrame, features_col: str, label_col: str) -> Dict[str, np.ndarray]:
        """Pearson chi-squared independence test of each feature vs label
        (ref ChiSquareTest.scala / mllib Statistics.chiSqTest)."""
        from scipy.stats import chi2
        x = frame[features_col]
        if x.ndim == 1:
            x = x[:, None]
        y = np.asarray(frame[label_col])
        d = x.shape[1]
        stats, pvals, dofs = np.zeros(d), np.zeros(d), np.zeros(d, dtype=int)
        y_codes, y_idx = np.unique(y, return_inverse=True)
        for j in range(d):
            f_codes, f_idx = np.unique(x[:, j], return_inverse=True)
            table = np.zeros((len(f_codes), len(y_codes)))
            np.add.at(table, (f_idx, y_idx), 1.0)
            expected = table.sum(1, keepdims=True) * table.sum(0, keepdims=True) / table.sum()
            with np.errstate(divide="ignore", invalid="ignore"):
                contrib = np.where(expected > 0, (table - expected) ** 2 / expected, 0.0)
            stat = float(contrib.sum())
            dof = (table.shape[0] - 1) * (table.shape[1] - 1)
            stats[j] = stat
            dofs[j] = dof
            pvals[j] = float(chi2.sf(stat, dof)) if dof > 0 else 1.0
        return {"pValues": pvals, "statistics": stats, "degreesOfFreedom": dofs}


class KolmogorovSmirnovTest:
    @staticmethod
    def test(frame: MLFrame, sample_col: str, dist: str = "norm",
             *params) -> Dict[str, float]:
        """One-sample two-sided KS test (ref KolmogorovSmirnovTest.scala)."""
        from scipy import stats as ss
        x = np.asarray(frame[sample_col], dtype=np.float64)
        if dist != "norm":
            raise ValueError("only 'norm' is supported (as the reference)")
        loc = params[0] if len(params) >= 1 else 0.0
        scale = params[1] if len(params) >= 2 else 1.0
        stat, p = ss.kstest(x, "norm", args=(loc, scale))
        return {"pValue": float(p), "statistic": float(stat)}


class ANOVATest:
    @staticmethod
    def test(frame: MLFrame, features_col: str, label_col: str) -> Dict[str, np.ndarray]:
        """One-way ANOVA F-test per feature, categorical label
        (ref ANOVATest.scala)."""
        from scipy.stats import f as f_dist
        x = frame[features_col]
        if x.ndim == 1:
            x = x[:, None]
        y = np.asarray(frame[label_col])
        classes = np.unique(y)
        n, d = x.shape
        k = len(classes)
        stats, pvals = np.zeros(d), np.zeros(d)
        grand = x.mean(axis=0)
        ss_between = np.zeros(d)
        ss_within = np.zeros(d)
        for c in classes:
            xc = x[y == c]
            ss_between += len(xc) * (xc.mean(axis=0) - grand) ** 2
            ss_within += ((xc - xc.mean(axis=0)) ** 2).sum(axis=0)
        df1, df2 = k - 1, n - k
        with np.errstate(divide="ignore", invalid="ignore"):
            f_stat = (ss_between / df1) / (ss_within / df2)
        # zero within-group variance with nonzero between = perfect separation
        f_stat = np.where((ss_within == 0) & (ss_between > 0), np.inf, f_stat)
        f_stat = np.where((ss_within == 0) & (ss_between == 0), 0.0, f_stat)
        stats[:] = f_stat
        pvals[:] = f_dist.sf(f_stat, df1, df2)
        return {"pValues": pvals, "fValues": stats,
                "degreesOfFreedom": np.array([df1, df2])}


class FValueTest:
    @staticmethod
    def test(frame: MLFrame, features_col: str, label_col: str) -> Dict[str, np.ndarray]:
        """F-test for regression (continuous label) per feature
        (ref FValueTest.scala)."""
        from scipy.stats import f as f_dist
        x = frame[features_col]
        if x.ndim == 1:
            x = x[:, None]
        y = np.asarray(frame[label_col], dtype=np.float64)
        n, d = x.shape
        xc = x - x.mean(axis=0)
        yc = y - y.mean()
        denom = np.sqrt((xc ** 2).sum(axis=0) * (yc ** 2).sum())
        with np.errstate(divide="ignore", invalid="ignore"):
            r = np.where(denom > 0, xc.T @ yc / denom, 0.0)
        df2 = n - 2
        f_stat = r ** 2 / np.maximum(1 - r ** 2, 1e-300) * df2
        return {"pValues": f_dist.sf(f_stat, 1, df2), "fValues": f_stat,
                "degreesOfFreedom": np.array([1, df2])}


class Correlation:
    @staticmethod
    def corr(frame: MLFrame, col: str, method: str = "pearson") -> DenseMatrix:
        """Feature correlation matrix (ref Correlation.scala; pearson via the
        reference's moment formula, spearman via rank transform then pearson,
        ref mllib/stat/correlation/SpearmanCorrelation.scala)."""
        x = frame[col]
        if x.ndim == 1:
            x = x[:, None]
        x = np.asarray(x, dtype=np.float64)
        if method == "spearman":
            from scipy.stats import rankdata
            x = np.apply_along_axis(rankdata, 0, x)
        elif method != "pearson":
            raise ValueError("method must be pearson or spearman")
        xc = x - x.mean(axis=0)
        cov = xc.T @ xc
        std = np.sqrt(np.maximum(np.diag(cov), 0.0))
        with np.errstate(divide="ignore", invalid="ignore"):
            corr = cov / std[:, None] / std[None, :]
        corr[~np.isfinite(corr)] = np.nan
        np.fill_diagonal(corr, 1.0)
        return DenseMatrix.from_array(corr)
