from cycloneml_tpu.ml.tuning.tuning import (
    ParamGridBuilder, CrossValidator, CrossValidatorModel,
    TrainValidationSplit, TrainValidationSplitModel,
)

__all__ = ["ParamGridBuilder", "CrossValidator", "CrossValidatorModel",
           "TrainValidationSplit", "TrainValidationSplitModel"]
