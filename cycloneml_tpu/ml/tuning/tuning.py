"""Model selection / hyperparameter tuning.

Parity with ref ml/tuning: ParamGridBuilder, CrossValidator.scala:80
(k-fold; ``parallelism`` — setParallelism:119), TrainValidationSplit.scala.

The reference's ``parallelism`` thread pool fanned independent Spark jobs
across a cluster; here every fit is an SPMD program over ONE shared mesh,
so a thread pool deadlocks XLA's collective rendezvous (the PR-2 hang,
now mechanized as graftlint JX007). Instead, ``parallelism > 1`` routes
grid points through the STACKED fit engine when the param maps differ
only in vmappable scalars (regParam) and the estimator supports
``fit_stacked``: all K grid points of one fold train as ONE vmapped SPMD
program — one compile for the whole grid (the stacked chunk program takes
the reg vector as runtime data, so every fold reuses it), one psum per
step carrying K gradients. Heterogeneous maps (structure-changing params,
elastic net, non-binary labels) fall back to the serial loop. See
docs/multi-model.md.
"""

from __future__ import annotations

from itertools import product
from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.param import Param, ParamMap, ParamValidators as V
from cycloneml_tpu.ml.shared import HasSeed
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable


class ParamGridBuilder:
    """(ref ParamGridBuilder in tuning/ParamGridBuilder.scala)."""

    def __init__(self):
        self._grid = {}

    def add_grid(self, param: Param, values) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def base_on(self, param_map: ParamMap) -> "ParamGridBuilder":
        for p, v in param_map.items():
            self._grid[p] = [v]
        return self

    def build(self) -> List[ParamMap]:
        if not self._grid:
            return [ParamMap()]
        keys = list(self._grid)
        out = []
        for combo in product(*(self._grid[k] for k in keys)):
            pm = ParamMap()
            for k, v in zip(keys, combo):
                pm.put(k, v)
            out.append(pm)
        return out


class _ValidatorParams(HasSeed):
    def _p_validator(self):
        self._p_seed(42)
        self.parallelism = self._param("parallelism",
                                       "concurrent fits (>= 1)", V.gt_eq(1),
                                       default=1)

    def set_estimator(self, est: Estimator):
        self._estimator = est
        return self

    def set_estimator_param_maps(self, maps: List[ParamMap]):
        self._param_maps = list(maps)
        return self

    def set_evaluator(self, ev):
        self._evaluator = ev
        return self

    def _fit_score_one(self, pm: ParamMap, train: MLFrame, valid: MLFrame,
                       lane: str = "") -> float:
        """One grid point's fit+score. With a ``lane`` label the work is
        a STRAGGLER LANE (group ``fit.lane``, one position per grid
        point, sampled once per fold/split): its duration feeds the
        online skew detector, and once the lane carries a latched
        verdict the armed speculation layer re-dispatches its next work
        — serially on the between-lanes idle mesh, NOT on a thread (two
        concurrent SPMD programs deadlock the shared mesh's gang
        collectives: mesh.safe_fit_parallelism / graftlint JX007) —
        with first-result-wins and a bitwise dedup of the duplicate
        (elastic/speculation.py)."""
        from cycloneml_tpu.elastic import speculation
        from cycloneml_tpu.observe import skew

        def work() -> float:
            with skew.timed_observe("fit.lane", lane):
                model = self._estimator.fit(train, pm)
                return float(self._evaluator.evaluate(model.transform(valid)))

        if not lane:
            model = self._estimator.fit(train, pm)
            return self._evaluator.evaluate(model.transform(valid))
        return speculation.maybe_speculate("fit.lane", lane, work,
                                           concurrent=False)

    # -- stacked (model-axis) grid evaluation --------------------------------
    def _stack_plan(self, frame: MLFrame):
        """``(base_estimator, reg_vector)`` when the whole grid can train as
        ONE stacked SPMD program per fold: every param map touches the same
        params, only ``regParam`` (a vmappable scalar) varies, the
        estimator supports stacked fits in its configured state, and the
        labels are binary. Anything else returns None — heterogeneous maps
        fall back to the serial path."""
        maps = getattr(self, "_param_maps", None)
        est = getattr(self, "_estimator", None)
        if (not maps or len(maps) < 2 or est is None
                or not hasattr(est, "fit_stacked")):
            return None
        keys = set(maps[0])
        if any(set(pm) != keys for pm in maps[1:]):
            return None
        reg_param = next((p for p in keys if p.name == "regParam"), None)
        if reg_param is None:
            return None

        def differs(a, b):
            # array-valued params (e.g. coefficient bounds) compare
            # elementwise; any doubt means "not provably constant" → serial
            try:
                return bool(np.any(np.asarray(a != b)))
            except Exception:
                return True

        for p in keys:
            if p is reg_param:
                continue
            v0 = maps[0].get(p)
            if any(differs(pm.get(p), v0) for pm in maps[1:]):
                return None  # a non-vmappable param varies across the grid
        base = est.copy(maps[0])
        if not (hasattr(base, "can_fit_stacked") and base.can_fit_stacked()):
            return None
        try:
            y = np.asarray(frame[base.get("labelCol")])
        except Exception:
            return None
        if not np.isin(y, (0.0, 1.0)).all():
            return None  # stacked fits are binomial
        return base, np.array([float(pm.get(reg_param)) for pm in maps])

    def _fit_score_stacked(self, base, reg_vec, train: MLFrame,
                           valid: MLFrame) -> np.ndarray:
        from cycloneml_tpu.elastic import speculation
        from cycloneml_tpu.observe import skew
        models = base.fit_stacked(train, reg_params=reg_vec)
        # the K fits ran as ONE gang program (no per-model fit lane
        # exists); per-model SCORING is host-separable work, so each
        # grid point's scoring is its straggler lane — same group, same
        # re-dispatch semantics as the serial path
        out = []
        for mi, m in enumerate(models):
            lane = f"grid{mi}"

            def work(m=m, lane=lane) -> float:
                with skew.timed_observe("fit.lane", lane):
                    return float(self._evaluator.evaluate(m.transform(valid)))

            out.append(speculation.maybe_speculate("fit.lane", lane, work,
                                                   concurrent=False))
        return np.array(out)


class CrossValidator(Estimator, _ValidatorParams, MLWritable, MLReadable):
    """(ref CrossValidator.scala:80)."""

    def __init__(self, uid=None, estimator=None, estimator_param_maps=None,
                 evaluator=None, **kw):
        super().__init__(uid)
        self._p_validator()
        self.numFolds = self._param("numFolds", "folds (>= 2)", V.gt_eq(2),
                                    default=3)
        self.foldCol = self._param("foldCol", "user-supplied fold column",
                                   default="")
        if estimator is not None:
            self.set_estimator(estimator)
        if estimator_param_maps is not None:
            self.set_estimator_param_maps(estimator_param_maps)
        if evaluator is not None:
            self.set_evaluator(evaluator)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "CrossValidatorModel":
        n_folds = self.get("numFolds")
        fold_col = self.get("foldCol")
        if fold_col:
            folds = np.asarray(frame[fold_col]).astype(int)
        else:
            rng = np.random.RandomState(self.get("seed"))
            folds = rng.randint(0, n_folds, frame.n_rows)
        maps = self._param_maps
        metrics = np.zeros(len(maps))
        from cycloneml_tpu.mesh import safe_fit_parallelism
        requested = self.get("parallelism")
        plan = self._stack_plan(frame) if requested > 1 else None
        if plan is not None:
            base, reg_vec = plan
            safe_fit_parallelism(requested, stacked_width=len(maps))
            for f in range(n_folds):
                train = frame.filter_rows(folds != f)
                valid = frame.filter_rows(folds == f)
                metrics += self._fit_score_stacked(base, reg_vec,
                                                   train, valid)
        else:
            # serial fallback: SPMD fits stay on this thread (a >1 thread
            # pool deadlocks the shared mesh — mesh.safe_fit_parallelism)
            safe_fit_parallelism(requested)
            for f in range(n_folds):
                train = frame.filter_rows(folds != f)
                valid = frame.filter_rows(folds == f)
                for mi, pm in enumerate(maps):
                    metrics[mi] += self._fit_score_one(pm, train, valid,
                                                       lane=f"grid{mi}")
        metrics /= n_folds
        best_idx = int(np.argmax(metrics) if self._evaluator.is_larger_better
                       else np.argmin(metrics))
        best = self._estimator.fit(frame, maps[best_idx])
        model = CrossValidatorModel(best, metrics.tolist(), uid=self.uid)
        self._copy_values(model)
        return model._set_parent(self)


class CrossValidatorModel(Model, _ValidatorParams, MLWritable, MLReadable):
    def __init__(self, best_model: Optional[Model] = None,
                 avg_metrics: Optional[List[float]] = None, uid=None):
        super().__init__(uid)
        self._p_validator()
        self.numFolds = self._param("numFolds", "folds", default=3)
        self.foldCol = self._param("foldCol", "fold column", default="")
        self.best_model = best_model
        self.avg_metrics = list(avg_metrics or [])

    def _transform(self, frame):
        return self.best_model.transform(frame)

    def _save_data(self, path):
        import json, os
        self.best_model.save(os.path.join(path, "bestModel"), overwrite=True)
        with open(os.path.join(path, "metrics.json"), "w") as fh:
            json.dump(self.avg_metrics, fh)

    def _load_data(self, path, meta):
        import json, os
        from cycloneml_tpu.ml.util_io import instantiate_from_metadata, load_metadata
        bp = os.path.join(path, "bestModel")
        bm_meta = load_metadata(bp)
        self.best_model = instantiate_from_metadata(bm_meta)
        self.best_model._load_data(bp, bm_meta)
        with open(os.path.join(path, "metrics.json")) as fh:
            self.avg_metrics = json.load(fh)


class TrainValidationSplit(Estimator, _ValidatorParams, MLWritable, MLReadable):
    """(ref TrainValidationSplit.scala)."""

    def __init__(self, uid=None, estimator=None, estimator_param_maps=None,
                 evaluator=None, **kw):
        super().__init__(uid)
        self._p_validator()
        self.trainRatio = self._param("trainRatio", "train fraction",
                                      V.in_range(0, 1, False, False),
                                      default=0.75)
        if estimator is not None:
            self.set_estimator(estimator)
        if estimator_param_maps is not None:
            self.set_estimator_param_maps(estimator_param_maps)
        if evaluator is not None:
            self.set_evaluator(evaluator)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "TrainValidationSplitModel":
        rng = np.random.RandomState(self.get("seed"))
        mask = rng.rand(frame.n_rows) < self.get("trainRatio")
        train, valid = frame.filter_rows(mask), frame.filter_rows(~mask)
        maps = self._param_maps
        from cycloneml_tpu.mesh import safe_fit_parallelism
        requested = self.get("parallelism")
        plan = self._stack_plan(frame) if requested > 1 else None
        if plan is not None:
            base, reg_vec = plan
            safe_fit_parallelism(requested, stacked_width=len(maps))
            metrics = self._fit_score_stacked(base, reg_vec, train, valid)
        else:
            safe_fit_parallelism(requested)
            metrics = np.asarray(
                [self._fit_score_one(pm, train, valid, lane=f"grid{mi}")
                 for mi, pm in enumerate(maps)])
        best_idx = int(np.argmax(metrics) if self._evaluator.is_larger_better
                       else np.argmin(metrics))
        best = self._estimator.fit(frame, maps[best_idx])
        model = TrainValidationSplitModel(best, metrics.tolist(), uid=self.uid)
        self._copy_values(model)
        return model._set_parent(self)


class TrainValidationSplitModel(CrossValidatorModel):
    def __init__(self, best_model=None, validation_metrics=None, uid=None):
        super().__init__(best_model, validation_metrics, uid=uid)
        self.trainRatio = self._param("trainRatio", "train fraction",
                                      default=0.75)

    @property
    def validation_metrics(self):
        # property, not an alias: _load_data rebinds avg_metrics after init
        return self.avg_metrics
