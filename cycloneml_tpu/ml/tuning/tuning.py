"""Model selection / hyperparameter tuning.

Parity with ref ml/tuning: ParamGridBuilder, CrossValidator.scala:80
(k-fold, fits folds concurrently via a thread pool sized by ``parallelism``
— setParallelism:119; same here), TrainValidationSplit.scala.
"""

from __future__ import annotations

import concurrent.futures as cf
from itertools import product
from typing import List, Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.param import Param, ParamMap, ParamValidators as V
from cycloneml_tpu.ml.shared import HasSeed
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable


class ParamGridBuilder:
    """(ref ParamGridBuilder in tuning/ParamGridBuilder.scala)."""

    def __init__(self):
        self._grid = {}

    def add_grid(self, param: Param, values) -> "ParamGridBuilder":
        self._grid[param] = list(values)
        return self

    def base_on(self, param_map: ParamMap) -> "ParamGridBuilder":
        for p, v in param_map.items():
            self._grid[p] = [v]
        return self

    def build(self) -> List[ParamMap]:
        if not self._grid:
            return [ParamMap()]
        keys = list(self._grid)
        out = []
        for combo in product(*(self._grid[k] for k in keys)):
            pm = ParamMap()
            for k, v in zip(keys, combo):
                pm.put(k, v)
            out.append(pm)
        return out


class _ValidatorParams(HasSeed):
    def _p_validator(self):
        self._p_seed(42)
        self.parallelism = self._param("parallelism",
                                       "concurrent fits (>= 1)", V.gt_eq(1),
                                       default=1)

    def set_estimator(self, est: Estimator):
        self._estimator = est
        return self

    def set_estimator_param_maps(self, maps: List[ParamMap]):
        self._param_maps = list(maps)
        return self

    def set_evaluator(self, ev):
        self._evaluator = ev
        return self

    def _fit_score_one(self, pm: ParamMap, train: MLFrame, valid: MLFrame) -> float:
        model = self._estimator.fit(train, pm)
        return self._evaluator.evaluate(model.transform(valid))


class CrossValidator(Estimator, _ValidatorParams, MLWritable, MLReadable):
    """(ref CrossValidator.scala:80)."""

    def __init__(self, uid=None, estimator=None, estimator_param_maps=None,
                 evaluator=None, **kw):
        super().__init__(uid)
        self._p_validator()
        self.numFolds = self._param("numFolds", "folds (>= 2)", V.gt_eq(2),
                                    default=3)
        self.foldCol = self._param("foldCol", "user-supplied fold column",
                                   default="")
        if estimator is not None:
            self.set_estimator(estimator)
        if estimator_param_maps is not None:
            self.set_estimator_param_maps(estimator_param_maps)
        if evaluator is not None:
            self.set_evaluator(evaluator)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "CrossValidatorModel":
        n_folds = self.get("numFolds")
        fold_col = self.get("foldCol")
        if fold_col:
            folds = np.asarray(frame[fold_col]).astype(int)
        else:
            rng = np.random.RandomState(self.get("seed"))
            folds = rng.randint(0, n_folds, frame.n_rows)
        maps = self._param_maps
        metrics = np.zeros(len(maps))
        jobs = []
        for f in range(n_folds):
            train = frame.filter_rows(folds != f)
            valid = frame.filter_rows(folds == f)
            for mi, pm in enumerate(maps):
                jobs.append((mi, pm, train, valid))
        from cycloneml_tpu.mesh import safe_fit_parallelism
        par = safe_fit_parallelism(self.get("parallelism"))
        if par > 1:
            with cf.ThreadPoolExecutor(max_workers=par) as pool:
                results = list(pool.map(
                    lambda j: (j[0], self._fit_score_one(j[1], j[2], j[3])), jobs))
        else:
            results = [(mi, self._fit_score_one(pm, tr, va))
                       for mi, pm, tr, va in jobs]
        for mi, score in results:
            metrics[mi] += score
        metrics /= n_folds
        best_idx = int(np.argmax(metrics) if self._evaluator.is_larger_better
                       else np.argmin(metrics))
        best = self._estimator.fit(frame, maps[best_idx])
        model = CrossValidatorModel(best, metrics.tolist(), uid=self.uid)
        self._copy_values(model)
        return model._set_parent(self)


class CrossValidatorModel(Model, _ValidatorParams, MLWritable, MLReadable):
    def __init__(self, best_model: Optional[Model] = None,
                 avg_metrics: Optional[List[float]] = None, uid=None):
        super().__init__(uid)
        self._p_validator()
        self.numFolds = self._param("numFolds", "folds", default=3)
        self.foldCol = self._param("foldCol", "fold column", default="")
        self.best_model = best_model
        self.avg_metrics = list(avg_metrics or [])

    def _transform(self, frame):
        return self.best_model.transform(frame)

    def _save_data(self, path):
        import json, os
        self.best_model.save(os.path.join(path, "bestModel"), overwrite=True)
        with open(os.path.join(path, "metrics.json"), "w") as fh:
            json.dump(self.avg_metrics, fh)

    def _load_data(self, path, meta):
        import json, os
        from cycloneml_tpu.ml.util_io import instantiate_from_metadata, load_metadata
        bp = os.path.join(path, "bestModel")
        bm_meta = load_metadata(bp)
        self.best_model = instantiate_from_metadata(bm_meta)
        self.best_model._load_data(bp, bm_meta)
        with open(os.path.join(path, "metrics.json")) as fh:
            self.avg_metrics = json.load(fh)


class TrainValidationSplit(Estimator, _ValidatorParams, MLWritable, MLReadable):
    """(ref TrainValidationSplit.scala)."""

    def __init__(self, uid=None, estimator=None, estimator_param_maps=None,
                 evaluator=None, **kw):
        super().__init__(uid)
        self._p_validator()
        self.trainRatio = self._param("trainRatio", "train fraction",
                                      V.in_range(0, 1, False, False),
                                      default=0.75)
        if estimator is not None:
            self.set_estimator(estimator)
        if estimator_param_maps is not None:
            self.set_estimator_param_maps(estimator_param_maps)
        if evaluator is not None:
            self.set_evaluator(evaluator)
        for k, v in kw.items():
            self.set(k, v)

    def _fit(self, frame: MLFrame) -> "TrainValidationSplitModel":
        rng = np.random.RandomState(self.get("seed"))
        mask = rng.rand(frame.n_rows) < self.get("trainRatio")
        train, valid = frame.filter_rows(mask), frame.filter_rows(~mask)
        maps = self._param_maps
        from cycloneml_tpu.mesh import safe_fit_parallelism
        par = safe_fit_parallelism(self.get("parallelism"))
        if par > 1:
            with cf.ThreadPoolExecutor(max_workers=par) as pool:
                metrics = list(pool.map(
                    lambda pm: self._fit_score_one(pm, train, valid), maps))
        else:
            metrics = [self._fit_score_one(pm, train, valid) for pm in maps]
        metrics = np.asarray(metrics)
        best_idx = int(np.argmax(metrics) if self._evaluator.is_larger_better
                       else np.argmin(metrics))
        best = self._estimator.fit(frame, maps[best_idx])
        model = TrainValidationSplitModel(best, metrics.tolist(), uid=self.uid)
        self._copy_values(model)
        return model._set_parent(self)


class TrainValidationSplitModel(CrossValidatorModel):
    def __init__(self, best_model=None, validation_metrics=None, uid=None):
        super().__init__(best_model, validation_metrics, uid=uid)
        self.trainRatio = self._param("trainRatio", "train fraction",
                                      default=0.75)

    @property
    def validation_metrics(self):
        # property, not an alias: _load_data rebinds avg_metrics after init
        return self.avg_metrics
