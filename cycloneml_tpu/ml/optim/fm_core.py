"""Factorization-machine training core shared by FMClassifier/FMRegressor.

Re-design of the reference (ref: ml/regression/FMRegressor.scala —
``trainImpl`` runs minibatch gradient descent with the AdamW or plain GD
updater over combined coefficients [factors, linear?, intercept?];
FMClassifier reuses it with logistic loss). TPU-first: the per-minibatch
loss/gradient is ONE jit-compiled psum program — the FM forward
(s = X·V, 0.5·Σ(s² − X²·V²)) is two MXU matmuls and the backward comes from
``jax.grad`` instead of the reference's hand-derived update — and the AdamW
state update is a tiny jitted step on the driver.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS


def fm_margin(jnp, x, coef, d: int, k: int, fit_intercept: bool,
              fit_linear: bool, precision):
    """margin_i = b + x·w + ½ Σ_f [(x·V_f)² − (x²)·(V_f²)]; V is (d, k)."""
    V = coef[: d * k].reshape(d, k)
    off = d * k
    if fit_linear:
        w = coef[off: off + d]
        off += d
    else:
        w = None
    b = coef[off] if fit_intercept else jnp.zeros((), coef.dtype)
    s = jnp.dot(x, V, precision=precision)                   # (bsz, k)
    quad = 0.5 * jnp.sum(
        s * s - jnp.dot(x * x, V * V, precision=precision), axis=1)
    margin = quad + b
    if w is not None:
        margin = margin + jnp.dot(x, w, precision=precision)
    return margin


def train_fm(ds: InstanceDataset, d: int, loss_type: str, factor_size: int,
             fit_intercept: bool, fit_linear: bool, reg_param: float,
             mini_batch_fraction: float, init_std: float, max_iter: int,
             step_size: float, tol: float, solver: str, seed: int,
             ) -> Tuple[np.ndarray, list]:
    """Returns (coef, objective_history). coef layout = [V, w?, b?]."""
    import jax
    import jax.numpy as jnp
    import optax

    k = factor_size
    hi = jax.lax.Precision.HIGHEST
    frac = mini_batch_fraction

    def agg(x, y, w, coef, key):
        keep = w > 0
        if frac < 1.0:
            shard_key = jax.random.fold_in(
                jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS)),
                jax.lax.axis_index(REPLICA_AXIS))
            u = jax.random.uniform(shard_key, w.shape, dtype=w.dtype)
            keep = jnp.logical_and(keep, u < frac)
        wm = w * keep.astype(w.dtype)

        def total_loss(c):
            m = fm_margin(jnp, x, c, d, k, fit_intercept, fit_linear, hi)
            if loss_type == "logistic":
                per = jax.nn.softplus(m) - y * m
            else:  # squaredError
                per = 0.5 * (m - y) ** 2
            return jnp.sum(wm * per)

        loss, grad = jax.value_and_grad(total_loss)(coef)
        return {"loss": loss, "grad": grad, "wsum": jnp.sum(wm)}

    run = ds.tree_aggregate_fn(agg)

    n_coef = d * k + (d if fit_linear else 0) + (1 if fit_intercept else 0)
    rng = np.random.RandomState(seed)
    coef = np.zeros(n_coef)
    coef[: d * k] = rng.randn(d * k) * init_std

    if solver == "adamW":
        # ref AdamWUpdater: beta1=0.9, beta2=0.999, eps=1e-8, weight decay =
        # regParam (decoupled)
        opt = optax.adamw(step_size, b1=0.9, b2=0.999, eps=1e-8,
                          weight_decay=reg_param)
    else:  # gd
        opt = optax.sgd(step_size)

    dtype = ds.w.dtype  # accumulator tier: X may store bf16
    opt_state = opt.init(jnp.asarray(coef, dtype))
    coef_j = jnp.asarray(coef, dtype)

    @jax.jit
    def apply_update(coef_j, opt_state, grad, wsum):
        g = grad / jnp.maximum(wsum, 1e-300)
        if solver == "gd" and reg_param > 0:
            g = g + reg_param * coef_j  # L2 for plain gd (ref SquaredL2Updater)
        updates, new_state = opt.update(g, opt_state, coef_j)
        return optax.apply_updates(coef_j, updates), new_state

    history = []
    prev = np.inf
    for t in range(max_iter):
        key = jax.random.PRNGKey(seed * 65537 + t)
        out = run(coef_j, key)
        # fetch ONLY the scalars, in one transfer (graftlint JX001);
        # grad/wsum stay on device — they feed straight into apply_update
        wsum, loss_sum = map(float, jax.device_get((out["wsum"],
                                                    out["loss"])))
        if wsum <= 0:
            continue
        loss = loss_sum / wsum
        history.append(loss)
        coef_j, opt_state = apply_update(coef_j, opt_state, out["grad"],
                                         out["wsum"])
        if frac >= 1.0 and abs(prev - loss) < tol * max(abs(prev), 1.0):
            prev = loss
            break
        prev = loss

    return np.asarray(coef_j, np.float64), history


def split_fm_coef(coef: np.ndarray, d: int, k: int, fit_intercept: bool,
                  fit_linear: bool):
    V = coef[: d * k].reshape(d, k)
    off = d * k
    w = coef[off: off + d] if fit_linear else np.zeros(d)
    if fit_linear:
        off += d
    b = float(coef[off]) if fit_intercept else 0.0
    return V, w, b


def fm_margin_np(x: np.ndarray, V: np.ndarray, w: np.ndarray, b: float):
    s = x @ V
    quad = 0.5 * ((s * s) - (x * x) @ (V * V)).sum(axis=1)
    return b + x @ w + quad
