"""Block aggregators over ELL sparse blocks.

Sparse twins of ``aggregators`` (same contract: sums, not means, psum'd by
``tree_aggregate``): margins come from gathers (``coef[indices]·values``
replacing the dense gemv, ref BinaryLogisticBlockAggregator.scala:97) and
gradients from ``segment_sum`` scatter-adds into the d-dim coefficient space
(replacing the transpose gemv :130) — O(nnz) instead of the dense path's
O(b·d). Measured on a v5e chip at Criteo shape (200k rows × 39 nnz,
d=2^18): ~55 ms/gradient for the scatter, ~114 ms/full eval ≈ 0.07 Gnnz/s —
a workload whose dense form (210 GB) cannot exist on the chip at all.
Pre-sorting contributions at ingest to hit the sorted segment path was
measured SLOWER (the permutation gather costs more than the scatter saves),
so the direct scatter stays. Throughput is flat in the table size (measured
identical from d=2^12 to 2^20): the cost is XLA's per-element gather/scatter
lowering, not HBM locality — so feature hashing narrows the model for
statistics/memory reasons, not speed.

Signature: ``(indices, values, y, w, coef) -> {"loss","grad","count"}`` with
``indices/values (b, k)``, padding slots (0, 0.0) and padding rows w=0 —
both exactly neutral: value 0 kills the gather term, weight 0 kills the row.
"""

from __future__ import annotations

import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp

Agg = Callable[..., Dict[str, jnp.ndarray]]


def _margins(indices, values, beta, b0):
    """x·β per row via gather: Σ_k values[i,k] · β[indices[i,k]]."""
    return jnp.sum(values * jnp.take(beta, indices, axis=0), axis=1) + b0


def _scatter_grad(indices, values, mult, d):
    """Σ_i mult_i · x_i as a segment-sum: scatter-add of
    (mult[:,None]·values) into d bins keyed by indices."""
    contrib = (mult[:, None] * values).reshape(-1)
    return jax.ops.segment_sum(contrib, indices.reshape(-1).astype(jnp.int32),
                               num_segments=d)


def _split(coef, d, fit_intercept):
    if fit_intercept:
        return coef[:d], coef[d]
    return coef, jnp.zeros((), coef.dtype)


@functools.lru_cache(maxsize=None)
def binary_logistic_sparse(d: int, fit_intercept: bool = True) -> Agg:
    """Sparse binomial logistic (dense twin: aggregators.binary_logistic)."""

    def agg(indices, values, y, w, coef):
        beta, b0 = _split(coef, d, fit_intercept)
        margin = _margins(indices, values, beta, b0)
        loss = jnp.sum(w * (jax.nn.softplus(margin) - y * margin))
        mult = w * (jax.nn.sigmoid(margin) - y)
        g = _scatter_grad(indices, values, mult, d)
        grad = (jnp.concatenate([g, jnp.sum(mult)[None]])
                if fit_intercept else g)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


@functools.lru_cache(maxsize=None)
def least_squares_sparse(d: int, fit_intercept: bool = True) -> Agg:
    """Sparse squared loss (dense twin: aggregators.least_squares)."""

    def agg(indices, values, y, w, coef):
        beta, b0 = _split(coef, d, fit_intercept)
        err = _margins(indices, values, beta, b0) - y
        loss = 0.5 * jnp.sum(w * err * err)
        mult = w * err
        g = _scatter_grad(indices, values, mult, d)
        grad = (jnp.concatenate([g, jnp.sum(mult)[None]])
                if fit_intercept else g)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


@functools.lru_cache(maxsize=None)
def hinge_sparse(d: int, fit_intercept: bool = True) -> Agg:
    """Sparse hinge loss (dense twin: aggregators.hinge)."""

    def agg(indices, values, y, w, coef):
        beta, b0 = _split(coef, d, fit_intercept)
        margin = _margins(indices, values, beta, b0)
        ysign = 2.0 * y - 1.0
        active = (1.0 - ysign * margin) > 0
        loss = jnp.sum(w * jnp.maximum(0.0, 1.0 - ysign * margin))
        mult = jnp.where(active, -ysign * w, 0.0)
        g = _scatter_grad(indices, values, mult, d)
        grad = (jnp.concatenate([g, jnp.sum(mult)[None]])
                if fit_intercept else g)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


@functools.lru_cache(maxsize=None)
def sparse_summary(d: int) -> Agg:
    """Single-pass weighted feature moments over ELL blocks
    (dense twin: ml/stat Summarizer's aggregation, ref Summarizer.scala:214):
    returns per-feature weighted sum and sum-of-squares plus weight/count —
    enough for mean/variance/std standardization of sparse data (zero entries
    contribute 0 to sums; the caller folds in the implicit zeros)."""

    def agg(indices, values, y, w, coef_unused):
        wk = w[:, None] * values
        seg = indices.reshape(-1).astype(jnp.int32)
        s1 = jax.ops.segment_sum((wk).reshape(-1), seg, num_segments=d)
        s2 = jax.ops.segment_sum((wk * values).reshape(-1), seg,
                                 num_segments=d)
        nnz = jax.ops.segment_sum(
            jnp.broadcast_to(w[:, None], values.shape).reshape(-1)
            * (values != 0).reshape(-1), seg, num_segments=d)
        return {"sum": s1, "sum_sq": s2, "nnz_weight": nnz,
                "weight_sum": jnp.sum(w),
                "weight_sq_sum": jnp.sum(w * w),
                "count": jnp.sum((w > 0).astype(jnp.float32))}

    return agg


# -- hybrid (ELL + COO overflow) aggregators ------------------------------------
# Rows wider than the ELL width carry a COO tail (shard-local row ids);
# margins add a per-row segment-sum of the tail to the ELL gather, and
# gradients scatter the tail's contributions by column. Padding COO entries
# are (row 0, col 0, val 0.0) — exactly neutral in both directions.

def _margins_hybrid(indices, values, coo_row, coo_idx, coo_val, beta, b0):
    base = _margins(indices, values, beta, b0)
    tail = jax.ops.segment_sum(coo_val * jnp.take(beta, coo_idx, axis=0),
                               coo_row.astype(jnp.int32),
                               num_segments=indices.shape[0])
    return base + tail


def _scatter_grad_hybrid(indices, values, coo_row, coo_idx, coo_val,
                         mult, d):
    g = _scatter_grad(indices, values, mult, d)
    return g + jax.ops.segment_sum(mult[coo_row] * coo_val,
                                   coo_idx.astype(jnp.int32),
                                   num_segments=d)


@functools.lru_cache(maxsize=None)
def binary_logistic_sparse_hybrid(d: int, fit_intercept: bool = True) -> Agg:
    """Hybrid twin of :func:`binary_logistic_sparse`."""

    def agg(indices, values, coo_row, coo_idx, coo_val, y, w, coef):
        beta, b0 = _split(coef, d, fit_intercept)
        margin = _margins_hybrid(indices, values, coo_row, coo_idx, coo_val,
                                 beta, b0)
        loss = jnp.sum(w * (jax.nn.softplus(margin) - y * margin))
        mult = w * (jax.nn.sigmoid(margin) - y)
        g = _scatter_grad_hybrid(indices, values, coo_row, coo_idx, coo_val,
                                 mult, d)
        grad = (jnp.concatenate([g, jnp.sum(mult)[None]])
                if fit_intercept else g)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


@functools.lru_cache(maxsize=None)
def least_squares_sparse_hybrid(d: int, fit_intercept: bool = True) -> Agg:
    """Hybrid twin of :func:`least_squares_sparse`."""

    def agg(indices, values, coo_row, coo_idx, coo_val, y, w, coef):
        beta, b0 = _split(coef, d, fit_intercept)
        err = _margins_hybrid(indices, values, coo_row, coo_idx, coo_val,
                              beta, b0) - y
        loss = 0.5 * jnp.sum(w * err * err)
        mult = w * err
        g = _scatter_grad_hybrid(indices, values, coo_row, coo_idx, coo_val,
                                 mult, d)
        grad = (jnp.concatenate([g, jnp.sum(mult)[None]])
                if fit_intercept else g)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


@functools.lru_cache(maxsize=None)
def sparse_summary_hybrid(d: int) -> Agg:
    """Hybrid twin of :func:`sparse_summary`: the COO tail's entries fold
    into the same per-feature moments (their row's weight gathered by the
    shard-local row id)."""
    base = sparse_summary(d)

    def agg(indices, values, coo_row, coo_idx, coo_val, y, w, coef_unused):
        out = base(indices, values, y, w, coef_unused)
        cw = jnp.take(w, coo_row.astype(jnp.int32), axis=0)
        seg = coo_idx.astype(jnp.int32)
        out = dict(out)
        out["sum"] = out["sum"] + jax.ops.segment_sum(
            cw * coo_val, seg, num_segments=d)
        out["sum_sq"] = out["sum_sq"] + jax.ops.segment_sum(
            cw * coo_val * coo_val, seg, num_segments=d)
        out["nnz_weight"] = out["nnz_weight"] + jax.ops.segment_sum(
            cw * (coo_val != 0), seg, num_segments=d)
        return out

    return agg
