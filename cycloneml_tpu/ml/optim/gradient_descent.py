"""Mini-batch gradient descent with pluggable updaters.

Analog of the reference's RDD-API optimizer family (ref: mllib/optimization/
GradientDescent.scala:34 — ``runMiniBatchSGD`` samples a miniBatchFraction
per step, treeAggregates the gradient, and applies an ``Updater``;
Updater.scala — SimpleUpdater, L1Updater (soft threshold), SquaredL2Updater;
step size decays as stepSize/√t exactly as here). The distributed gradient
is one jitted mesh program per step; sampling uses a per-step Bernoulli mask
folded into the row weights, so shapes stay static for XLA (the reference's
``sample()`` materializes a subset — dynamic shapes don't translate).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.observe import tracing
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class Updater:
    """(ref Updater.scala) — returns (new_weights, reg_value)."""

    def compute(self, weights: np.ndarray, gradient: np.ndarray,
                step_size: float, iteration: int, reg_param: float
                ) -> Tuple[np.ndarray, float]:
        raise NotImplementedError


class SimpleUpdater(Updater):
    def compute(self, weights, gradient, step_size, iteration, reg_param):
        eta = step_size / np.sqrt(iteration)
        return weights - eta * gradient, 0.0


class SquaredL2Updater(Updater):
    """w ← w(1 − η·λ) − η·g ; reg = λ‖w‖²/2 (ref SquaredL2Updater)."""

    def compute(self, weights, gradient, step_size, iteration, reg_param):
        eta = step_size / np.sqrt(iteration)
        new_w = weights * (1.0 - eta * reg_param) - eta * gradient
        return new_w, 0.5 * reg_param * float(new_w @ new_w)


class L1Updater(Updater):
    """Soft-thresholding proximal step (ref L1Updater.compute)."""

    def compute(self, weights, gradient, step_size, iteration, reg_param):
        eta = step_size / np.sqrt(iteration)
        w = weights - eta * gradient
        shrink = reg_param * eta
        w = np.sign(w) * np.maximum(np.abs(w) - shrink, 0.0)
        return w, reg_param * float(np.abs(w).sum())


class GradientDescent:
    """(ref GradientDescent.scala:34 runMiniBatchSGD)

    ``agg`` is any block aggregator ``(x, y, w, coef) -> {loss, grad,
    count}`` from ``aggregators``/``sparse_aggregators``; per step the row
    weights are multiplied by a Bernoulli(miniBatchFraction) mask (static
    shapes; expectation matches the reference's sampling) and the summed
    gradient is normalized by the sampled weight like the reference divides
    by miniBatchSize.
    """

    def __init__(self, step_size: float = 1.0, num_iterations: int = 100,
                 reg_param: float = 0.0, mini_batch_fraction: float = 1.0,
                 updater: Optional[Updater] = None,
                 convergence_tol: float = 0.001, seed: int = 0):
        self.step_size = step_size
        self.num_iterations = num_iterations
        self.reg_param = reg_param
        self.mini_batch_fraction = mini_batch_fraction
        self.updater = updater or SimpleUpdater()
        self.convergence_tol = convergence_tol
        self.seed = seed

    def optimize(self, dataset, agg: Callable, x0: np.ndarray
                 ) -> Tuple[np.ndarray, list]:
        """Returns (weights, stochastic loss history) — the reference returns
        the same pair from runMiniBatchSGD."""
        import jax
        import jax.numpy as jnp

        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS

        frac = self.mini_batch_fraction

        def fn(*args):
            # works for both tiers: (rows..., w, coef, step) with w the last
            # row-sharded array; per-shard Bernoulli mask (keyed on step AND
            # both mesh axes — every shard must sample independently) keeps
            # shapes static
            *rows, w, coef, step = args
            if frac < 1.0:
                key = jax.random.fold_in(jax.random.PRNGKey(self.seed), step)
                key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
                key = jax.random.fold_in(key,
                                         jax.lax.axis_index(REPLICA_AXIS))
                w = w * (jax.random.uniform(key, w.shape) < frac)
            return agg(*rows, w, coef)

        compiled = dataset.tree_aggregate_fn(fn)

        w = np.asarray(x0, dtype=np.float64).copy()
        history: list = []
        # seed regVal from the INITIAL weights with a zero gradient, exactly
        # as runMiniBatchSGD does before the loop (GradientDescent.scala:249
        # "compute the initial regVal") — each history entry then pairs the
        # pre-update stochastic loss with the reg value of the weights the
        # loss was evaluated AT, not the post-update ones
        _, reg = self.updater.compute(w, np.zeros_like(w), 0.0, 1,
                                      self.reg_param)
        updates = 0
        for t in range(1, self.num_iterations + 1):
            with tracing.span("dispatch", "gd.step", evals=1):
                out_dev = compiled(jnp.asarray(w, jnp.float32),
                                   jnp.asarray(t, jnp.int32))
                # one transfer for count+loss+grad, not three (JX001)
                with tracing.span("transfer", "gd.readback") as tsp:
                    out = jax.device_get(out_dev)
                    tsp.annotate_bytes(out)
            count = float(out["count"])
            if count <= 0:
                # empty mini-batch: no update, no history entry (the
                # reference skips when miniBatchSize == 0) — recording 0.0
                # would fake convergence
                continue
            loss = float(out["loss"]) / count
            grad = np.asarray(out["grad"], dtype=np.float64) / count
            history.append(loss + reg)
            prev_w = w
            w, reg = self.updater.compute(w, grad, self.step_size, t,
                                          self.reg_param)
            updates += 1
            # reference convergence test (GradientDescent.isConverged):
            # ‖w_t − w_{t−1}‖ < tol · max(‖w_{t−1}‖, 1); never checked on the
            # first ACTUAL update (the reference's previousWeights is still
            # None then — w₁ vs the user-supplied x0 is not a convergence
            # signal, and skipped empty mini-batches don't count)
            if self.convergence_tol > 0 and updates > 1:
                delta = float(np.linalg.norm(w - prev_w))
                if delta < self.convergence_tol * max(
                        float(np.linalg.norm(prev_w)), 1.0):
                    logger.info("GradientDescent converged at iteration %d", t)
                    break
        return w, history

class StackedGradientDescent(GradientDescent):
    """Model-axis (vmapped) mini-batch SGD: K models over ONE design matrix.

    The stacked twin of :meth:`GradientDescent.optimize` — the dataset
    carries a ``(n_pad, K)`` label matrix as ``y``, the aggregator is
    vmapped over the model axis (``aggregators.stack_aggregator``), and
    every step is ONE batched psum producing K gradients. Per-model
    convergence masks freeze early-converged models (no weight update, no
    history entry — exactly where their serial run would have stopped)
    while the rest keep stepping; the per-step Bernoulli mask is keyed on
    step+seed only, so each model sees the SAME sample sequence its serial
    run would.
    """

    def optimize_stacked(self, dataset, agg: Callable, x0: np.ndarray
                         ) -> Tuple[np.ndarray, list]:
        """``x0`` is (K, n); returns ``(weights (K, n), histories)`` where
        ``histories[k]`` is model k's stochastic loss history (what serial
        ``optimize`` returns per model)."""
        import jax
        import jax.numpy as jnp

        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
        from cycloneml_tpu.ml.optim import aggregators

        stacked = aggregators.stack_aggregator(agg)
        frac = self.mini_batch_fraction

        def fn(*args):
            *rows, w, coef, step = args
            if frac < 1.0:
                key = jax.random.fold_in(jax.random.PRNGKey(self.seed), step)
                key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
                key = jax.random.fold_in(key,
                                         jax.lax.axis_index(REPLICA_AXIS))
                w = w * (jax.random.uniform(key, w.shape) < frac)
            return stacked(*rows, w, coef)

        compiled = dataset.tree_aggregate_fn(fn)

        W = np.asarray(x0, dtype=np.float64).copy()
        n_models = W.shape[0]
        histories: list = [[] for _ in range(n_models)]
        regs = np.zeros(n_models)
        for kk in range(n_models):
            _, regs[kk] = self.updater.compute(
                W[kk], np.zeros_like(W[kk]), 0.0, 1, self.reg_param)
        live = np.ones(n_models, dtype=bool)
        updates = np.zeros(n_models, dtype=np.int64)
        for t in range(1, self.num_iterations + 1):
            if not live.any():
                break
            with tracing.span("dispatch", "gd.step", evals=1,
                              n_models=n_models):
                out_dev = compiled(jnp.asarray(W, jnp.float32),
                                   jnp.asarray(t, jnp.int32))
                with tracing.span("transfer", "gd.readback") as tsp:
                    out = jax.device_get(out_dev)
                    tsp.annotate_bytes(out)
            count = np.asarray(out["count"], dtype=np.float64)
            if float(count.max()) <= 0:
                # empty mini-batch (shared sample mask): no model updates
                continue
            loss = np.asarray(out["loss"], dtype=np.float64) / count
            grad = np.asarray(out["grad"], dtype=np.float64) / count[:, None]
            for kk in np.nonzero(live)[0]:
                histories[kk].append(loss[kk] + regs[kk])
                prev = W[kk].copy()
                W[kk], regs[kk] = self.updater.compute(
                    W[kk], grad[kk], self.step_size, t, self.reg_param)
                updates[kk] += 1
                if self.convergence_tol > 0 and updates[kk] > 1:
                    delta = float(np.linalg.norm(W[kk] - prev))
                    if delta < self.convergence_tol * max(
                            float(np.linalg.norm(prev)), 1.0):
                        live[kk] = False
                        logger.info(
                            "StackedGradientDescent: model %d converged at "
                            "iteration %d (%d/%d still live)", kk, t,
                            int(live.sum()), n_models)
        return W, histories
