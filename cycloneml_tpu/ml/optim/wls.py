"""WeightedLeastSquares — the reference's normal-equation solver component.

Semantics port of ml/optim/WeightedLeastSquares.scala:101-326 and
NormalEquationSolver.scala:59-153 (CholeskySolver + QuasiNewtonSolver),
TPU-shaped: the moment aggregation (the reference's ``treeAggregate(new
Aggregator)``) is ONE jitted device pass producing {wSum, bBar, bbBar,
aBar, aaBar, abBar}; the (d+1)-sized standardized normal-equation solve
then runs on the driver in f64, exactly where the reference solves after
its aggregate.

Distinctions that matter for golden parity (and differ from the
LinearRegression l-bfgs path):

- moments are POPULATION-weighted (aVar = aaBar − aBar², divided by wSum)
  — glmnet's convention, NOT the Summarizer's unbiased denominator;
- the intercept is an APPENDED column of the standardized system (getAtA
  at :312), not a centering trick, and the quasi-Newton cost function
  pins it to bBar − aBar·β every evaluation (NormalEquationSolver.scala:
  134-144);
- zero-variance features get zero coefficients via the bStd/aStd=0
  mapping (:290);
- a constant label short-circuits with fitIntercept (or an all-zero
  label), refuses regularization when the label is standardized, and
  otherwise trains with bStd = |bBar| (:117-141).

GLM's IRLS and LinearRegression's 'normal' solver are this component's
estimator-level callers in the reference (SURVEY §2.3 optimizers row).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

AUTO = "auto"
CHOLESKY = "cholesky"
QUASI_NEWTON = "quasi-newton"

MAX_NUM_FEATURES = 4096  # ref WeightedLeastSquares.MAX_NUM_FEATURES:335


class WeightedLeastSquaresModel:
    def __init__(self, coefficients: np.ndarray, intercept: float,
                 diag_inv_atwa: np.ndarray, objective_history):
        self.coefficients = coefficients
        self.intercept = intercept
        self.diag_inv_atwa = diag_inv_atwa
        self.objective_history = list(objective_history)

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(x) @ self.coefficients + self.intercept


_agg_jit = None


def _moments(x, y, w):
    """One device pass for the summary moments (ref Aggregator.add/merge;
    the psum over blocks replaces treeAggregate). The jitted kernel is
    module-cached so repeated fits at one shape (IRLS iterations,
    hyperparameter sweeps) compile once and dispatch thereafter."""
    import jax
    import jax.numpy as jnp

    global _agg_jit
    if _agg_jit is None:
        @jax.jit
        def agg(x, y, w):
            return {
                "w_sum": jnp.sum(w),
                "b_sum": jnp.sum(w * y),
                "bb_sum": jnp.sum(w * y * y),
                "a_sum": jnp.sum(x * w[:, None], axis=0),
                "ab_sum": jnp.sum(x * (w * y)[:, None], axis=0),
                "aa_sum": jnp.einsum("bi,bj->ij", x * w[:, None], x,
                                     precision=jax.lax.Precision.HIGHEST),
            }
        _agg_jit = agg

    out = _agg_jit(jnp.asarray(x), jnp.asarray(y), jnp.asarray(w))
    return {k: np.asarray(v, dtype=np.float64) for k, v in out.items()}


class WeightedLeastSquares:
    """Normal-equation WLS with the reference's exact solver semantics."""

    def __init__(self, fit_intercept: bool, reg_param: float = 0.0,
                 elastic_net_param: float = 0.0,
                 standardize_features: bool = True,
                 standardize_label: bool = True,
                 solver_type: str = AUTO,
                 max_iter: int = 100, tol: float = 1e-6):
        if reg_param < 0:
            raise ValueError("regParam must be >= 0")
        if not 0.0 <= elastic_net_param <= 1.0:
            raise ValueError("elasticNetParam must be in [0, 1]")
        if solver_type not in (AUTO, CHOLESKY, QUASI_NEWTON):
            raise ValueError(f"unknown solver {solver_type!r}")
        self.fit_intercept = fit_intercept
        self.reg_param = float(reg_param)
        self.elastic_net_param = float(elastic_net_param)
        self.standardize_features = standardize_features
        self.standardize_label = standardize_label
        self.solver_type = solver_type
        self.max_iter = max_iter
        self.tol = tol

    # -- public ----------------------------------------------------------
    def fit(self, x, y, w: Optional[np.ndarray] = None
            ) -> WeightedLeastSquaresModel:
        """``x``/``y``/``w`` may be numpy OR live (possibly sharded)
        device arrays — they pass straight into the jitted moment pass
        with no host round-trip, so a mesh-sharded dataset aggregates in
        place and only the O(d²) moments come back to the driver."""
        n, d = x.shape
        if d > MAX_NUM_FEATURES:
            raise ValueError(
                f"WeightedLeastSquares supports at most {MAX_NUM_FEATURES} "
                f"features, got {d}")
        if w is None:
            w = np.ones(n)
        m = _moments(x, y, w)
        return self._solve_from_moments(m, d)

    # -- the reference algorithm -----------------------------------------
    def _solve_from_moments(self, m, d: int) -> WeightedLeastSquaresModel:
        w_sum = m["w_sum"]
        if w_sum <= 0:
            raise ValueError("sum of weights must be positive")
        raw_b_bar = m["b_sum"] / w_sum
        raw_bb_bar = m["bb_sum"] / w_sum
        raw_b_std = float(np.sqrt(max(raw_bb_bar - raw_b_bar ** 2, 0.0)))

        if raw_b_std == 0.0:
            if self.fit_intercept or raw_b_bar == 0.0:
                # ref :121-136: constant label needs no training
                return WeightedLeastSquaresModel(
                    np.zeros(d), float(raw_b_bar) if self.fit_intercept
                    else 0.0, np.zeros(1), [0.0])
            if self.reg_param > 0.0 and self.standardize_label:
                raise ValueError(
                    "The standard deviation of the label is zero. Model "
                    "cannot be regularized when labels are standardized")
        b_std = abs(float(raw_b_bar)) if raw_b_std == 0.0 else raw_b_std
        b_bar = float(raw_b_bar) / b_std
        bb_bar = float(raw_bb_bar) / (b_std * b_std)

        raw_a_bar = m["a_sum"] / w_sum
        raw_aa_bar = m["aa_sum"] / w_sum
        raw_ab_bar = m["ab_sum"] / w_sum
        a_var = np.maximum(np.diag(raw_aa_bar) - raw_a_bar ** 2, 0.0)
        a_std = np.sqrt(a_var)
        live = a_std > 0
        inv_std = np.where(live, 1.0 / np.where(live, a_std, 1.0), 0.0)

        a_bar = raw_a_bar * inv_std
        ab_bar = raw_ab_bar * inv_std / b_std
        aa_bar = raw_aa_bar * np.outer(inv_std, inv_std)

        eff_reg = self.reg_param / b_std
        eff_l1 = self.elastic_net_param * eff_reg
        eff_l2 = (1.0 - self.elastic_net_param) * eff_reg

        # L2 onto the standardized diagonal (ref :213-231)
        lam = np.full(d, eff_l2)
        if not self.standardize_features:
            lam = np.where(live, lam * inv_std * inv_std, 0.0)
        if not self.standardize_label:
            lam = lam * b_std
        aa_bar = aa_bar + np.diag(lam)

        # augmented system: intercept rides as an appended bias column
        if self.fit_intercept:
            ata = np.block([[aa_bar, a_bar[:, None]],
                            [a_bar[None, :], np.ones((1, 1))]])
            atb = np.concatenate([ab_bar, [b_bar]])
        else:
            ata = aa_bar
            atb = ab_bar

        use_qn = (self.solver_type == QUASI_NEWTON
                  or (self.solver_type == AUTO
                      and self.elastic_net_param != 0.0
                      and self.reg_param != 0.0))
        if use_qn:
            sol, history, aa_inv = self._quasi_newton(
                ata, atb, a_bar, b_bar, bb_bar, a_std, eff_l1, d)
        else:
            try:
                sol, history, aa_inv = self._cholesky(ata, atb)
            except np.linalg.LinAlgError:
                if self.solver_type != AUTO:
                    raise
                # ref :266-273: auto falls back to QN on singular AtA
                sol, history, aa_inv = self._quasi_newton(
                    ata, atb, a_bar, b_bar, bb_bar, a_std, None, d)

        if self.fit_intercept:
            coef_std, intercept = sol[:d], float(sol[d]) * b_std
        else:
            coef_std, intercept = sol, 0.0
        coef = coef_std * np.where(live, b_std * inv_std, 0.0)

        if aa_inv is not None:
            mult = np.concatenate([a_var, [1.0]]) if self.fit_intercept \
                else a_var
            with np.errstate(divide="ignore"):
                diag = np.where(mult > 0,
                                np.diag(aa_inv) / (w_sum * mult), np.inf)
        else:
            diag = np.zeros(1)
        return WeightedLeastSquaresModel(coef, intercept, diag, history)

    def _cholesky(self, ata, atb):
        # np.linalg.cholesky raises LinAlgError on non-PD — the reference's
        # SingularMatrixException analog
        chol = np.linalg.cholesky(ata)
        sol = np.linalg.solve(chol.T, np.linalg.solve(chol, atb))
        inv = np.linalg.inv(ata)
        return sol, [0.0], inv

    def _quasi_newton(self, ata, atb, a_bar, b_bar, bb_bar, a_std,
                      eff_l1, d: int):
        from cycloneml_tpu.ml.optim.lbfgs import LBFGS, OWLQN

        k = ata.shape[0]

        def f(coef):
            coef = np.asarray(coef, dtype=np.float64).copy()
            if self.fit_intercept:
                # ref NormalEquationCostFun:134-144 — the bias coordinate
                # is pinned to its optimum given the features
                coef[d] = b_bar - float(coef[:d] @ a_bar)
            aax = ata @ coef
            loss = 0.5 * bb_bar - float(atb @ coef) + 0.5 * float(coef @ aax)
            return loss, aax - atb

        x0 = np.zeros(k)
        if self.fit_intercept:
            x0[d] = b_bar
        if eff_l1:
            l1_vec = np.zeros(k)
            for i in range(d):
                if self.standardize_features:
                    l1_vec[i] = eff_l1
                else:
                    l1_vec[i] = eff_l1 / a_std[i] if a_std[i] != 0 else 0.0
            opt = OWLQN(max_iter=self.max_iter, tol=self.tol, l1_reg=l1_vec)
        else:
            opt = LBFGS(max_iter=self.max_iter, tol=self.tol)
        state = None
        for state in opt.iterations(f, x0):
            pass
        sol = np.asarray(state.x, dtype=np.float64).copy()
        if self.fit_intercept:
            sol[d] = b_bar - float(sol[:d] @ a_bar)
        return sol, list(state.loss_history), None
