"""Distributed loss function — the treeAggregate gradient reduction.

Equivalent of ``RDDLossFunction`` (ref: ml/optim/loss/RDDLossFunction.scala:47,
whose ``calculate:56`` broadcasts coefficients and ``treeAggregate:61``s an
aggregator over the data) plus ``DifferentiableRegularization`` (L2Reg): here
the broadcast is the replicated ``coef`` argument of a jit-compiled shard_map
program and the reduction is a hierarchical psum — one XLA program per
L-BFGS iteration instead of one Spark job (SURVEY §3.3).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.parallel import collectives


class DistributedLossFunction:
    """Callable (coef) -> (loss, grad) in float64 host space.

    - ``agg``: a block aggregator from ``aggregators`` (sums, not means)
    - ``l2_reg_fn``: optional (coef) -> (loss, grad) driver-side penalty
      (≈ L2RegFunction; handles featuresStd / intercept exclusion)
    - normalisation by total weight matches the reference (loss and grad are
      divided by weightSum inside the aggregator's merge in Spark; we divide
      once at the end — same value).
    """

    def __init__(self, dataset: InstanceDataset, agg: Callable,
                 l2_reg_fn: Optional[Callable] = None,
                 weight_sum: Optional[float] = None):
        self._agg_call = dataset.tree_aggregate_fn(agg)
        self._ctx = dataset.ctx
        self.l2_reg_fn = l2_reg_fn
        if weight_sum is None:
            import jax.numpy as jnp
            # w is the last sharded array for both the dense (x, y, w) and
            # sparse (indices, values, y, w) dataset tiers
            ws = dataset.tree_aggregate_fn(
                lambda *arrs: {"ws": jnp.sum(arrs[-1])})()
            weight_sum = float(ws["ws"])
        self.weight_sum = weight_sum
        self.n_evals = 0

    def __call__(self, coef: np.ndarray) -> Tuple[float, np.ndarray]:
        self.n_evals += 1
        out = self._agg_call(coef)
        loss = float(out["loss"]) / self.weight_sum
        grad = np.asarray(out["grad"], dtype=np.float64) / self.weight_sum
        if self.l2_reg_fn is not None:
            rl, rg = self.l2_reg_fn(coef)
            loss += rl
            grad += rg
        if hasattr(self._ctx, "record_step"):
            # one distributed gradient evaluation ≈ one stage's TaskMetrics
            self._ctx.record_step({"loss": loss})
        return loss, grad


def standardize_dataset(ds: InstanceDataset, features_std: np.ndarray):
    """Scale feature blocks by 1/std in HBM (≈ the reference persisting
    standardized blocks, LogisticRegression.scala:968). Zero-variance
    features scale to 0, matching the reference's exclusion. Returns
    (standardized dataset, inv_std)."""
    import jax
    import jax.numpy as jnp

    inv_std = np.where(features_std > 0, 1.0 / np.where(
        features_std > 0, features_std, 1.0), 0.0)
    scaled = jax.jit(lambda x, s: x * s)(ds.x, jnp.asarray(inv_std))
    return InstanceDataset(ds.ctx, scaled, ds.y, ds.w, ds.n_rows,
                           ds.n_features), inv_std


def validate_binary_labels(y: np.ndarray, what: str) -> None:
    """Reject anything outside {0, 1} — catches the ±1 SVM convention that
    would silently corrupt margin-based losses (the aggregators map y via
    2y−1)."""
    bad = ~np.isin(y, (0.0, 1.0))
    if bad.any():
        raise ValueError(
            f"{what} requires labels in {{0, 1}}, found "
            f"{np.unique(y[bad])[:5]}")


def l2_regularization(reg_param: float, d: int, fit_intercept: bool,
                      features_std: Optional[np.ndarray] = None,
                      standardize: bool = True) -> Optional[Callable]:
    """L2 penalty matching the reference's L2RegFunction semantics
    (ref: ml/optim/regularizer — applied to feature coefficients only, never
    the intercept; when ``standardization=false`` the penalty is computed in
    the ORIGINAL feature space even though training runs in standardized
    space, i.e. each β_j is divided by std_j before squaring).

    The coef vector passed in is in standardized space (β_std = β_orig·std).
    """
    if reg_param == 0.0:
        return None
    std = None
    if not standardize:
        if features_std is None:
            raise ValueError("features_std required when standardization=false")
        std = np.where(features_std > 0, features_std, 1.0)

    def fn(coef: np.ndarray) -> Tuple[float, np.ndarray]:
        grad = np.zeros_like(coef)
        beta = coef[:d]
        if std is None:
            loss = 0.5 * reg_param * float(np.dot(beta, beta))
            grad[:d] = reg_param * beta
        else:
            b = beta / std
            loss = 0.5 * reg_param * float(np.dot(b, b))
            grad[:d] = reg_param * beta / (std * std)
        return loss, grad

    return fn
