"""Distributed loss function — the treeAggregate gradient reduction.

Equivalent of ``RDDLossFunction`` (ref: ml/optim/loss/RDDLossFunction.scala:47,
whose ``calculate:56`` broadcasts coefficients and ``treeAggregate:61``s an
aggregator over the data) plus ``DifferentiableRegularization`` (L2Reg): here
the broadcast is the replicated ``coef`` argument of a jit-compiled shard_map
program and the reduction is a hierarchical psum — one XLA program per
L-BFGS iteration instead of one Spark job (SURVEY §3.3).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.observe import attribution, tracing
from cycloneml_tpu.parallel import collectives


def _weight_sum_agg(*arrs):
    """w is the last sharded array for both the dense (x, y, w) and sparse
    (indices, values, y, w) dataset tiers."""
    import jax.numpy as jnp
    return {"ws": jnp.sum(arrs[-1])}


class DistributedLossFunction:
    """Callable (coef) -> (loss, grad) in float64 host space.

    - ``agg``: a block aggregator from ``aggregators`` (sums, not means)
    - ``l2_reg_fn``: optional (coef) -> (loss, grad) driver-side penalty
      (≈ L2RegFunction; handles featuresStd / intercept exclusion)
    - normalisation by total weight matches the reference (loss and grad are
      divided by weightSum inside the aggregator's merge in Spark; we divide
      once at the end — same value).
    """

    def __init__(self, dataset: InstanceDataset, agg: Callable,
                 l2_reg_fn: Optional[Callable] = None,
                 weight_sum: Optional[float] = None,
                 extra_args: tuple = ()):
        # ``extra_args``: replicated device arrays the aggregator takes
        # BEFORE the coefficients (e.g. inv_std/scaled_mean for the
        # fold-standardization-into-the-read aggregators). They join the
        # fixed argument tuple so DeviceLBFGS's fused program threads them
        # through unchanged and the compiled program stays dataset-generic.
        base = dataset.tree_aggregate_fn(agg)
        if extra_args:
            extra = tuple(extra_args)

            # delegate to base per call (NOT a snapshot tuple): base reads
            # ds.x/ds.y/ds.w through their properties each invocation, so
            # a StorageManager-evicted dataset transparently restores
            # instead of dispatching on deleted buffers
            def call(*coef):
                return base(*extra, *coef)

            call.compiled = base.compiled
            call.arrays = lambda: base.arrays() + extra
            self._agg_call = call
        else:
            self._agg_call = base
        self._ctx = dataset.ctx
        self.l2_reg_fn = l2_reg_fn
        if weight_sum is None:
            # _weight_sum_agg is module-level so its program is cached across
            # fits (a fresh lambda here cost a full XLA recompile per fit)
            ws = dataset.tree_aggregate_fn(_weight_sum_agg)()
            weight_sum = float(ws["ws"])
        self.weight_sum = weight_sum
        self.n_evals = 0
        self.n_dispatches = 0  # host->device round trips (the relay cost)

    def __call__(self, coef: np.ndarray) -> Tuple[float, np.ndarray]:
        self.n_evals += 1
        self.n_dispatches += 1
        import jax
        with tracing.span("dispatch", "loss.eval", evals=1):
            out_dev = self._agg_call(coef)  # 'collective' span inside
            with tracing.span("transfer", "loss.readback") as tsp:
                out = jax.device_get(out_dev)  # one transfer, not two
                tsp.annotate_bytes(out)
        loss = float(out["loss"]) / self.weight_sum
        grad = np.asarray(out["grad"], dtype=np.float64) / self.weight_sum
        if self.l2_reg_fn is not None:
            rl, rg = self.l2_reg_fn(coef)
            loss += float(rl)
            grad += np.asarray(rg, dtype=np.float64)
        if hasattr(self._ctx, "record_step"):
            # one distributed gradient evaluation ≈ one stage's TaskMetrics
            self._ctx.record_step({"loss": loss})
        return loss, grad

    # -- device-resident line search ------------------------------------------
    def device_line_search(self, x: np.ndarray, direction: np.ndarray,
                           value: float, dg0: float, init_alpha: float,
                           c1: float, c2: float, max_evals: int):
        """Run the ENTIRE strong-Wolfe search in one XLA dispatch.

        The host path pays one dispatch plus readbacks per φ(α) evaluation
        (~30 round trips per L-BFGS iteration through a TPU relay); here the
        bracket+zoom state machine is a ``lax.while_loop`` whose φ is the
        inlined psum aggregation, so a whole iteration is one dispatch and
        one small readback. The reference pays one full Spark *job* per
        evaluation (ref RDDLossFunction.scala:56) — this is the structure we
        beat, not emulate. Returns ``(alpha, value_new, grad_new)`` with the
        host-f64 types the optimizer expects, or ``None`` when regularization
        has no traceable twin (caller falls back to the host search).
        """
        if self.l2_reg_fn is not None and \
                not hasattr(self.l2_reg_fn, "traceable"):
            return None
        import jax

        from cycloneml_tpu.parallel import faults

        # the fused program dispatches the aggregation from INSIDE one XLA
        # program, so the tree_aggregate-level injection points never see
        # these steps — fire them here, once per fused dispatch
        # (preempt_notice then multihost.host first, mirroring
        # _instrument_dispatch: a decommission notice precedes the loss
        # it announces, and a dead peer host surfaces as the collective
        # that cannot complete)
        faults.inject("multihost.preempt_notice")
        faults.inject("multihost.host")
        faults.inject("collectives.step")
        arrays = self._agg_call.arrays()
        # line-search arithmetic lives in the ACCUMULATOR tier — f32 on
        # TPU, f64 under x64 tests (matching the host path exactly) — never
        # the (possibly bf16) data tier: optimizer state at storage width
        # would destroy the Wolfe tests' resolution
        from cycloneml_tpu.dataset.instance import compute_dtype
        cdt = np.dtype(compute_dtype())
        l2_t = getattr(self.l2_reg_fn, "traceable", None) \
            if self.l2_reg_fn is not None else None
        # the cache is module-level and keyed on PROGRAM identity (the cached
        # aggregation program + the cached l2 traceable): repeated fits with
        # the same configuration reuse one compiled executable instead of
        # paying a ~30 s TPU recompile per fit. weight_sum is a runtime
        # argument for the same reason — baking it in would fork the cache.
        key = (self._agg_call.compiled, l2_t, float(c1), float(c2),
               int(max_evals), cdt.str)
        fn = _ls_program_cache.get(key)
        fresh = fn is None
        if fresh:
            fn = _build_line_search(self._agg_call.compiled, l2_t,
                                    c1, c2, max_evals, cdt)
            # bounded: standardization=False fits key on a fresh l2 fn per
            # fit and would otherwise grow this without limit
            _ls_program_cache.put(key, fn)
        args = (*arrays,
                np.asarray(x, dtype=cdt),
                np.asarray(direction, dtype=cdt),
                cdt.type(value), cdt.type(dg0),
                cdt.type(init_alpha),
                cdt.type(self.weight_sum))
        pid = None
        # full tracer only: the flight-recorder ring must not trigger the
        # AOT cost analyze / budget check. A live attribution window buys
        # the harvest too (scoped fits join FLOPs/bytes on the program id).
        win = attribution.dispatch_window()
        tr = tracing.full_active()
        if tr is not None or win.live:
            # cost harvest BEFORE the dispatch (registry-cached once per
            # program identity): a raise-mode budget guard must fire before
            # the oversized program executes, and the AOT analyze must not
            # land inside the dispatch/compile spans
            from cycloneml_tpu.observe import costs
            pid = costs.ensure("lbfgs.line_search", key, fn, args)
            if fresh and tr is not None:
                costs.check_budget(pid)
        win.annotate_program(pid)
        with win:
            with tracing.span("dispatch", "lbfgs.line_search") as dsp:
                if fresh:
                    with tracing.span("compile", "lbfgs.line_search"):
                        res = fn(*args)
                else:
                    res = fn(*args)
                with tracing.span("transfer", "line_search.readback") as tsp:
                    out = jax.device_get(res)
                    tsp.annotate_bytes(out)
        alpha, v, g, evals = out
        dsp.annotate(evals=int(evals))
        if tr is not None:
            from cycloneml_tpu.observe import costs
            dsp.annotate(program=pid)
            costs.note_execution(tr, pid)
        self.n_evals += int(evals)
        self.n_dispatches += 1
        loss = float(v)
        if hasattr(self._ctx, "record_step"):
            self._ctx.record_step({"loss": loss, "line_search_evals": int(evals)})
        return float(alpha), loss, np.asarray(g, dtype=np.float64)


def stacked_l2_scale(d: int, n_coef: int,
                     features_std: Optional[np.ndarray] = None,
                     standardize: bool = True) -> np.ndarray:
    """Per-coordinate scale for the stacked L2 penalty
    ``0.5 · reg_k · Σ_j coef_kj² · scale_j`` — the runtime-argument form of
    :func:`l2_regularization` (feature coords 1 — or 1/std² when
    ``standardization=false`` computes the penalty in original space —
    intercept coords 0), so ONE compiled stacked program serves every
    per-model reg vector instead of forking the program cache per λ."""
    scale = np.zeros(n_coef)
    if standardize or features_std is None:
        scale[:d] = 1.0
    else:
        s = np.where(features_std > 0, features_std, 1.0)
        scale[:d] = 1.0 / (s * s)
    return scale


def stacked_host_l2(loss: np.ndarray, grad: np.ndarray,
                    coef_stack: np.ndarray, reg: np.ndarray,
                    l2_scale: Optional[np.ndarray]):
    """Apply the per-model L2 penalty to a stacked host-f64 (loss, grad)
    pair: ``loss_k += 0.5·reg_k·Σ_j coef_kj²·scale_j``. Runtime data, not
    program structure — one compiled stacked program serves every reg
    vector. Shared by the in-core stacked loss and its streamed twin so
    their penalties are bit-identical for the parity suites."""
    if l2_scale is None or not np.any(reg > 0):
        return loss, grad
    cs = np.asarray(coef_stack, dtype=np.float64)
    loss = loss + 0.5 * reg * np.sum(cs * cs * l2_scale[None, :], axis=1)
    grad = grad + reg[:, None] * cs * l2_scale[None, :]
    return loss, grad


class StackedDistributedLossFunction:
    """Model-axis (vmapped) twin of :class:`DistributedLossFunction`.

    Callable ``(coef_stack (K, n_coef)) -> (loss (K,), grad (K, n_coef))``
    in host float64. ``dataset`` must carry the stacked ``(n_pad, K)`` label
    matrix as its ``y`` (see ``InstanceDataset.derive``) and ``agg`` the
    vmapped aggregator twin (``aggregators.stack_scaled_aggregator``), so K
    independent binomial objectives over ONE shared design matrix evaluate
    as a single SPMD program — one psum with a leading model axis, never K
    rendezvous-prone concurrent programs (the PR-2 deadlock).

    The L2 term is carried as runtime data — per-model ``reg`` ``(K,)`` plus
    the shared per-coordinate ``l2_scale`` from :func:`stacked_l2_scale` —
    both host-side here and inlined by the stacked chunk program, keeping
    program-cache identity across reg vectors (CV folds reuse one compile).
    """

    def __init__(self, dataset: InstanceDataset, agg: Callable,
                 n_models: int, reg: Optional[np.ndarray] = None,
                 l2_scale: Optional[np.ndarray] = None,
                 weight_sum: Optional[float] = None,
                 extra_args: tuple = ()):
        base = dataset.tree_aggregate_fn(agg)
        if extra_args:
            extra = tuple(extra_args)

            def call(*coef):
                return base(*extra, *coef)

            call.compiled = base.compiled
            call.arrays = lambda: base.arrays() + extra
            self._agg_call = call
        else:
            self._agg_call = base
        self._ctx = dataset.ctx
        self.n_models = int(n_models)
        self.reg = (np.zeros(self.n_models) if reg is None
                    else np.asarray(reg, dtype=np.float64))
        self.l2_scale = (None if l2_scale is None
                         else np.asarray(l2_scale, dtype=np.float64))
        if weight_sum is None:
            ws = dataset.tree_aggregate_fn(_weight_sum_agg)()
            weight_sum = float(ws["ws"])
        self.weight_sum = weight_sum
        self.n_evals = 0        # batched objective evaluations (each covers
        self.n_dispatches = 0   # all K models); host->device round trips

    def __call__(self, coef_stack: np.ndarray):
        self.n_evals += 1
        self.n_dispatches += 1
        import jax
        with tracing.span("dispatch", "loss.eval", evals=1,
                          n_models=self.n_models):
            out_dev = self._agg_call(coef_stack)
            with tracing.span("transfer", "loss.readback") as tsp:
                out = jax.device_get(out_dev)
                tsp.annotate_bytes(out)
        loss = np.asarray(out["loss"], dtype=np.float64) / self.weight_sum
        grad = np.asarray(out["grad"], dtype=np.float64) / self.weight_sum
        loss, grad = stacked_host_l2(loss, grad, coef_stack, self.reg,
                                     self.l2_scale)
        if hasattr(self._ctx, "record_step"):
            # one batched gradient evaluation ≈ one stage over all K models
            self._ctx.record_step({"loss": float(np.mean(loss)),
                                   "n_models": self.n_models})
        return loss, grad


_ls_program_cache = collectives.BoundedProgramCache(64)


def _build_line_search(compiled, l2_t, c1: float, c2: float, max_evals: int,
                       cdt: np.dtype):
    import jax
    import jax.numpy as jnp

    def program(*args):
        arrays = args[:-6]
        x0, dirn, value0, dg0, init_alpha, ws = args[-6:]
        # divide by ws, matching the host path's `loss / weight_sum`
        # bit-for-bit (a reciprocal-multiply drifts in the last ulp,
        # which 40 unregularized iterations amplify)

        def phi(alpha):
            coef = x0 + alpha * dirn
            out = compiled(*arrays, coef)
            loss = (out["loss"] / ws).astype(cdt)
            grad = (out["grad"] / ws).astype(cdt)
            if l2_t is not None:
                rl, rg = l2_t(coef)
                loss = loss + rl
                grad = grad + rg
            return loss, grad, jnp.dot(dirn, grad)

        g_zero = jnp.zeros((x0.shape[0],), cdt)
        return wolfe_search(phi, g_zero, value0, dg0, init_alpha,
                            c1, c2, max_evals, cdt)

    return jax.jit(program)


def _select_bcast(mask, a, b):
    """``jnp.where`` with the mask right-padded to the operand rank — lets
    one boolean select both scalar state fields and gradient pytree leaves
    (rank 0/1 unbatched; leading model axis + trailing coord axes when the
    search runs batched). Ranks are static trace-time metadata."""
    import jax.numpy as jnp
    extra = a.ndim - mask.ndim
    if extra > 0:
        mask = mask.reshape(mask.shape + (1,) * extra)
    return jnp.where(mask, a, b)


def wolfe_search(phi, g_zero, value0, dg0, init_alpha,
                 c1: float, c2: float, max_evals: int, cdt, active=None):
    """Traced strong-Wolfe bracket+zoom (Nocedal-Wright alg 3.5/3.6) as a
    ``lax.while_loop`` state machine — the device-resident twin of the host
    search in ``lbfgs._strong_wolfe``.

    ``phi(alpha) -> (value, grad_pytree, dg)``; ``g_zero`` is a zero pytree
    matching the gradient structure (any sharding — the feature-sharded
    path threads a (beta_sharded, b0_scalar) pair through unchanged).
    Returns ``(alpha, value, grad_pytree, evals)``.

    Batched (model-axis) form: when ``value0``/``dg0``/``init_alpha`` carry a
    leading ``(K,)`` axis (and ``g_zero`` leaves a leading ``K``), each model
    runs its OWN bracket+zoom trajectory in lockstep evaluation steps — one
    batched ``phi`` per step — and models whose search terminates freeze
    (state selected through, no further effect) instead of forcing the rest
    to stop. ``active`` (``(K,)`` bool, optional) marks models that must not
    search at all (already-converged models in a stacked fit): they start in
    the done phase with zero evals. Per-model ``evals`` counts only live
    steps, so the batched search's global step count is ``evals.max()``.
    """
    import jax
    import jax.numpy as jnp

    value0 = jnp.asarray(value0, cdt)
    zero = jnp.zeros(jnp.shape(value0), cdt)
    izero = jnp.zeros(jnp.shape(value0), jnp.int32)
    phase0 = izero if active is None else \
        jnp.where(active, 0, 2).astype(jnp.int32)
    state = dict(
        phase=phase0,   # 0 bracket, 1 zoom, 2 done
        evals=izero, bi=izero, zj=izero,
        alpha_prev=zero, v_prev=value0 + zero, d_prev=dg0 + zero,
        alpha_next=init_alpha + zero,
        lo=zero, hi=zero,
        v_lo=zero, d_lo=zero,
        v_hi=zero,
        res_alpha=zero, res_v=value0 + zero,
        res_g=g_zero,
    )

    def cond(s):
        return jnp.any(s["phase"] < 2)

    def body(s):
        in_bracket = s["phase"] == 0
        alpha = jnp.where(in_bracket, s["alpha_next"],
                          0.5 * (s["lo"] + s["hi"]))
        v, g, dg = phi(alpha)
        armijo_fail = v > value0 + c1 * alpha * dg0
        wolfe_ok = jnp.abs(dg) <= -c2 * dg0

        # -- bracket phase (Nocedal-Wright alg 3.5) --
        b_zoom_a = armijo_fail | ((s["bi"] > 0) & (v >= s["v_prev"]))
        b_done = (~b_zoom_a) & wolfe_ok
        b_zoom_b = (~b_zoom_a) & (~b_done) & (dg >= 0)
        b_cont = ~(b_zoom_a | b_done | b_zoom_b)
        # budget exhausted while still bracketing: accept current eval
        # (the host path's fallback re-evaluates at the next doubled α;
        # this branch is unreachable in practice — 30 doublings)
        b_exhaust = b_cont & (s["bi"] + 1 >= max_evals)
        enter_zoom = b_zoom_a | b_zoom_b

        # -- zoom phase (alg 3.6) --
        z_hi_a = armijo_fail | (v >= s["v_lo"])
        z_done = (~z_hi_a) & wolfe_ok
        z_flip = (~z_hi_a) & (~z_done) & (dg * (s["hi"] - s["lo"]) >= 0)
        z_hi = jnp.where(z_hi_a, alpha, jnp.where(z_flip, s["lo"], s["hi"]))
        z_v_hi = jnp.where(z_hi_a, v, jnp.where(z_flip, s["v_lo"], s["v_hi"]))
        z_lo = jnp.where(z_hi_a, s["lo"], alpha)
        z_v_lo = jnp.where(z_hi_a, s["v_lo"], v)
        z_d_lo = jnp.where(z_hi_a, s["d_lo"], dg)
        z_exhaust = (jnp.abs(z_hi - z_lo) < 1e-12) | \
            (s["zj"] + 1 >= max_evals)

        phase = jnp.where(
            in_bracket,
            jnp.where(b_done | b_exhaust, 2,
                      jnp.where(enter_zoom, 1, 0)),
            jnp.where(z_done | z_exhaust, 2, 1)).astype(jnp.int32)

        # zoom bracket: freshly entered from bracket phase, or updated
        lo = jnp.where(in_bracket,
                       jnp.where(b_zoom_a, s["alpha_prev"], alpha),
                       z_lo)
        v_lo = jnp.where(in_bracket,
                         jnp.where(b_zoom_a, s["v_prev"], v), z_v_lo)
        d_lo = jnp.where(in_bracket,
                         jnp.where(b_zoom_a, s["d_prev"], dg), z_d_lo)
        hi = jnp.where(in_bracket,
                       jnp.where(b_zoom_a, alpha, s["alpha_prev"]),
                       z_hi)
        v_hi = jnp.where(in_bracket,
                         jnp.where(b_zoom_a, v, s["v_prev"]), z_v_hi)

        # result: bracket records only on termination; zoom records
        # every eval (the host zoom's running ``best``)
        set_res = jnp.where(in_bracket, b_done | b_exhaust, True)
        new = dict(
            phase=phase,
            evals=s["evals"] + 1,
            bi=s["bi"] + in_bracket.astype(jnp.int32),
            zj=s["zj"] + (~in_bracket).astype(jnp.int32),
            alpha_prev=jnp.where(in_bracket & b_cont, alpha,
                                 s["alpha_prev"]),
            v_prev=jnp.where(in_bracket & b_cont, v, s["v_prev"]),
            d_prev=jnp.where(in_bracket & b_cont, dg, s["d_prev"]),
            alpha_next=jnp.where(in_bracket & b_cont, alpha * 2.0,
                                 s["alpha_next"]),
            lo=lo, hi=hi, v_lo=v_lo, d_lo=d_lo, v_hi=v_hi,
            res_alpha=jnp.where(set_res, alpha, s["res_alpha"]),
            res_v=jnp.where(set_res, v, s["res_v"]),
            res_g=jax.tree_util.tree_map(
                lambda gn, gs: _select_bcast(set_res, gn, gs),
                g, s["res_g"]),
        )
        # per-model freeze: a lane whose search already terminated keeps its
        # state verbatim (the batched while runs until EVERY lane is done;
        # without the select its result would keep moving). Unbatched, the
        # while cond makes `live` trivially true — XLA folds the selects.
        live = s["phase"] < 2
        return {
            key: (jax.tree_util.tree_map(
                lambda nv, ov: _select_bcast(live, nv, ov),
                nv_, s[key]) if key == "res_g"
                else _select_bcast(live, nv_, s[key]))
            for key, nv_ in new.items()
        }

    final = jax.lax.while_loop(cond, body, state)
    return (final["res_alpha"], final["res_v"], final["res_g"],
            final["evals"])


_scale_rows = None


def _get_scale_rows():
    global _scale_rows
    if _scale_rows is None:
        import jax
        # .astype(x.dtype): the standardized copy stays IN the data tier —
        # a bf16 block scaled by an f32/f64 vector would otherwise promote
        # and re-materialize X at 2-4x its storage width
        _scale_rows = jax.jit(lambda x, s: (x * s).astype(x.dtype))
    return _scale_rows


_center_scale_rows = None


def _get_center_scale_rows():
    global _center_scale_rows
    if _center_scale_rows is None:
        import jax
        _center_scale_rows = jax.jit(
            lambda x, s, mu: ((x - mu) * s).astype(x.dtype))
    return _center_scale_rows


def inv_std_vector(features_std: np.ndarray) -> np.ndarray:
    """1/σ per feature with zero-variance features excluded to 0 — the one
    place the reference's exclusion rule (LogisticRegression.scala:649
    featuresStd != 0 guard) is encoded."""
    return np.where(features_std > 0, 1.0 / np.where(
        features_std > 0, features_std, 1.0), 0.0)


def standardize_dataset(ds: InstanceDataset, features_std: np.ndarray,
                        center_mean: Optional[np.ndarray] = None):
    """Scale feature blocks by 1/std in HBM (≈ the reference persisting
    standardized blocks, LogisticRegression.scala:968). Zero-variance
    features scale to 0, matching the reference's exclusion.

    ``center_mean`` additionally centers: x̂ = (x − μ)/σ — the reference's
    ``fitWithMean`` conditioning fix (SPARK-34448,
    LogisticRegression.scala:946-955). The reference implements centering
    as a margin offset inside the aggregator to keep sparse blocks sparse;
    this dense tier centers the (already dense) standardized copy
    directly, which is the same objective with the same memory footprint
    and keeps the aggregator program-cache identity. Padded rows carry
    w=0, so their shifted values never contribute.

    Returns (standardized dataset, inv_std)."""
    import jax
    import jax.numpy as jnp

    inv_std = inv_std_vector(features_std)
    if center_mean is not None:
        scaled = _get_center_scale_rows()(
            ds.x, jnp.asarray(inv_std), jnp.asarray(center_mean))
    else:
        scaled = _get_scale_rows()(ds.x, jnp.asarray(inv_std))
    return ds.derive(x=scaled), inv_std


def validate_binary_labels(y: np.ndarray, what: str) -> None:
    """Reject anything outside {0, 1} — catches the ±1 SVM convention that
    would silently corrupt margin-based losses (the aggregators map y via
    2y−1)."""
    bad = ~np.isin(y, (0.0, 1.0))
    if bad.any():
        raise ValueError(
            f"{what} requires labels in {{0, 1}}, found "
            f"{np.unique(y[bad])[:5]}")


def l2_regularization(reg_param: float, d: int, fit_intercept: bool,
                      features_std: Optional[np.ndarray] = None,
                      standardize: bool = True) -> Optional[Callable]:
    if standardize:
        # cached: a stable fn (and .traceable) identity per parameter set is
        # what lets the device line-search program cache hit across fits
        return _l2_standardized(float(reg_param), int(d), bool(fit_intercept))
    return _l2_regularization(reg_param, d, fit_intercept, features_std,
                              standardize)


@functools.lru_cache(maxsize=None)
def _l2_standardized(reg_param: float, d: int, fit_intercept: bool):
    return _l2_regularization(reg_param, d, fit_intercept, None, True)


def _l2_regularization(reg_param: float, d: int, fit_intercept: bool,
                       features_std: Optional[np.ndarray] = None,
                       standardize: bool = True) -> Optional[Callable]:
    """L2 penalty matching the reference's L2RegFunction semantics
    (ref: ml/optim/regularizer — applied to feature coefficients only, never
    the intercept; when ``standardization=false`` the penalty is computed in
    the ORIGINAL feature space even though training runs in standardized
    space, i.e. each β_j is divided by std_j before squaring).

    The coef vector passed in is in standardized space (β_std = β_orig·std).
    """
    if reg_param == 0.0:
        return None
    std = None
    if not standardize:
        if features_std is None:
            raise ValueError("features_std required when standardization=false")
        std = np.where(features_std > 0, features_std, 1.0)

    def make(xp):
        def fn(coef):
            beta = coef[:d]
            if std is None:
                loss = 0.5 * reg_param * xp.dot(beta, beta)
                gbeta = reg_param * beta
            else:
                s = xp.asarray(std, dtype=coef.dtype)
                b = beta / s
                loss = 0.5 * reg_param * xp.dot(b, b)
                gbeta = reg_param * beta / (s * s)
            grad = xp.concatenate(
                [gbeta, xp.zeros(coef.shape[0] - d, dtype=coef.dtype)])
            return loss, grad
        return fn

    fn = make(np)
    # jnp twin for inlining inside jitted programs (device line search)
    import jax.numpy as jnp
    fn.traceable = make(jnp)
    # introspection for paths that re-derive the penalty in another layout
    # (the feature-sharded line search applies reg directly to its sharded
    # beta slice — only valid for the standardized, uniform-λ penalty)
    fn.reg_param = float(reg_param)
    fn.is_standardized = std is None
    return fn
