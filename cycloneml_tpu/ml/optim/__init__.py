from cycloneml_tpu.ml.optim.lbfgs import LBFGS, LBFGSB, OWLQN, OptimState
from cycloneml_tpu.ml.optim import aggregators

__all__ = ["LBFGS", "LBFGSB", "OWLQN", "OptimState", "aggregators"]
