from cycloneml_tpu.ml.optim.lbfgs import LBFGS, LBFGSB, OWLQN, OptimState
from cycloneml_tpu.ml.optim.wls import (WeightedLeastSquares,
                                        WeightedLeastSquaresModel)
from cycloneml_tpu.ml.optim import aggregators

__all__ = ["LBFGS", "LBFGSB", "OWLQN", "OptimState", "WeightedLeastSquares",
           "WeightedLeastSquaresModel", "aggregators"]
