from cycloneml_tpu.ml.optim.lbfgs import LBFGS, OWLQN, OptimState
from cycloneml_tpu.ml.optim import aggregators

__all__ = ["LBFGS", "OWLQN", "OptimState", "aggregators"]
