from cycloneml_tpu.ml.optim.lbfgs import LBFGS, LBFGSB, OWLQN, OptimState
from cycloneml_tpu.ml.optim.wls import (WeightedLeastSquares,
                                        WeightedLeastSquaresModel)
from cycloneml_tpu.ml.optim import aggregators

__all__ = ["LBFGS", "LBFGSB", "OWLQN", "OptimState", "WeightedLeastSquares",
           "WeightedLeastSquaresModel", "aggregators"]


def __getattr__(name):
    # stacked-fit engine entry points, imported lazily (they pull in the
    # device modules, which the light host-only users of this package —
    # e.g. the WLS normal-equation path — never need)
    if name in ("StackedDeviceLBFGS", "StackedOptimResult"):
        from cycloneml_tpu.ml.optim import device_lbfgs
        return getattr(device_lbfgs, name)
    if name in ("StackedGradientDescent", "GradientDescent"):
        from cycloneml_tpu.ml.optim import gradient_descent
        return getattr(gradient_descent, name)
    if name == "StackedDistributedLossFunction":
        from cycloneml_tpu.ml.optim import loss
        return loss.StackedDistributedLossFunction
    raise AttributeError(name)
