"""Driver-side quasi-Newton optimizers.

Re-implements the semantics of Breeze's ``LBFGS`` / ``OWLQN`` as used by the
reference's estimators (ref: ml/classification/LogisticRegression.scala:25
imports breeze LBFGS/OWLQN; createOptimizer:777-814; mllib/optimization/
LBFGS.scala:37 runLBFGS:183) — NOT a port of Breeze: a clean
Nocedal–Wright L-BFGS with strong-Wolfe line search (what Breeze's
``StrongWolfeLineSearch`` implements), two-loop recursion with history
m=10 (Spark's default ``aggregationDepth``-independent corrections), initial
Hessian scaling γ = sᵀy/yᵀy, and Breeze-compatible convergence tests
(max iterations; relative function-value improvement ≤ tol; gradient-norm
ratio). OWL-QN adds the L1 pseudo-gradient and orthant projection.

The loss/grad callable is typically the jit-compiled mesh aggregation
(psum over ICI); optimizer state stays on the host in float64 — exactly the
reference's driver-side Breeze arrangement (SURVEY §3.3).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Tuple

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

LossGrad = Callable[[np.ndarray], Tuple[float, np.ndarray]]


@dataclass
class OptimState:
    x: np.ndarray
    value: float
    grad: np.ndarray
    iteration: int = 0
    converged: bool = False
    converged_reason: str = ""
    loss_history: List[float] = field(default_factory=list)
    # curvature memory, carried so training can checkpoint/resume EXACTLY
    # (the reference has no mid-training checkpointing at all — SURVEY §5.4
    # flags step-level checkpoint as the required improvement)
    hist_s: List[np.ndarray] = field(default_factory=list)
    hist_y: List[np.ndarray] = field(default_factory=list)
    raw_grad: Optional[np.ndarray] = None  # OWLQN: grad before pseudo-grad

    def to_pytree(self) -> dict:
        return {"x": self.x, "value": self.value, "grad": self.grad,
                "iteration": self.iteration,
                "converged": self.converged,
                "converged_reason": self.converged_reason,
                "loss_history": list(self.loss_history),
                "hist_s": list(self.hist_s), "hist_y": list(self.hist_y),
                "raw_grad": self.raw_grad}

    @classmethod
    def from_pytree(cls, t: dict) -> "OptimState":
        return cls(x=np.asarray(t["x"]), value=float(t["value"]),
                   grad=np.asarray(t["grad"]), iteration=int(t["iteration"]),
                   converged=bool(t.get("converged", False)),
                   converged_reason=str(t.get("converged_reason", "")),
                   loss_history=[float(v) for v in t["loss_history"]],
                   hist_s=[np.asarray(s) for s in t["hist_s"]],
                   hist_y=[np.asarray(y) for y in t["hist_y"]],
                   raw_grad=(np.asarray(t["raw_grad"])
                             if t.get("raw_grad") is not None else None))


class _History:
    """L-BFGS curvature-pair memory (two-loop recursion)."""

    def __init__(self, m: int):
        self.m = m
        self.s: List[np.ndarray] = []
        self.y: List[np.ndarray] = []

    def update(self, s: np.ndarray, y: np.ndarray) -> None:
        # curvature condition: keep the pair only if sᵀy is safely positive
        if float(np.dot(s, y)) > 1e-10 * float(np.dot(y, y)):
            self.s.append(s)
            self.y.append(y)
            if len(self.s) > self.m:
                self.s.pop(0)
                self.y.pop(0)

    def direction(self, grad: np.ndarray) -> np.ndarray:
        q = grad.copy()
        k = len(self.s)
        alpha = np.empty(k)
        rho = np.empty(k)
        for i in range(k - 1, -1, -1):
            rho[i] = 1.0 / np.dot(self.y[i], self.s[i])
            alpha[i] = rho[i] * np.dot(self.s[i], q)
            q -= alpha[i] * self.y[i]
        if k > 0:
            gamma = np.dot(self.s[-1], self.y[-1]) / np.dot(self.y[-1], self.y[-1])
            q *= gamma
        for i in range(k):
            beta = rho[i] * np.dot(self.y[i], q)
            q += (alpha[i] - beta) * self.s[i]
        return -q


def _strong_wolfe(f: LossGrad, x: np.ndarray, value: float, grad: np.ndarray,
                  direction: np.ndarray, init_alpha: float = 1.0,
                  c1: float = 1e-4, c2: float = 0.9,
                  max_evals: int = 30) -> Tuple[float, float, np.ndarray]:
    """Strong-Wolfe line search (Nocedal & Wright alg. 3.5/3.6 — the scheme
    Breeze's StrongWolfeLineSearch follows). Returns (alpha, f(x+αd), g)."""

    d_dot_g0 = float(np.dot(direction, grad))
    if d_dot_g0 >= 0:
        raise ValueError("direction is not a descent direction")

    # fused path: a DistributedLossFunction runs the whole bracket+zoom
    # search in ONE device dispatch (vs one dispatch per phi eval here)
    fused = getattr(f, "device_line_search", None)
    if fused is not None:
        out = fused(x, direction, value, d_dot_g0, init_alpha,
                    c1, c2, max_evals)
        if out is not None:
            return out

    def phi(alpha: float):
        v, g = f(x + alpha * direction)
        return v, g, float(np.dot(direction, g))

    def zoom(lo, hi, v_lo, d_lo, v_hi):
        best = None
        for _ in range(max_evals):
            # cubic-safe bisection (Breeze uses interpolation; bisection keeps
            # the same Wolfe guarantees and is deterministic)
            alpha = 0.5 * (lo + hi)
            v, g, dg = phi(alpha)
            if v > value + c1 * alpha * d_dot_g0 or v >= v_lo:
                hi, v_hi = alpha, v
            else:
                if abs(dg) <= -c2 * d_dot_g0:
                    return alpha, v, g
                if dg * (hi - lo) >= 0:
                    hi, v_hi = lo, v_lo
                lo, v_lo, d_lo = alpha, v, dg
            best = (alpha, v, g)
            if abs(hi - lo) < 1e-12:
                break
        return best

    alpha_prev, v_prev, d_prev = 0.0, value, d_dot_g0
    alpha = init_alpha
    for i in range(max_evals):
        v, g, dg = phi(alpha)
        if v > value + c1 * alpha * d_dot_g0 or (i > 0 and v >= v_prev):
            out = zoom(alpha_prev, alpha, v_prev, d_prev, v)
            if out is None:
                break
            return out
        if abs(dg) <= -c2 * d_dot_g0:
            return alpha, v, g
        if dg >= 0:
            out = zoom(alpha, alpha_prev, v, dg, v_prev)
            if out is None:
                break
            return out
        alpha_prev, v_prev, d_prev = alpha, v, dg
        alpha *= 2.0
    # fall back to the last evaluated point if Wolfe could not be satisfied
    v, g, _ = phi(alpha)
    return alpha, v, g


def _reopen(resume: OptimState, max_iter: int) -> OptimState:
    """'max iterations reached' is a budget stop, not convergence: a resumed
    run with a larger budget continues (real convergence reasons hold)."""
    import dataclasses
    if (resume.converged
            and resume.converged_reason == "max iterations reached"
            and resume.iteration < max_iter):
        return dataclasses.replace(resume, converged=False,
                                   converged_reason="")
    return resume


class LBFGS:
    """Limited-memory BFGS (Breeze-LBFGS semantics).

    Convergence mirrors Breeze's FirstOrderMinimizer checks used by the
    reference: maxIter; |Δf| ≤ tol·max(|f|,|f'|,1e-6) (relative improvement);
    ‖g‖/max(‖x‖,1) ≤ tol-ish gradient test.
    """

    def __init__(self, max_iter: int = 100, m: int = 10, tol: float = 1e-6,
                 grad_tol: Optional[float] = None):
        self.max_iter = max_iter
        self.m = m
        self.tol = tol
        self.grad_tol = grad_tol if grad_tol is not None else tol

    def _converged(self, state: OptimState, f_old: float) -> Optional[str]:
        if state.iteration >= self.max_iter:
            return "max iterations reached"
        denom = max(abs(state.value), abs(f_old), 1e-6)
        if abs(f_old - state.value) <= self.tol * denom:
            return "function value converged"
        gnorm = float(np.linalg.norm(state.grad))
        if gnorm <= self.grad_tol * max(float(np.linalg.norm(state.x)), 1.0):
            return "gradient converged"
        return None

    def iterations(self, f: LossGrad, x0: np.ndarray,
                   resume: Optional[OptimState] = None):
        """Generator of OptimState per iteration (like Breeze .iterations).
        Pass a checkpointed ``resume`` state to continue exactly where a
        previous run stopped (same curvature memory → identical trajectory)."""
        hist = _History(self.m)
        if resume is not None:
            state = _reopen(resume, self.max_iter)
            hist.s = [np.asarray(s) for s in resume.hist_s]
            hist.y = [np.asarray(y) for y in resume.hist_y]
        else:
            x = np.asarray(x0, dtype=np.float64).copy()
            value, grad = f(x)
            state = OptimState(x=x, value=float(value),
                               grad=np.asarray(grad, dtype=np.float64))
            state.loss_history.append(state.value)
        yield state
        if state.converged:
            return  # resumed from a finished checkpoint: nothing to do
        while True:
            d = hist.direction(state.grad)
            init_alpha = 1.0 if state.iteration > 0 else \
                min(1.0, 1.0 / max(float(np.linalg.norm(state.grad)), 1e-12))
            try:
                alpha, v_new, g_new = _strong_wolfe(
                    f, state.x, state.value, state.grad, d, init_alpha)
            except ValueError:
                hist = _History(self.m)  # reset on non-descent (Breeze retries)
                d = -state.grad
                alpha, v_new, g_new = _strong_wolfe(
                    f, state.x, state.value, state.grad, d,
                    min(1.0, 1.0 / max(float(np.linalg.norm(state.grad)), 1e-12)))
            x_new = state.x + alpha * d
            g_new = np.asarray(g_new, dtype=np.float64)
            hist.update(x_new - state.x, g_new - state.grad)
            f_old = state.value
            state = OptimState(
                x=x_new, value=float(v_new), grad=g_new,
                iteration=state.iteration + 1,
                loss_history=state.loss_history + [float(v_new)],
                hist_s=list(hist.s), hist_y=list(hist.y))
            reason = self._converged(state, f_old)
            if reason is not None:
                state.converged = True
                state.converged_reason = reason
            yield state
            if state.converged:
                return

    def minimize(self, f: LossGrad, x0: np.ndarray,
                 resume: Optional[OptimState] = None) -> OptimState:
        state = None
        for state in self.iterations(f, x0, resume=resume):
            pass
        return state


class LBFGSB(LBFGS):
    """Box-constrained L-BFGS (Breeze-LBFGSB semantics — the optimizer the
    reference selects whenever coefficient bounds are set,
    ref LogisticRegression.scala:788 ``new BreezeLBFGSB(lowerBounds,
    upperBounds, ...)``).

    Projected-gradient formulation: the quasi-Newton direction is built from
    the PROJECTED gradient (components at an active bound pointing outward
    are clipped to zero), every line-search trial point is projected into
    the box, and convergence tests use the projected gradient — the same
    fixed points as Byrd-Lu-Nocedal-Zhu without its generalized-Cauchy
    subspace machinery (scipy's L-BFGS-B is the parity oracle in tests).

    Line searches run on the HOST (one device dispatch per φ evaluation):
    the box projection sits between the optimizer and the loss, so the fused
    device-resident search does not apply. That still beats the reference's
    structure — Breeze LBFGSB is host-driven with one Spark job per
    evaluation — but bounded fits cost more dispatches per iteration than
    unbounded ones.
    """

    def __init__(self, lower: np.ndarray, upper: np.ndarray,
                 max_iter: int = 100, m: int = 10, tol: float = 1e-6,
                 grad_tol: Optional[float] = None):
        super().__init__(max_iter, m, tol, grad_tol)
        self.lower = np.asarray(lower, dtype=np.float64)
        self.upper = np.asarray(upper, dtype=np.float64)
        if np.any(self.lower > self.upper):
            raise ValueError("lower bound exceeds upper bound")

    def _clip(self, x: np.ndarray) -> np.ndarray:
        return np.clip(x, self.lower, self.upper)

    def _projected_grad(self, x: np.ndarray, grad: np.ndarray) -> np.ndarray:
        """Gradient with active-bound components pointing outward zeroed
        (the 'gradient clipping at active bounds' of the reference's
        bound-constrained path)."""
        at_lo = (x <= self.lower) & (grad > 0)
        at_hi = (x >= self.upper) & (grad < 0)
        return np.where(at_lo | at_hi, 0.0, grad)

    def iterations(self, f: LossGrad, x0: np.ndarray,
                   resume: Optional[OptimState] = None):
        hist = _History(self.m)
        if resume is not None:
            state = _reopen(resume, self.max_iter)
            hist.s = [np.asarray(s) for s in resume.hist_s]
            hist.y = [np.asarray(y) for y in resume.hist_y]
            raw_grad = (np.asarray(resume.raw_grad)
                        if resume.raw_grad is not None else resume.grad)
        else:
            x = self._clip(np.asarray(x0, dtype=np.float64))
            value, grad = f(x)
            raw_grad = np.asarray(grad, dtype=np.float64)
            state = OptimState(x=x, value=float(value),
                               grad=self._projected_grad(x, raw_grad),
                               raw_grad=raw_grad)
            state.loss_history.append(state.value)
            if not np.any(state.grad):
                # the (clipped) start is already a KKT point of the box —
                # degenerate bounds (lower == upper) land here too
                state.converged = True
                state.converged_reason = "gradient converged"
        yield state
        if state.converged:
            return
        while True:
            if not np.any(state.grad):
                import dataclasses
                state = dataclasses.replace(
                    state, converged=True,
                    converged_reason="gradient converged")
                yield state
                return
            d = hist.direction(state.grad)
            # zero direction components that would immediately leave the box
            at_lo = (state.x <= self.lower) & (d < 0)
            at_hi = (state.x >= self.upper) & (d > 0)
            d = np.where(at_lo | at_hi, 0.0, d)
            if not np.any(d):
                d = -state.grad

            def f_boxed(xt: np.ndarray):
                xt = self._clip(xt)
                v, g = f(xt)
                return float(v), np.asarray(g, dtype=np.float64)

            init_alpha = 1.0 if state.iteration > 0 else \
                min(1.0, 1.0 / max(float(np.linalg.norm(state.grad)), 1e-12))
            try:
                alpha, v_new, g_new = _strong_wolfe(
                    f_boxed, state.x, state.value, state.grad, d, init_alpha)
            except ValueError:
                hist = _History(self.m)
                d = -state.grad
                alpha, v_new, g_new = _strong_wolfe(
                    f_boxed, state.x, state.value, state.grad, d,
                    min(1.0, 1.0 / max(float(np.linalg.norm(state.grad)),
                                       1e-12)))
            x_new = self._clip(state.x + alpha * d)
            raw_grad_new = np.asarray(g_new, dtype=np.float64)
            pg_new = self._projected_grad(x_new, raw_grad_new)
            # reduced-space curvature: pairs are only meaningful within one
            # face of the box. When the active set changes, old pairs
            # describe a different subspace — drop them (the classic
            # active-set restart); within a face, mask y to the free
            # coordinates so the two-loop recursion models the reduced
            # Hessian (s is already zero at active coordinates).
            active_new = (x_new <= self.lower) | (x_new >= self.upper)
            active_old = (state.x <= self.lower) | (state.x >= self.upper)
            if not np.array_equal(active_new, active_old):
                hist = _History(self.m)
            else:
                free = ~active_new
                hist.update((x_new - state.x) * free,
                            (raw_grad_new - raw_grad) * free)
            f_old = state.value
            raw_grad = raw_grad_new
            state = OptimState(
                x=x_new, value=float(v_new), grad=pg_new,
                iteration=state.iteration + 1,
                loss_history=state.loss_history + [float(v_new)],
                hist_s=list(hist.s), hist_y=list(hist.y),
                raw_grad=raw_grad_new)
            reason = self._converged(state, f_old)
            if reason is not None:
                state.converged = True
                state.converged_reason = reason
            yield state
            if state.converged:
                return


class OWLQN(LBFGS):
    """Orthant-wise limited-memory quasi-Newton for L1 regularization
    (Breeze-OWLQN semantics; selected by the reference when elasticNet has an
    L1 component, ref LogisticRegression.scala:814).

    ``l1_reg`` may be a scalar or per-coordinate array (the reference passes
    0 for the intercept and per-feature values under standardization).
    """

    def __init__(self, max_iter: int = 100, m: int = 10, tol: float = 1e-6,
                 l1_reg=0.0):
        super().__init__(max_iter, m, tol)
        self.l1_reg = l1_reg

    def _l1(self, x: np.ndarray) -> float:
        return float(np.sum(np.abs(x) * self.l1_reg))

    def _pseudo_grad(self, x: np.ndarray, grad: np.ndarray) -> np.ndarray:
        """Sub-gradient of f + λ‖x‖₁ choosing the steepest-descent element."""
        lam = np.broadcast_to(np.asarray(self.l1_reg, dtype=np.float64), x.shape)
        pg = np.where(x > 0, grad + lam, np.where(x < 0, grad - lam, 0.0))
        at_zero = (x == 0)
        pg = np.where(at_zero & (grad + lam < 0), grad + lam, pg)
        pg = np.where(at_zero & (grad - lam > 0), grad - lam, pg)
        return pg

    def iterations(self, f: LossGrad, x0: np.ndarray,
                   resume: Optional[OptimState] = None):
        hist = _History(self.m)
        if resume is not None:
            state = _reopen(resume, self.max_iter)
            x = np.asarray(resume.x, dtype=np.float64)
            hist.s = [np.asarray(s) for s in resume.hist_s]
            hist.y = [np.asarray(y) for y in resume.hist_y]
            raw_grad = (np.asarray(resume.raw_grad)
                        if resume.raw_grad is not None else resume.grad)
        else:
            x = np.asarray(x0, dtype=np.float64).copy()
            value, grad = f(x)
            value = float(value) + self._l1(x)
            grad = np.asarray(grad, dtype=np.float64)
            state = OptimState(x=x, value=value,
                               grad=self._pseudo_grad(x, grad), raw_grad=grad)
            state.loss_history.append(state.value)
            raw_grad = grad
        yield state
        if state.converged:
            return  # resumed from a finished checkpoint: nothing to do
        while True:
            d = hist.direction(state.grad)
            # project direction onto the pseudo-gradient descent orthant
            d = np.where(d * state.grad >= 0, 0.0, d) if self._has_l1() else d
            if not np.any(d):
                d = -state.grad
            orthant = np.where(x != 0, np.sign(x), -np.sign(state.grad))

            def f_projected(xt: np.ndarray):
                xt = np.where(xt * orthant >= 0, xt, 0.0)  # orthant projection
                v, g = f(xt)
                return float(v) + self._l1(xt), np.asarray(g, dtype=np.float64)

            init_alpha = 1.0 if state.iteration > 0 else \
                min(1.0, 1.0 / max(float(np.linalg.norm(state.grad)), 1e-12))
            try:
                alpha, v_new, g_new = _strong_wolfe(
                    f_projected, state.x, state.value, state.grad, d, init_alpha,
                    c2=0.99)  # Breeze OWLQN relaxes curvature
            except ValueError:
                d = -state.grad
                alpha, v_new, g_new = _strong_wolfe(
                    f_projected, state.x, state.value, state.grad, d,
                    min(1.0, 1.0 / max(float(np.linalg.norm(state.grad)), 1e-12)),
                    c2=0.99)
            x_new = state.x + alpha * d
            x_new = np.where(x_new * orthant >= 0, x_new, 0.0)
            raw_grad_new = g_new
            pg_new = self._pseudo_grad(x_new, raw_grad_new)
            hist.update(x_new - state.x, raw_grad_new - raw_grad)
            f_old = state.value
            x = x_new
            raw_grad = raw_grad_new
            state = OptimState(
                x=x_new, value=float(v_new), grad=pg_new,
                iteration=state.iteration + 1,
                loss_history=state.loss_history + [float(v_new)],
                hist_s=list(hist.s), hist_y=list(hist.y),
                raw_grad=raw_grad_new)
            reason = self._converged(state, f_old)
            if reason is not None:
                state.converged = True
                state.converged_reason = reason
            yield state
            if state.converged:
                return

    def _has_l1(self) -> bool:
        return bool(np.any(np.asarray(self.l1_reg) > 0))
