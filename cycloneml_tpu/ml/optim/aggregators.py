"""Differentiable block aggregators.

Replaces the reference's ``ml/optim/aggregator/*`` family
(ref: BinaryLogisticBlockAggregator.scala:41 with its forward ``gemv:97`` and
transpose-gemv backward ``:130``; siblings Multinomial, LeastSquares, Hinge,
Huber under ml/optim/aggregator/) with pure JAX functions over instance
blocks. The per-block math is identical — margins via a block matmul (MXU),
multipliers, gradient via the transpose matmul — but written once as a loss
whose gradient ``jax.grad`` (or the hand-derived closed form below, kept for
clarity and exact parity) produces.

Every aggregator has signature ``(x, y, w, coef) -> {"loss","grad","count"}``
where ``x:(b,d) y:(b,) w:(b,)`` is a (shard of a) block with zero-weight
padding rows and ``coef`` is the flat parameter vector. They are summed
across the mesh by ``collectives.tree_aggregate`` — the treeAggregate
replacement (ref RDDLossFunction.scala:61). Losses/gradients are SUMS, not
means; the caller divides by weightSum exactly like the reference.

Layout conventions (match the reference's flat coefficient layout):
- binary logistic / linear / hinge: ``[w_0..w_{d-1}, intercept?]``
- multinomial: ``[W.flatten(order=C) (k,d), intercepts(k)?]``
- huber: ``[w_0..w_{d-1}, intercept?, sigma]``
"""

from __future__ import annotations

import functools
from typing import Callable, Dict

import jax
import jax.numpy as jnp

Agg = Callable[..., Dict[str, jnp.ndarray]]

def matmul_precision():
    """Matmul precision for the aggregator hot path, resolved from
    ``cyclone.compute.matmulPrecision`` when an aggregator is BUILT (each
    fit builds its aggregators, so a session change applies to the next
    fit). See the config entry's doc for the measured guidance: 'highest'
    is both the parity choice AND at least as fast for the gemv-shaped
    binary path on v5e (HBM-bound); 'default' exists for MXU-bound shapes
    like wide multinomial."""
    from cycloneml_tpu import context as _c
    from cycloneml_tpu.conf import CycloneConf, MATMUL_PRECISION
    conf = (_c._active_context.conf if _c._active_context is not None
            else CycloneConf())
    # a ValueError from an invalid setting must surface — silently falling
    # back would make the misconfiguration invisible for every fit
    name = conf.get(MATMUL_PRECISION)
    return (jax.lax.Precision.DEFAULT if name == "default"
            else jax.lax.Precision.HIGHEST)


def _split_coef(coef, d, fit_intercept):
    if fit_intercept:
        return coef[:d], coef[d]
    return coef, jnp.zeros((), coef.dtype)


def _narrow(dt) -> bool:
    from cycloneml_tpu.dataset.instance import is_narrow_dtype
    return is_narrow_dtype(dt)


def _tier_dot(a, b, prec, acc=None):
    """``jnp.dot`` across the data/accumulator tier boundary.

    Full-width (f32/f64) operands take the pre-tier path UNCHANGED — the
    ``cyclone.data.dtype=float32`` opt-out is bit-identical by
    construction. When either operand is narrow (bf16/f16/fp8 data tier),
    the other is cast DOWN to the storage width (dtype promotion would
    otherwise upcast — and re-materialize — the whole X block) and the dot
    accumulates into ``acc`` via ``preferred_element_type``: narrow
    multiplicands, fp32 accumulation — the Micikevicius et al. (2018)
    mixed-precision recipe, natively an MXU bf16×bf16→f32 matmul on TPU.
    ``acc`` defaults to the full-width operand's dtype (the optimizer's
    accumulator tier: f32, or f64 under x64).

    The fp8 rung (``float8_e4m3fn``) rides the SAME recipe one step
    narrower: X holds per-column-scaled e4m3 codes (the scale folds into
    the replicated ``inv_std`` operand — dequant-in-kernel, no wide X
    anywhere), and the vector operand (coefficients forward, multipliers
    backward) is cast to e4m3 per evaluation. That cast is the fp8 tier's
    accuracy boundary — ~2^-4 relative rounding per element, NaN past
    ±448 (e4m3fn has no inf) — which is exactly what the per-fit envelope
    probe (``instance.fp8_probe_ok``) and the bf16 fallback police; the
    byte ledger (``costs.sweep_cost``) is why no in-graph clamp exists
    here: any extra (n,)-pass would cost the very bytes the tier saves.
    When the two operands sit in DIFFERENT narrow tiers (fp8 X against a
    bf16 label stack), the dot runs at the NARROWEST width — bf16→e4m3 is
    the only lossy direction, and it is the one the recipe already takes
    for f32 operands.
    """
    if not (_narrow(a.dtype) or _narrow(b.dtype)):
        return jnp.dot(a, b, precision=prec)
    if acc is None:
        acc = b.dtype if _narrow(a.dtype) else a.dtype
        if _narrow(acc):
            acc = jnp.float32
    if _narrow(a.dtype) and _narrow(b.dtype):
        nt = a.dtype if (jnp.dtype(a.dtype).itemsize
                         <= jnp.dtype(b.dtype).itemsize) else b.dtype
    else:
        nt = a.dtype if _narrow(a.dtype) else b.dtype
    return jnp.dot(a.astype(nt), b.astype(nt), precision=prec,
                   preferred_element_type=acc)


def binary_logistic(d: int, fit_intercept: bool = True) -> Agg:
    """Binomial logistic loss (ref BinaryLogisticBlockAggregator.scala:41).

    loss_i = w_i * (softplus(m_i) - y_i * m_i) with margin m = x·β + β₀ —
    algebraically the same stable form the reference branches on label.
    """
    return _binary_logistic(d, fit_intercept, matmul_precision())


@functools.lru_cache(maxsize=None)
def _binary_logistic(d: int, fit_intercept: bool, prec) -> Agg:
    # factories are lru-cached on their semantic parameters so repeated fits
    # hand tree_aggregate the SAME function object — program-cache identity
    # (collectives._program_cache) is what prevents a recompile per fit

    def agg(x, y, w, coef):
        beta, b0 = _split_coef(coef, d, fit_intercept)
        margin = _tier_dot(x, beta, prec) + b0                  # forward gemv:97
        loss = jnp.sum(w * (jax.nn.softplus(margin) - y * margin))
        multiplier = w * (jax.nn.sigmoid(margin) - y)          # :112 multiplier
        g = _tier_dot(x.T, multiplier, prec)                    # backward gemv:130
        grad = jnp.concatenate([g, jnp.sum(multiplier)[None]]) if fit_intercept else g
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


def binary_logistic_scaled(d: int, fit_intercept: bool = True) -> Agg:
    """Binomial logistic loss over RAW feature blocks with standardization
    folded into the read: margin = x·(inv_std∘β̂) − scaled_mean·β̂ + β₀ and
    grad_β̂ = inv_std∘(xᵀmult) − scaled_mean·Σmult are algebraically the
    aggregation over x̂ = (x−μ)/σ without EVER materializing x̂ — the
    standardized copy (2× the HBM working set and one full read+write
    pass per fit) disappears (r3 verdict item 4: "fold standardization
    into the aggregator read"; the reference instead persists scaled
    instance blocks, LogisticRegression.scala:968).

    Signature: ``agg(x, y, w, inv_std, scaled_mean, coef)`` — inv_std and
    scaled_mean ride as REPLICATED arguments (not closure constants), so
    the compiled program is reused across datasets. Pass
    ``scaled_mean=zeros`` when not centering (fitWithMean off).
    """
    return _binary_logistic_scaled(d, fit_intercept, matmul_precision())


@functools.lru_cache(maxsize=None)
def _binary_logistic_scaled(d: int, fit_intercept: bool, prec) -> Agg:

    def agg(x, y, w, inv_std, scaled_mean, coef):
        beta, b0 = _split_coef(coef, d, fit_intercept)
        sb = inv_std * beta
        margin = (_tier_dot(x, sb, prec)
                  - jnp.dot(scaled_mean, beta, precision=prec) + b0)
        loss = jnp.sum(w * (jax.nn.softplus(margin) - y * margin))
        multiplier = w * (jax.nn.sigmoid(margin) - y)
        msum = jnp.sum(multiplier)
        g = (inv_std * _tier_dot(x.T, multiplier, prec)
             - scaled_mean * msum)
        grad = jnp.concatenate([g, msum[None]]) if fit_intercept else g
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


def multinomial_logistic(d: int, k: int, fit_intercept: bool = True) -> Agg:
    """Softmax cross-entropy over k classes with k full coefficient vectors
    (ref MultinomialLogisticBlockAggregator.scala; the reference also keeps
    all k vectors rather than k-1, making the problem over-parameterised
    exactly like this)."""
    return _multinomial_logistic(d, k, fit_intercept, matmul_precision())


@functools.lru_cache(maxsize=None)
def _multinomial_logistic(d: int, k: int, fit_intercept: bool, prec) -> Agg:

    def agg(x, y, w, coef):
        if fit_intercept:
            wmat = coef[: d * k].reshape(k, d)
            b = coef[d * k:]
        else:
            wmat = coef.reshape(k, d)
            b = jnp.zeros((k,), coef.dtype)
        margins = _tier_dot(x, wmat.T, prec) + b                # (bsz, k)
        log_z = jax.nn.logsumexp(margins, axis=1)
        y_idx = y.astype(jnp.int32)
        picked = jnp.take_along_axis(margins, y_idx[:, None], axis=1)[:, 0]
        loss = jnp.sum(w * (log_z - picked))
        probs = jax.nn.softmax(margins, axis=1)
        onehot = jax.nn.one_hot(y_idx, k, dtype=probs.dtype)  # {0,1} exact; fp8 x refuses implicit promotion
        mult = w[:, None] * (probs - onehot)                   # (bsz, k)
        gw = _tier_dot(mult.T, x, prec)                         # (k, d)
        if fit_intercept:
            grad = jnp.concatenate([gw.reshape(-1), jnp.sum(mult, axis=0)])
        else:
            grad = gw.reshape(-1)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


def multinomial_logistic_scaled(d: int, k: int,
                                fit_intercept: bool = True) -> Agg:
    """Multinomial twin of :func:`binary_logistic_scaled`: softmax
    cross-entropy over RAW feature blocks with standardization (and
    fitWithMean centering) folded into the read — margins are
    x·(W∘inv_std)ᵀ − W·scaled_mean + b, gradients unscale per class. The
    standardized copy never materializes for multinomial fits either."""
    return _multinomial_logistic_scaled(d, k, fit_intercept,
                                        matmul_precision())


@functools.lru_cache(maxsize=None)
def _multinomial_logistic_scaled(d: int, k: int, fit_intercept: bool,
                                 prec) -> Agg:

    def agg(x, y, w, inv_std, scaled_mean, coef):
        if fit_intercept:
            wmat = coef[: d * k].reshape(k, d)
            b = coef[d * k:]
        else:
            wmat = coef.reshape(k, d)
            b = jnp.zeros((k,), coef.dtype)
        wmat_s = wmat * inv_std[None, :]
        offset = jnp.dot(wmat, scaled_mean, precision=prec)      # (k,)
        margins = (_tier_dot(x, wmat_s.T, prec)
                   - offset[None, :] + b)                        # (bsz, k)
        log_z = jax.nn.logsumexp(margins, axis=1)
        y_idx = y.astype(jnp.int32)
        picked = jnp.take_along_axis(margins, y_idx[:, None], axis=1)[:, 0]
        loss = jnp.sum(w * (log_z - picked))
        probs = jax.nn.softmax(margins, axis=1)
        onehot = jax.nn.one_hot(y_idx, k, dtype=probs.dtype)  # {0,1} exact; fp8 x refuses implicit promotion
        mult = w[:, None] * (probs - onehot)                     # (bsz, k)
        msum = jnp.sum(mult, axis=0)                             # (k,)
        gw = (_tier_dot(mult.T, x, prec) * inv_std[None, :]
              - msum[:, None] * scaled_mean[None, :])            # (k, d)
        if fit_intercept:
            grad = jnp.concatenate([gw.reshape(-1), msum])
        else:
            grad = gw.reshape(-1)
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


def least_squares(d: int, fit_intercept: bool = True) -> Agg:
    """Squared loss ½ w (x·β + β₀ − y)² (ref LeastSquaresBlockAggregator)."""
    return _least_squares(d, fit_intercept, matmul_precision())


@functools.lru_cache(maxsize=None)
def _least_squares(d: int, fit_intercept: bool, prec) -> Agg:

    def agg(x, y, w, coef):
        beta, b0 = _split_coef(coef, d, fit_intercept)
        err = _tier_dot(x, beta, prec) + b0 - y
        loss = 0.5 * jnp.sum(w * err * err)
        mult = w * err
        g = _tier_dot(x.T, mult, prec)
        grad = jnp.concatenate([g, jnp.sum(mult)[None]]) if fit_intercept else g
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


def least_squares_scaled(d: int) -> Agg:
    """Least-squares twin of :func:`binary_logistic_scaled`: squared loss
    over RAW feature blocks with the doubly-standardized objective folded
    into the read. The LinearRegression l-bfgs path trains on
    x̂ = (x−μ)/σ_x (centered only when fitting an intercept) against
    ŷ = y/σ_y − ȳ̂; with ``sb = inv_std∘β`` the residual is

      err = x·sb − (μ̂·β − ȳ̂) − y·(1/σ_y)        (μ̂ = scaled mean; the
                                                 whole centering is a scalar
                                                 offset outside the row pass)
      grad_β̂ = inv_std∘(xᵀmult) − μ̂·Σmult

    so neither the standardized X copy nor the scaled-y copy ever
    materializes — the fit's HBM working set is the raw data tier itself.

    Signature ``agg(x, y, w, inv_std, scaled_mean, y_pars, coef)`` with
    ``y_pars = [1/σ_y, ȳ̂]`` riding as a replicated (2,) runtime argument
    (program identity is dataset-generic, like inv_std/scaled_mean). Pass
    ``scaled_mean = zeros`` and ``y_pars[1] = 0`` for the no-intercept
    (uncentered) objective. No intercept coordinate exists: the intercept
    is recovered in closed form ȳ − β·x̄ after optimization.
    """
    return _least_squares_scaled(d, matmul_precision())


@functools.lru_cache(maxsize=None)
def _least_squares_scaled(d: int, prec) -> Agg:

    def agg(x, y, w, inv_std, scaled_mean, y_pars, coef):
        sb = inv_std * coef
        off = jnp.dot(scaled_mean, coef, precision=prec) - y_pars[1]
        err = _tier_dot(x, sb, prec) - off - y * y_pars[0]
        loss = 0.5 * jnp.sum(w * err * err)
        mult = w * err
        msum = jnp.sum(mult)
        g = inv_std * _tier_dot(x.T, mult, prec) - scaled_mean * msum
        return {"loss": loss, "grad": g, "count": jnp.sum(w)}

    return agg


def hinge(d: int, fit_intercept: bool = True) -> Agg:
    """Hinge loss for LinearSVC (ref HingeBlockAggregator): labels in {0,1}
    mapped to ±1 as 2y−1; loss_i = w_i max(0, 1 − ŷ_i m_i)."""
    return _hinge(d, fit_intercept, matmul_precision())


@functools.lru_cache(maxsize=None)
def _hinge(d: int, fit_intercept: bool, prec) -> Agg:

    def agg(x, y, w, coef):
        beta, b0 = _split_coef(coef, d, fit_intercept)
        margin = _tier_dot(x, beta, prec) + b0
        ysign = 2.0 * y - 1.0
        active = (1.0 - ysign * margin) > 0
        loss = jnp.sum(w * jnp.maximum(0.0, 1.0 - ysign * margin))
        mult = jnp.where(active, -ysign * w, 0.0)
        g = _tier_dot(x.T, mult, prec)
        grad = jnp.concatenate([g, jnp.sum(mult)[None]]) if fit_intercept else g
        return {"loss": loss, "grad": grad, "count": jnp.sum(w)}

    return agg


def huber(d: int, fit_intercept: bool = True, epsilon: float = 1.35) -> Agg:
    """Huber loss with jointly-optimised scale σ (ref HuberBlockAggregator,
    following Owen 2007 as the reference does): coef = [β, β₀?, σ];
    loss_i = w_i (σ + ℓ_ε((y−μ)/σ) σ)."""
    return _huber(d, fit_intercept, float(epsilon), matmul_precision())


@functools.lru_cache(maxsize=None)
def _huber(d: int, fit_intercept: bool, epsilon: float, prec) -> Agg:

    def agg(x, y, w, coef):
        beta, b0 = _split_coef(coef[:-1], d, fit_intercept)
        sigma = coef[-1]
        mu = _tier_dot(x, beta, prec) + b0
        r = (y - mu) / sigma
        abs_r = jnp.abs(r)
        outlier = abs_r > epsilon
        loss_i = jnp.where(
            outlier,
            sigma + (2.0 * epsilon * abs_r - epsilon * epsilon) * sigma,
            sigma + r * r * sigma)
        loss = jnp.sum(w * loss_i)
        # d/dmu and d/dsigma — matches the reference's piecewise gradients
        dmu = jnp.where(outlier, -2.0 * epsilon * jnp.sign(r), -2.0 * r)
        mult = w * dmu
        g = _tier_dot(x.T, mult, prec)
        dsig_i = jnp.where(outlier,
                           1.0 - epsilon * epsilon,
                           1.0 - r * r)
        dsig = jnp.sum(w * dsig_i)
        parts = [g]
        if fit_intercept:
            parts.append(jnp.sum(mult)[None])
        parts.append(dsig[None])
        return {"loss": loss, "grad": jnp.concatenate(parts), "count": jnp.sum(w)}

    return agg


@functools.lru_cache(maxsize=None)
def stack_aggregator(agg: Agg) -> Agg:
    """Model-axis twin of a plain ``(x, y, w, coef)`` aggregator.

    ``vmap`` pushes a leading model axis through the block matmuls
    mechanically (Frostig, Johnson & Leary, SysML 2018): the stacked twin
    takes a ``(b, K)`` label matrix (axis 1 — labels stay ROW-sharded like
    every other dataset array) and ``(K, n_coef)`` coefficients, with
    ``x``/``w`` shared, and returns ``{loss (K,), grad (K, n_coef),
    count (K,)}`` — so ``tree_aggregate`` reduces all K models' partials in
    ONE psum with a leading model axis. lru-cached on the base aggregator so
    repeated stacked fits keep program-cache identity (one XLA compile per
    (mesh, K, shapes), amortized over all K models)."""
    return jax.vmap(agg, in_axes=(None, 1, None, 0))


@functools.lru_cache(maxsize=None)
def stack_scaled_aggregator(agg: Agg) -> Agg:
    """Model-axis twin of a scaled aggregator
    ``(x, y, w, inv_std, scaled_mean, coef)`` (standardization folded into
    the read): labels vmap over axis 1, coefficients over axis 0, everything
    else — including the shared standardization vectors — broadcasts."""
    return jax.vmap(agg, in_axes=(None, 1, None, None, None, 0))


def autodiff_check(agg_loss_only: Callable, d: int):
    """Return jax.grad of a loss-only aggregator — used in tests to verify the
    hand-derived gradients above (SURVEY §7 step 5: 'where jax.grad can
    replace hand-written gradients (verify parity!)')."""
    return jax.grad(agg_loss_only)


def binary_logistic_pallas_scaled(d: int, fit_intercept: bool = True) -> Agg:
    """Pallas twin of :func:`binary_logistic_scaled`: raw feature blocks,
    standardization folded around the kernel's row pass
    (ops/kernels.fused_binary_logistic_scaled) — the kernel path no longer
    needs the standardized copy either."""
    return _binary_logistic_pallas_scaled(d, fit_intercept)


@functools.lru_cache(maxsize=None)
def _binary_logistic_pallas_scaled(d: int, fit_intercept: bool) -> Agg:
    from cycloneml_tpu.ops.kernels import fused_binary_logistic_scaled

    def agg(x, y, w, inv_std, scaled_mean, coef):
        return fused_binary_logistic_scaled(
            x, y, w, inv_std, scaled_mean, coef, d, fit_intercept)

    return agg


def least_squares_pallas_scaled(d: int) -> Agg:
    """Pallas twin of :func:`least_squares_scaled`: the residual sweep
    (margin → err → loss/mult/grad) runs as one VMEM-resident row pass
    (ops/kernels.fused_least_squares_scaled); standardization and the
    label scaling are algebra outside it, so the kernel reads the raw
    data-tier blocks exactly once per evaluation."""
    return _least_squares_pallas_scaled(d)


@functools.lru_cache(maxsize=None)
def _least_squares_pallas_scaled(d: int) -> Agg:
    from cycloneml_tpu.ops.kernels import fused_least_squares_scaled

    def agg(x, y, w, inv_std, scaled_mean, y_pars, coef):
        return fused_least_squares_scaled(
            x, y, w, inv_std, scaled_mean, y_pars, coef, d)

    return agg
