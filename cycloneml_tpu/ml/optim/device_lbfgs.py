"""Device-resident chunked L-BFGS.

The host optimizer (``lbfgs.LBFGS``) pays one device dispatch per
iteration even with the fused line search — through a TPU relay that is
~70-200 ms of pure latency per L-BFGS step while the gradient math itself
takes single-digit milliseconds. This module runs WHOLE CHUNKS of K
iterations inside one jitted program: the two-loop recursion over a
fixed-size (m, n) curvature ring buffer, the strong-Wolfe search
(``loss.wolfe_search`` — the same traced state machine the per-iteration
fused path uses), the curvature-condition history update, and the
Breeze-style convergence tests all stay on device; the host sees one
dispatch and one small readback per chunk.

Structure beaten, not emulated: the reference pays one Spark JOB per loss
evaluation (RDDLossFunction.scala:56) — ~30 jobs per iteration; the host
path here pays 1 dispatch per iteration; this path pays 1/K.

Semantics match ``lbfgs.LBFGS`` (same Wolfe machine, same two-loop, same
curvature condition sᵀy > 1e-10·yᵀy, same convergence tests) computed in
the accumulator tier's dtype — f64 under the CPU test config (trajectories match
the host path), f32 on TPU (last-ulp drift; the convergence thresholds are
~1e-6 relative, within f32's resolution for these well-scaled problems).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from cycloneml_tpu.ml.optim.lbfgs import LBFGS, OptimState
from cycloneml_tpu.observe import attribution, costs, tracing
from cycloneml_tpu.parallel.collectives import BoundedProgramCache
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_program_cache = BoundedProgramCache(32)


def _budget_guarded_chunk(name: str, key, prog, args, chunk: int, ctx,
                          build, allow_stream: bool = False):
    """Compile-time memory budget guard for a chunk program: harvest its
    predicted peak HBM (XLA memory_analysis via observe/costs.py), post
    ``MemoryBudgetExceeded`` when it exceeds ``cyclone.memory.budgetFraction``
    × device memory, and degrade to a smaller chunk instead of OOMing.

    ``allow_stream=True`` declares that the CALLER has an out-of-core
    fallback (estimators set ``DeviceLBFGS.oocore_fallback``): when the
    halving bottoms out at chunk 1 with the program still over budget and
    ``cyclone.oocore.mode`` permits, the guard raises
    ``costs.OutOfCoreRequired`` — the estimator catches it and re-routes
    the fit through the streaming epoch engine instead of warn-proceeding
    (or raising under ``budgetAction=raise``). Direct optimizer users
    (no fallback declared) keep the pre-oocore warn/raise contract.

    Much of the footprint is chunk-INDEPENDENT (data arrays, coefficients,
    curvature history), so a proportional guess is only a starting point:
    each candidate is rebuilt via ``build(chunk)`` and RE-ANALYZED, and the
    loop caps every guess at half the previous chunk so it makes progress
    even when shrinking barely helps, terminating at chunk 1 (per-iteration
    dispatches — warn-only proceeds there even if still over budget; there
    is no smaller program to degrade to). Chunk size never changes the
    trajectory (chunk-size-invariance tests), only dispatch granularity.

    Returns ``(chunk, key, prog, fresh)`` — unchanged inputs when the
    guard is disarmed, the backend reports nothing, or the budget holds.
    """
    fresh = None
    conf = getattr(ctx, "conf", None)
    if conf is None or not costs.guard_armed(conf):
        return chunk, key, prog, fresh
    bus = getattr(ctx, "listener_bus", None)
    pid = costs.ensure(name, key, prog, args)
    # degradation comes FIRST even under budgetAction=raise: raising is
    # the terminal escalation once no smaller chunk remains, not a veto
    # on the degradation the guard exists to perform
    verdict = costs.check_budget(pid, conf=conf, bus=bus, allow_raise=False)
    while verdict is not None and verdict.exceeded and chunk > 1:
        new_chunk = min(costs.select_chunk(chunk, verdict.predicted_bytes,
                                           verdict.budget_bytes),
                        max(1, chunk // 2))
        logger.warning(
            "%s: predicted peak HBM %d B/device over budget %d B — "
            "degrading deviceChunk %d -> %d",
            name, verdict.predicted_bytes, verdict.budget_bytes, chunk,
            new_chunk)
        chunk = new_chunk
        key, prog, fresh = build(chunk)
        pid = costs.ensure(name, key, prog, args)
        verdict = costs.check_budget(pid, conf=conf, bus=bus,
                                     allow_raise=False)
    if verdict is not None and verdict.exceeded:
        if allow_stream:
            from cycloneml_tpu.oocore.engine import degrade_allowed
            if degrade_allowed(ctx):
                # graceful at any data:memory ratio: the estimator owns a
                # streaming twin of this fit — hand the decision back up
                # instead of warn-proceeding toward an OOM or raising
                raise costs.OutOfCoreRequired(name, verdict)
        if verdict.action == "raise":
            raise costs.MemoryBudgetError(
                f"{name}: still {verdict.predicted_bytes} bytes/device over "
                f"the {verdict.budget_bytes}-byte budget at deviceChunk "
                f"{chunk} — no smaller program to degrade to "
                f"(cyclone.memory.budgetAction=raise)")
        logger.warning(
            "%s: still %d B/device over the %d B budget at deviceChunk %d — "
            "proceeding (warn-only); the footprint is dominated by "
            "chunk-independent state", name, verdict.predicted_bytes,
            verdict.budget_bytes, chunk)
    return chunk, key, prog, fresh


def _build_chunk(compiled, l2_t, m: int, K: int, c1: float, c2: float,
                 max_ls: int, cdt: np.dtype, *, n_arrays: int):
    """jit program: K L-BFGS iterations on device.

    Args: (*arrays, coef, S, Y, k_hist, f0, g0, first, ws, tol, grad_tol,
    it_limit, need_init) → (coef, S, Y, k_hist, f, g, losses(K), n_iters,
    evals, converged_code, f0, g0). ``l2_t`` is the penalty's jnp twin
    (``l2_regularization(...).traceable``) — the SAME implementation the
    fused line search inlines, so the two device paths cannot drift.

    The big state operands — coef ``(n,)`` and the two ``(m, n)``
    curvature ring buffers plus the gradient — are DONATED: each chunk
    consumes the previous chunk's output, so the old buffers are dead the
    moment the dispatch leaves the host (graftlint JX009 is the static
    safety net for exactly this discipline). XLA aliases them onto the
    matching outputs, shaving ``2·m·n + 2·n`` accumulator-width elements
    off the program's peak HBM — visible as an ``hbm_peak_bytes`` drop in
    the cost rollup (`alias_size_in_bytes` is subtracted at the
    observe/costs.py waist). ``n_arrays`` positions the donated argnums
    past the data arrays, which are REUSED across dispatches and must
    never be donated.
    """
    import jax
    import jax.numpy as jnp

    from cycloneml_tpu.ml.optim.loss import wolfe_search

    def program(*args):
        (arrays, coef0, S0, Y0, k0, f_in, g_in, first,
         ws, tol, grad_tol, it_limit, need_init) = \
            (args[:-12], *args[-12:])

        def f_and_g(coef):
            out = compiled(*arrays, coef)
            loss = (out["loss"] / ws).astype(cdt)
            grad = (out["grad"] / ws).astype(cdt)
            if l2_t is not None:
                rl, rg = l2_t(coef)
                loss = loss + rl
                grad = grad + rg
            return loss, grad

        def two_loop(S, Y, k, g):
            idxs_bwd = jnp.arange(m - 1, -1, -1)

            def bwd(q, i):
                valid = i >= m - k
                sy = jnp.dot(Y[i], S[i])
                rho = jnp.where(valid, 1.0 / jnp.where(valid, sy, 1.0), 0.0)
                a = rho * jnp.dot(S[i], q)
                return q - a * Y[i], (a, rho)

            q, (alphas, rhos) = jax.lax.scan(bwd, g, idxs_bwd)
            last_sy = jnp.dot(S[m - 1], Y[m - 1])
            last_yy = jnp.dot(Y[m - 1], Y[m - 1])
            gamma = jnp.where(k > 0, last_sy / jnp.maximum(last_yy, 1e-300),
                              1.0)
            r = gamma * q

            def fwd(r, inp):
                i, a, rho = inp
                beta = rho * jnp.dot(Y[i], r)
                return r + (a - beta) * S[i], None

            # forward pass visits oldest→newest: reverse the bwd outputs
            r, _ = jax.lax.scan(
                fwd, r, (idxs_bwd[::-1], alphas[::-1], rhos[::-1]))
            return -r

        zero = cdt.type(0.0)

        def body(carry):
            (coef, S, Y, k, f, g, it, evals, done, losses) = carry
            d = two_loop(S, Y, k, g)
            dg0 = jnp.dot(d, g)
            # non-descent: reset history, steepest descent (host semantics)
            bad = dg0 >= 0
            d = jnp.where(bad, -g, d)
            k = jnp.where(bad, 0, k)
            dg0 = jnp.where(bad, -jnp.dot(g, g), dg0)
            gnorm = jnp.sqrt(jnp.maximum(jnp.dot(g, g), 1e-300))
            # host semantics: the scaled step min(1, 1/||g||) applies on the
            # very first iteration AND on every steepest-descent restart
            init_alpha = jnp.where(
                (first & (it == 0)) | bad,
                jnp.minimum(1.0, 1.0 / gnorm), cdt.type(1.0))

            def phi(alpha):
                v, grad = f_and_g(coef + alpha * d)
                return v, grad, jnp.dot(d, grad)

            alpha, f_new, g_new, ev = wolfe_search(
                phi, jnp.zeros_like(g), f, dg0, init_alpha,
                c1, c2, max_ls, cdt)
            s = alpha * d
            y = g_new - g
            # curvature condition (host _History.update)
            keep = jnp.dot(s, y) > 1e-10 * jnp.dot(y, y)
            S = jnp.where(keep, jnp.roll(S, -1, axis=0).at[-1].set(s), S)
            Y = jnp.where(keep, jnp.roll(Y, -1, axis=0).at[-1].set(y), Y)
            k = jnp.where(keep, jnp.minimum(k + 1, m), k)
            # Breeze-style convergence (host LBFGS._converged)
            denom = jnp.maximum(jnp.maximum(jnp.abs(f_new), jnp.abs(f)),
                                1e-6)
            f_conv = jnp.abs(f - f_new) <= tol * denom
            gn = jnp.sqrt(jnp.maximum(jnp.dot(g_new, g_new), 0.0))
            xn = jnp.sqrt(jnp.maximum(jnp.dot(coef + s, coef + s), 0.0))
            g_conv = gn <= grad_tol * jnp.maximum(xn, 1.0)
            code = jnp.where(f_conv, 1,
                             jnp.where(g_conv, 2, 0)).astype(jnp.int32)
            losses = losses.at[it].set(f_new)
            return (coef + s, S, Y, k, f_new, g_new, it + 1,
                    evals + ev, code, losses)

        def cond(carry):
            it, done = carry[6], carry[8]
            return (it < jnp.minimum(K, it_limit)) & (done == 0)

        # fused initial evaluation: a fresh fit computes f(x0)/∇f(x0) inside
        # THIS dispatch instead of paying a separate round trip for it
        f0, g0 = jax.lax.cond(need_init,
                              lambda: f_and_g(coef0),
                              lambda: (f_in, g_in))
        evals0 = jnp.where(need_init, 1, 0).astype(jnp.int32)
        losses0 = jnp.full((K,), jnp.nan, cdt)
        init = (coef0, S0, Y0, k0, f0, g0, jnp.int32(0), evals0,
                jnp.int32(0), losses0)
        (coef, S, Y, k, f, g, it, evals, code, losses) = \
            jax.lax.while_loop(cond, body, init)
        return coef, S, Y, k, f, g, losses, it, evals, code, f0, g0

    # donate the S/Y ring buffers (positions past the data arrays) — at
    # 2·m·n they dominate the optimizer state's HBM, and the driver only
    # ever exposes SLICES of them (hist_s/hist_y are fresh gather
    # outputs), so no caller can hold the donated buffers. coef/grad are
    # deliberately NOT donated: the generator yields them as
    # OptimState.x/.grad and the resilience retry/checkpoint path retains
    # those states across chunk dispatches — donating them would delete
    # the retained state's buffers behind the caller's back (exactly the
    # JX009 hazard class, one dispatch later)
    return jax.jit(program, donate_argnums=(n_arrays + 1, n_arrays + 2))


class DeviceLBFGS(LBFGS):
    """L-BFGS running ``chunk`` iterations per device dispatch.

    Works with a ``DistributedLossFunction`` over the dense tier whose L2
    term (if any) is the standardized uniform penalty — the same
    preconditions as the fused line search, checked by the caller
    (LogisticRegression selects this optimizer automatically when they
    hold and no checkpointing is requested; ``cyclone.ml.lbfgs.deviceChunk``
    sizes or disables it).
    """

    def __init__(self, max_iter: int = 100, m: int = 10, tol: float = 1e-6,
                 grad_tol: Optional[float] = None, chunk: int = 8,
                 c1: float = 1e-4, c2: float = 0.9, max_ls: int = 30):
        super().__init__(max_iter, m, tol, grad_tol)
        self.chunk = max(int(chunk), 1)
        self.c1, self.c2, self.max_ls = c1, c2, max_ls
        # set by estimators that own a streaming twin of the fit: lets the
        # budget guard raise OutOfCoreRequired (caught by the estimator)
        # when chunk-halving bottoms out still over budget
        self.oocore_fallback = False

    def iterations(self, f, x0: np.ndarray,
                   resume: Optional[OptimState] = None):
        import jax
        import jax.numpy as jnp

        arrays = f._agg_call.arrays()
        # optimizer state lives in the ACCUMULATOR tier (f32 / f64-under-
        # x64), never the possibly-bf16 data tier X is stored in
        from cycloneml_tpu.dataset.instance import compute_dtype
        cdt = np.dtype(compute_dtype())
        n = len(np.asarray(x0))
        l2_t = getattr(f.l2_reg_fn, "traceable", None) \
            if f.l2_reg_fn is not None else None
        if f.l2_reg_fn is not None and l2_t is None:
            raise ValueError(
                "DeviceLBFGS needs a regularizer with a traceable (jnp) "
                "twin; use the host LBFGS otherwise")
        chunk = self.chunk
        self.effective_chunk = chunk

        def build(k):
            key = ("lbfgs_chunk", f._agg_call.compiled, l2_t, self.m, k,
                   float(self.c1), float(self.c2), int(self.max_ls), cdt.str)
            prog = _program_cache.get(key)
            fresh = prog is None  # first dispatch pays trace + compile
            if fresh:
                prog = _build_chunk(f._agg_call.compiled, l2_t, self.m,
                                    k, self.c1, self.c2, self.max_ls, cdt,
                                    n_arrays=len(arrays))
                _program_cache.put(key, prog)
            return key, prog, fresh

        key, prog, fresh = build(chunk)

        if resume is not None:
            from cycloneml_tpu.ml.optim.lbfgs import _reopen
            state = _reopen(resume, self.max_iter)
            S = np.zeros((self.m, n), dtype=cdt)
            Y = np.zeros((self.m, n), dtype=cdt)
            hk = min(len(resume.hist_s), self.m)
            for i, (s_, y_) in enumerate(zip(resume.hist_s[-self.m:],
                                             resume.hist_y[-self.m:])):
                S[self.m - hk + i] = np.asarray(s_)
                Y[self.m - hk + i] = np.asarray(y_)
            k_hist = hk
            # iteration-0 resumes must keep the host path's scaled first
            # step (init_alpha = min(1, 1/||g||))
            first = state.iteration == 0
            need_init = False
            yield state
            if state.converged:
                return
            # jnp.array (copy=True), NOT asarray: a resume state may hand
            # us live device arrays; the copy keeps the generator's
            # working buffers disjoint from whatever the caller retains
            # (coef/grad are never donated — see _build_chunk — but the
            # resume contract shouldn't depend on that)
            coef = jnp.array(state.x, cdt)
            f_d = cdt.type(state.value)
            g_d = jnp.array(state.grad, cdt)
        else:
            # fresh fit: f(x0) is computed INSIDE the first chunk dispatch;
            # the iteration-0 state is yielded when that chunk returns
            state = None
            S = np.zeros((self.m, n), dtype=cdt)
            Y = np.zeros((self.m, n), dtype=cdt)
            k_hist = 0
            first = True
            need_init = True
            coef = jnp.asarray(np.asarray(x0, dtype=cdt))
            f_d = cdt.type(0.0)
            g_d = jnp.zeros(n, cdt)

        S_d, Y_d = jnp.asarray(S), jnp.asarray(Y)
        k_d = jnp.int32(k_hist)
        guarded = False
        pid = None
        while True:
            # big state (coef/S/Y/grad) stays ON DEVICE between chunks —
            # only scalars and the per-iteration loss vector come back per
            # dispatch; the full f64 state materializes on yield only when
            # a consumer touches the arrays (np.asarray forces the copy)
            base_iter = state.iteration if state is not None else 0
            args = (*arrays, coef, S_d, Y_d, k_d, f_d, g_d,
                    np.bool_(first), cdt.type(f.weight_sum),
                    cdt.type(self.tol), cdt.type(self.grad_tol),
                    np.int32(max(self.max_iter - base_iter, 0)),
                    np.bool_(need_init))
            if not guarded:
                # args are chunk-size-independent, so a degraded program
                # dispatches the same operands — only K shrinks
                guarded = True
                chunk, key, prog, new_fresh = _budget_guarded_chunk(
                    "lbfgs.chunk", key, prog, args, chunk,
                    getattr(f, "_ctx", None), build,
                    allow_stream=self.oocore_fallback)
                if new_fresh is not None:
                    fresh = new_fresh
                    self.effective_chunk = chunk
            win = attribution.dispatch_window()
            with win:
                with tracing.span("dispatch", "lbfgs.chunk") as dsp:
                    if fresh:
                        with tracing.span("compile", "lbfgs.chunk"):
                            (coef_d, S_d, Y_d, k_d, f_d, g_d, losses_d, it_d,
                             evals_d, code_d, f0_d, g0_d) = prog(*args)
                        fresh = False
                    else:
                        (coef_d, S_d, Y_d, k_d, f_d, g_d, losses_d, it_d,
                         evals_d, code_d, f0_d, g0_d) = prog(*args)
                    with tracing.span("transfer", "lbfgs.readback") as tsp:
                        f_h, losses, it, evals, code, k_h, f0_h = \
                            jax.device_get(
                                (f_d, losses_d, it_d, evals_d, code_d, k_d,
                                 f0_d))
                        tsp.annotate_bytes(
                            (f_h, losses, it, evals, code, k_h, f0_h))
                dsp.annotate(evals=int(evals))
                # cost harvest only under a FULL tracer OR a live
                # attribution window: the flight-recorder ring records
                # spans and must not pay an AOT analyze, but a scoped fit
                # buys the FLOPs/bytes join (shared registry, one harvest
                # per program either way)
                tr = tracing.full_active()
                if (tr is not None or win.live) and pid is None:
                    pid = costs.ensure("lbfgs.chunk", key, prog, args)
                win.annotate_program(pid)
                if tr is not None:
                    dsp.annotate(program=pid)
                    costs.note_execution(tr, pid)
            coef = coef_d
            first = False
            f.n_evals += int(evals)
            f.n_dispatches += 1
            if need_init:
                state = OptimState(
                    x=np.asarray(x0, np.float64).copy(),
                    value=float(f0_h), grad=g0_d,
                    loss_history=[float(f0_h)])
                need_init = False
                yield state
            n_new = int(it)
            losses = [float(v) for v in losses[:n_new]]
            hk = int(k_h)
            # device slices: no host transfer unless a consumer (the
            # checkpoint/resume path) actually reads them
            hist_s = [S_d[i] for i in range(self.m - hk, self.m)]
            hist_y = [Y_d[i] for i in range(self.m - hk, self.m)]
            state = OptimState(
                x=coef_d, value=float(f_h), grad=g_d,
                iteration=state.iteration + n_new,
                loss_history=state.loss_history + losses,
                hist_s=hist_s, hist_y=hist_y)
            if hasattr(f, "_ctx") and hasattr(f._ctx, "record_step"):
                f._ctx.record_step({"loss": state.value,
                                    "chunk_iterations": n_new})
            # precedence matches host _converged: a budget stop outranks
            # the value/gradient tests (the estimator's non-convergence
            # warning keys off this reason)
            if state.iteration >= self.max_iter:
                state.converged = True
                state.converged_reason = "max iterations reached"
            elif int(code) == 1:
                state.converged = True
                state.converged_reason = "function value converged"
            elif int(code) == 2:
                state.converged = True
                state.converged_reason = "gradient converged"
            if state.converged:
                # terminal state: hand back host-f64 arrays as the host
                # optimizer does
                state.x = np.asarray(coef_d, np.float64)
                state.grad = np.asarray(g_d, np.float64)
            yield state
            if state.converged:
                return
            f_d = cdt.type(f_h)


# -- stacked (model-axis) variant ---------------------------------------------

def _build_stacked_chunk(compiled, m: int, K_iters: int, c1: float, c2: float,
                         max_ls: int, cdt: np.dtype, *, n_arrays: int):
    """jit program: up to ``K_iters`` L-BFGS iterations for a STACK of
    models inside one dispatch.

    Every piece of optimizer state carries a leading model axis — coef
    ``(K, n)``, curvature ring buffers ``(K, m, n)``, per-model f/g/history
    count — and the objective is the stacked aggregation (one psum, model
    axis leading). The strong-Wolfe machine is ``loss.wolfe_search`` in its
    batched form: each model walks its own bracket+zoom trajectory in
    lockstep evaluation steps and freezes when ITS search terminates.
    Per-model convergence codes freeze early-converged models (state
    selected through unchanged) instead of stopping — or lockstepping —
    the rest; the chunk ends when every model converged or the iteration
    budget is spent.

    The L2 penalty is runtime data (``reg (K,)`` per model + the shared
    per-coordinate ``l2_scale``), NOT baked in, so one compiled program
    serves every reg vector (CV folds over a λ grid reuse one compile).

    Args: ``(*arrays, coef, S, Y, k_hist, f0, g0, first, ws, reg, l2s,
    tol, grad_tol, it_limit, need_init, code_in)`` →
    ``(coef, S, Y, k_hist, f, g, losses (K, K_iters), steps, iters (K,),
    evals (K,), evals_global, code (K,), f_init)``. ``code_in`` carries the
    previous chunk's per-model convergence codes back in — a model frozen
    in chunk t must START chunk t+1 frozen, or every chunk boundary would
    un-freeze it for one spurious iteration and the result would depend on
    the chunk size.
    """
    import jax
    import jax.numpy as jnp

    from cycloneml_tpu.ml.optim.loss import wolfe_search

    def two_loop_one(S, Y, k, g):
        idxs_bwd = jnp.arange(m - 1, -1, -1)

        def bwd(q, i):
            valid = i >= m - k
            sy = jnp.dot(Y[i], S[i])
            rho = jnp.where(valid, 1.0 / jnp.where(valid, sy, 1.0), 0.0)
            a = rho * jnp.dot(S[i], q)
            return q - a * Y[i], (a, rho)

        q, (alphas, rhos) = jax.lax.scan(bwd, g, idxs_bwd)
        last_sy = jnp.dot(S[m - 1], Y[m - 1])
        last_yy = jnp.dot(Y[m - 1], Y[m - 1])
        gamma = jnp.where(k > 0, last_sy / jnp.maximum(last_yy, 1e-300), 1.0)
        r = gamma * q

        def fwd(r, inp):
            i, a, rho = inp
            beta = rho * jnp.dot(Y[i], r)
            return r + (a - beta) * S[i], None

        r, _ = jax.lax.scan(
            fwd, r, (idxs_bwd[::-1], alphas[::-1], rhos[::-1]))
        return -r

    two_loop = jax.vmap(two_loop_one)

    def program(*args):
        (arrays, coef0, S0, Y0, k0, f_in, g_in, first,
         ws, reg, l2s, tol, grad_tol, it_limit, need_init, code_in) = \
            (args[:-15], *args[-15:])

        def f_and_g(coef):
            out = compiled(*arrays, coef)
            loss = (out["loss"] / ws).astype(cdt)
            grad = (out["grad"] / ws).astype(cdt)
            # runtime-data L2 (same math as l2_regularization's traceable
            # twin, vectorized over the model axis). A vmapped dot, not a
            # masked sum-reduce: it lowers like the serial twin's
            # ``jnp.dot(beta, beta)`` (zero intercept products are exact),
            # so stacked and serial trajectories stay bit-aligned instead
            # of flipping iterations at the convergence-tol boundary.
            loss = loss + 0.5 * reg * jax.vmap(jnp.dot)(coef * l2s[None, :],
                                                        coef)
            grad = grad + reg[:, None] * coef * l2s[None, :]
            return loss, grad

        def body(carry):
            (coef, S, Y, k, f, g, step, iters, ev_pm, ev_g, code,
             losses) = carry
            live = code == 0
            d = two_loop(S, Y, k, g)
            dg0 = jnp.sum(d * g, axis=1)
            bad = dg0 >= 0
            d = jnp.where(bad[:, None], -g, d)
            k = jnp.where(bad, 0, k)
            gg = jnp.sum(g * g, axis=1)
            dg0 = jnp.where(bad, -gg, dg0)
            gnorm = jnp.sqrt(jnp.maximum(gg, 1e-300))
            init_alpha = jnp.where(
                (first & (step == 0)) | bad,
                jnp.minimum(1.0, 1.0 / gnorm), cdt.type(1.0)).astype(cdt)

            def phi(alpha):
                v, grad = f_and_g(coef + alpha[:, None] * d)
                return v, grad, jnp.sum(d * grad, axis=1)

            alpha, f_new, g_new, ev = wolfe_search(
                phi, jnp.zeros_like(g), f, dg0, init_alpha,
                c1, c2, max_ls, cdt, active=live)
            s_vec = alpha[:, None] * d
            y_vec = g_new - g
            keep = live & (jnp.sum(s_vec * y_vec, axis=1)
                           > 1e-10 * jnp.sum(y_vec * y_vec, axis=1))
            S = jnp.where(keep[:, None, None],
                          jnp.roll(S, -1, axis=1).at[:, -1].set(s_vec), S)
            Y = jnp.where(keep[:, None, None],
                          jnp.roll(Y, -1, axis=1).at[:, -1].set(y_vec), Y)
            k = jnp.where(keep, jnp.minimum(k + 1, m), k)
            denom = jnp.maximum(jnp.maximum(jnp.abs(f_new), jnp.abs(f)),
                                1e-6)
            f_conv = jnp.abs(f - f_new) <= tol * denom
            gn = jnp.sqrt(jnp.maximum(jnp.sum(g_new * g_new, axis=1), 0.0))
            xn = jnp.sqrt(jnp.maximum(
                jnp.sum((coef + s_vec) ** 2, axis=1), 0.0))
            g_conv = gn <= grad_tol * jnp.maximum(xn, 1.0)
            code_new = jnp.where(f_conv, 1,
                                 jnp.where(g_conv, 2, 0)).astype(jnp.int32)
            losses = losses.at[:, step].set(
                jnp.where(live, f_new, jnp.nan).astype(cdt))
            return (jnp.where(live[:, None], coef + s_vec, coef),
                    S, Y, k,
                    jnp.where(live, f_new, f),
                    jnp.where(live[:, None], g_new, g),
                    step + 1,
                    iters + live.astype(jnp.int32),
                    ev_pm + ev,
                    ev_g + jnp.max(ev),
                    jnp.where(live, code_new, code),
                    losses)

        def cond(carry):
            step, code = carry[6], carry[10]
            return (step < jnp.minimum(K_iters, it_limit)) \
                & jnp.any(code == 0)

        K = coef0.shape[0]
        f_init, g_init = jax.lax.cond(need_init,
                                      lambda: f_and_g(coef0),
                                      lambda: (f_in, g_in))
        ev0 = jnp.where(need_init, 1, 0).astype(jnp.int32)
        init = (coef0, S0, Y0, k0, f_init, g_init, jnp.int32(0),
                jnp.zeros((K,), jnp.int32), jnp.full((K,), ev0),
                ev0, code_in,
                jnp.full((K, K_iters), jnp.nan, cdt))
        (coef, S, Y, k, f, g, step, iters, ev_pm, ev_g, code, losses) = \
            jax.lax.while_loop(cond, body, init)
        return (coef, S, Y, k, f, g, losses, step, iters, ev_pm, ev_g,
                code, f_init)

    # donate the FULL stacked state — coef (K,n), S/Y (K,m,n), g (K,n):
    # unlike the serial generator, minimize() is not resumable and never
    # yields mid-run, so these buffers cannot be retained by a caller —
    # the driver rebinds all four from the outputs every chunk and the
    # inputs really are dead on dispatch; the (K,m,n) ring buffers
    # dominate the optimizer state's HBM at stacked widths
    return jax.jit(program, donate_argnums=(
        n_arrays, n_arrays + 1, n_arrays + 2, n_arrays + 5))


@dataclass
class StackedOptimResult:
    """Terminal state of one stacked fit: every field carries the model
    axis; histories/reasons are per model (the per-model analog of the
    serial path's OptimState + converged_reason)."""

    x: np.ndarray                       # (K, n) float64
    values: np.ndarray                  # (K,)
    iterations: np.ndarray              # (K,) int — per-model LIVE iters
    converged_reasons: List[str] = field(default_factory=list)
    loss_histories: List[List[float]] = field(default_factory=list)
    evals: Optional[np.ndarray] = None  # (K,) per-model loss/grad evals


class StackedDeviceLBFGS:
    """Chunked L-BFGS over a stack of K models sharing one design matrix.

    The model-axis variant of :class:`DeviceLBFGS`: one dispatch advances
    ALL models up to ``chunk`` iterations (batched objective = one psum with
    a leading model axis), per-model convergence masks freeze
    early-converged models on device, and the host sees one small readback
    per chunk. Preconditions match the serial chunked path: dense replicated
    tier, standardized-or-original-space uniform L2 carried as runtime data
    (``StackedDistributedLossFunction.reg``/``l2_scale``), no bounds/L1.
    """

    def __init__(self, max_iter: int = 100, m: int = 10, tol: float = 1e-6,
                 grad_tol: Optional[float] = None, chunk: int = 8,
                 c1: float = 1e-4, c2: float = 0.9, max_ls: int = 30):
        self.max_iter = max_iter
        self.m = m
        self.tol = tol
        self.grad_tol = grad_tol if grad_tol is not None else tol
        self.chunk = max(int(chunk), 1)
        self.c1, self.c2, self.max_ls = c1, c2, max_ls

    def minimize(self, f, x0: np.ndarray) -> StackedOptimResult:
        """``f`` is a ``StackedDistributedLossFunction``; ``x0`` is the
        (K, n_coef) stacked start point."""
        import jax
        import jax.numpy as jnp

        x0 = np.asarray(x0, dtype=np.float64)
        K, n = x0.shape
        if K != f.n_models:
            raise ValueError(
                f"x0 stacks {K} models but the loss carries {f.n_models}")
        arrays = f._agg_call.arrays()
        from cycloneml_tpu.dataset.instance import compute_dtype
        cdt = np.dtype(compute_dtype())  # accumulator tier, == w's dtype
        chunk = self.chunk
        self.effective_chunk = chunk

        def build(kc):
            key = ("stacked_lbfgs_chunk", f._agg_call.compiled, self.m,
                   kc, float(self.c1), float(self.c2), int(self.max_ls),
                   cdt.str)
            prog = _program_cache.get(key)
            fresh = prog is None
            if fresh:
                prog = _build_stacked_chunk(f._agg_call.compiled, self.m,
                                            kc, self.c1, self.c2,
                                            self.max_ls, cdt,
                                            n_arrays=len(arrays))
                _program_cache.put(key, prog)
            return key, prog, fresh

        key, prog, fresh = build(chunk)

        coef = jnp.asarray(x0.astype(cdt))
        S_d = jnp.zeros((K, self.m, n), cdt)
        Y_d = jnp.zeros((K, self.m, n), cdt)
        k_d = jnp.zeros((K,), jnp.int32)
        f_d = jnp.zeros((K,), cdt)
        g_d = jnp.zeros((K, n), cdt)
        reg_d = jnp.asarray(f.reg.astype(cdt))
        l2s = (f.l2_scale if f.l2_scale is not None else np.zeros(n))
        l2s_d = jnp.asarray(l2s.astype(cdt))
        first, need_init = True, True
        total_iter = 0
        iters_total = np.zeros(K, dtype=np.int64)
        evals_total = np.zeros(K, dtype=np.int64)
        histories: List[List[float]] = [[] for _ in range(K)]
        code_h = np.zeros(K, dtype=np.int64)
        guarded = False
        pid = None
        while True:
            args = (*arrays, coef, S_d, Y_d, k_d, f_d, g_d,
                    np.bool_(first), cdt.type(f.weight_sum), reg_d, l2s_d,
                    cdt.type(self.tol), cdt.type(self.grad_tol),
                    np.int32(max(self.max_iter - total_iter, 0)),
                    np.bool_(need_init),
                    code_h.astype(np.int32))
            if not guarded:
                guarded = True
                chunk, key, prog, new_fresh = _budget_guarded_chunk(
                    "lbfgs.stacked_chunk", key, prog, args, chunk,
                    getattr(f, "_ctx", None), build)
                if new_fresh is not None:
                    fresh = new_fresh
                    self.effective_chunk = chunk
            win = attribution.dispatch_window()
            with win:
                with tracing.span("dispatch", "lbfgs.stacked_chunk",
                                  n_models=K) as dsp:
                    if fresh:
                        with tracing.span("compile", "lbfgs.stacked_chunk"):
                            (coef, S_d, Y_d, k_d, f_d, g_d, losses_d, step_d,
                             it_d, ev_d, evg_d, code_d, f0_d) = prog(*args)
                        fresh = False
                    else:
                        (coef, S_d, Y_d, k_d, f_d, g_d, losses_d, step_d,
                         it_d, ev_d, evg_d, code_d, f0_d) = prog(*args)
                    with tracing.span("transfer", "lbfgs.readback") as tsp:
                        (losses, steps, iters, ev_pm, ev_g, code_h,
                         f0_h) = jax.device_get(
                            (losses_d, step_d, it_d, ev_d, evg_d, code_d,
                             f0_d))
                        tsp.annotate_bytes(
                            (losses, steps, iters, ev_pm, ev_g, code_h, f0_h))
                dsp.annotate(evals=int(ev_g))
                # full tracer only: no AOT analyze under the flight ring —
                # but a live attribution window buys the same one-time
                # harvest for the scope's FLOPs/bytes join
                tr = tracing.full_active()
                if (tr is not None or win.live) and pid is None:
                    pid = costs.ensure("lbfgs.stacked_chunk", key, prog,
                                       args)
                win.annotate_program(pid)
                if tr is not None:
                    dsp.annotate(program=pid)
                    costs.note_execution(tr, pid)
            f.n_evals += int(ev_g)
            f.n_dispatches += 1
            if need_init:
                for kk in range(K):
                    histories[kk].append(float(f0_h[kk]))
                need_init = False
            first = False
            for kk in range(K):
                for v in losses[kk, :int(steps)]:
                    if not np.isnan(v):
                        histories[kk].append(float(v))
            iters_total += np.asarray(iters, dtype=np.int64)
            evals_total += np.asarray(ev_pm, dtype=np.int64)
            total_iter += int(steps)
            if hasattr(f, "_ctx") and hasattr(f._ctx, "record_step"):
                f._ctx.record_step({
                    "loss": float(np.nanmean(losses[:, :max(int(steps), 1)]))
                    if int(steps) else float(np.mean(f0_h)),
                    "chunk_iterations": int(steps), "n_models": K})
            if (code_h != 0).all() or total_iter >= self.max_iter:
                break
        # budget stop outranks the value/gradient tests, as in the serial
        # paths (the estimator's non-convergence warning keys off this)
        reasons = []
        for kk in range(K):
            if code_h[kk] == 1:
                reasons.append("function value converged")
            elif code_h[kk] == 2:
                reasons.append("gradient converged")
            else:
                reasons.append("max iterations reached")
        return StackedOptimResult(
            x=np.asarray(coef, dtype=np.float64),
            values=np.asarray(f_d, dtype=np.float64),
            iterations=iters_total,
            converged_reasons=reasons,
            loss_histories=histories,
            evals=evals_total)


# -- streamed stacked L-BFGS: K host optimizers, one epoch per round ----------

def _phi_eval(x, direction, alpha):
    """One φ(α) evaluation as a sub-generator: yields the trial point,
    receives ``(value, grad)`` from the driver's batched evaluation."""
    v, g = yield x + alpha * direction
    g = np.asarray(g, dtype=np.float64)
    return float(v), g, float(np.dot(direction, g))


def _zoom_gen(x, direction, value, d_dot_g0, lo, hi, v_lo, d_lo, v_hi,
              c1, c2, max_evals):
    # lbfgs._strong_wolfe's zoom, verbatim, with phi as a yield point
    best = None
    for _ in range(max_evals):
        alpha = 0.5 * (lo + hi)
        v, g, dg = yield from _phi_eval(x, direction, alpha)
        if v > value + c1 * alpha * d_dot_g0 or v >= v_lo:
            hi, v_hi = alpha, v
        else:
            if abs(dg) <= -c2 * d_dot_g0:
                return alpha, v, g
            if dg * (hi - lo) >= 0:
                hi, v_hi = lo, v_lo
            lo, v_lo, d_lo = alpha, v, dg
        best = (alpha, v, g)
        if abs(hi - lo) < 1e-12:
            break
    return best


def _strong_wolfe_gen(x, value, grad, direction, init_alpha,
                      c1=1e-4, c2=0.9, max_evals=30):
    """Generator twin of ``lbfgs._strong_wolfe`` (bracket + bisection
    zoom, identical branch structure and constants) with every φ(α)
    evaluation a ``yield`` — so K concurrent searches can be serviced by
    ONE batched objective evaluation per round. There is deliberately no
    fused device path here: the streamed objective has none (each eval
    is an epoch), which is exactly why the searches batch across models
    instead."""
    d_dot_g0 = float(np.dot(direction, grad))
    if d_dot_g0 >= 0:
        raise ValueError("direction is not a descent direction")
    alpha_prev, v_prev, d_prev = 0.0, value, d_dot_g0
    alpha = init_alpha
    for i in range(max_evals):
        v, g, dg = yield from _phi_eval(x, direction, alpha)
        if v > value + c1 * alpha * d_dot_g0 or (i > 0 and v >= v_prev):
            out = yield from _zoom_gen(x, direction, value, d_dot_g0,
                                       alpha_prev, alpha, v_prev, d_prev, v,
                                       c1, c2, max_evals)
            if out is None:
                break
            return out
        if abs(dg) <= -c2 * d_dot_g0:
            return alpha, v, g
        if dg >= 0:
            out = yield from _zoom_gen(x, direction, value, d_dot_g0,
                                       alpha, alpha_prev, v, dg, v_prev,
                                       c1, c2, max_evals)
            if out is None:
                break
            return out
        alpha_prev, v_prev, d_prev = alpha, v, dg
        alpha *= 2.0
    v, g, _ = yield from _phi_eval(x, direction, alpha)
    return alpha, v, g


def _lbfgs_gen(x0, max_iter, m, tol, grad_tol, c1, c2, max_ls):
    """One model's L-BFGS as a coroutine: mirrors ``lbfgs.LBFGS``
    decision-for-decision (curvature condition, two-loop direction,
    init-alpha rule, non-descent reset-and-retry, Breeze convergence
    tests in the same precedence), with every loss/grad evaluation a
    ``yield x`` answered by ``send((value, grad))``. Identical (v, g)
    replies therefore reproduce the serial trajectory bit-for-bit —
    the streamed-stacked parity test pins exactly this. Returns
    ``(x, value, iterations, reason, loss_history)`` via StopIteration."""
    from cycloneml_tpu.ml.optim.lbfgs import _History
    x = np.asarray(x0, dtype=np.float64).copy()
    v, g = yield x
    value = float(v)
    grad = np.asarray(g, dtype=np.float64)
    loss_history = [value]
    hist = _History(m)
    iteration = 0
    while True:
        d = hist.direction(grad)
        init_alpha = 1.0 if iteration > 0 else \
            min(1.0, 1.0 / max(float(np.linalg.norm(grad)), 1e-12))
        try:
            alpha, v_new, g_new = yield from _strong_wolfe_gen(
                x, value, grad, d, init_alpha, c1, c2, max_ls)
        except ValueError:
            hist = _History(m)  # reset on non-descent (Breeze retries)
            d = -grad
            alpha, v_new, g_new = yield from _strong_wolfe_gen(
                x, value, grad, d,
                min(1.0, 1.0 / max(float(np.linalg.norm(grad)), 1e-12)),
                c1, c2, max_ls)
        x_new = x + alpha * d
        g_new = np.asarray(g_new, dtype=np.float64)
        hist.update(x_new - x, g_new - grad)
        f_old = value
        x, value, grad = x_new, float(v_new), g_new
        iteration += 1
        loss_history.append(value)
        # LBFGS._converged, same precedence: budget, then value, then grad
        if iteration >= max_iter:
            return x, value, iteration, "max iterations reached", \
                loss_history
        denom = max(abs(value), abs(f_old), 1e-6)
        if abs(f_old - value) <= tol * denom:
            return x, value, iteration, "function value converged", \
                loss_history
        gnorm = float(np.linalg.norm(grad))
        if gnorm <= grad_tol * max(float(np.linalg.norm(x)), 1.0):
            return x, value, iteration, "gradient converged", loss_history


class StackedHostLBFGS:
    """Host-driven L-BFGS over a stack of K models whose objective is
    EXPENSIVE per evaluation and cheap per model — the streamed regime,
    where one evaluation is a whole double-buffered epoch.

    K serial optimizers run as coroutines (:func:`_lbfgs_gen`); each
    round stacks their pending trial points into one ``(K, n)`` matrix
    and makes ONE call to the stacked objective
    (``StackedStreamingLossFunction`` — one epoch serves every model),
    then feeds each model its row back. A converged model's slot keeps
    repeating its terminal point (vmapped programs take no ragged axis;
    the replies are ignored), so total epochs = max over models of that
    model's serial eval count, not the sum — the per-model epoch cost
    drops ~K× for homogeneous grids. Device-chunked state never appears:
    unlike :class:`StackedDeviceLBFGS` this driver is pure host float64,
    which is what lets it ride an objective that is itself a host fold.
    """

    def __init__(self, max_iter: int = 100, m: int = 10, tol: float = 1e-6,
                 grad_tol: Optional[float] = None, c1: float = 1e-4,
                 c2: float = 0.9, max_ls: int = 30):
        self.max_iter = max_iter
        self.m = m
        self.tol = tol
        self.grad_tol = grad_tol if grad_tol is not None else tol
        self.c1, self.c2, self.max_ls = c1, c2, max_ls

    def minimize(self, f, x0: np.ndarray) -> StackedOptimResult:
        """``f`` maps a ``(K, n)`` stack to ``((K,), (K, n))`` host-f64
        loss/grad (the ``StackedStreamingLossFunction`` contract)."""
        x0 = np.asarray(x0, dtype=np.float64)
        K, n = x0.shape
        gens = [_lbfgs_gen(x0[kk], self.max_iter, self.m, self.tol,
                           self.grad_tol, self.c1, self.c2, self.max_ls)
                for kk in range(K)]
        pending = np.zeros((K, n))
        done: List[Optional[tuple]] = [None] * K
        evals = np.zeros(K, dtype=np.int64)
        for kk, gen in enumerate(gens):
            pending[kk] = next(gen)  # prime: first yield is the start point
        rounds = 0
        while any(d is None for d in done):
            with tracing.span("dispatch", "lbfgs.stacked_host",
                              n_models=K, round=rounds,
                              live=sum(d is None for d in done)):
                L, G = f(pending)
            rounds += 1
            for kk, gen in enumerate(gens):
                if done[kk] is not None:
                    continue  # frozen slot: reply ignored
                evals[kk] += 1
                try:
                    pending[kk] = gen.send(
                        (float(L[kk]), np.asarray(G[kk], dtype=np.float64)))
                except StopIteration as fin:
                    done[kk] = fin.value
                    pending[kk] = fin.value[0]  # terminal point rides along
        return StackedOptimResult(
            x=np.stack([d[0] for d in done]),
            values=np.asarray([d[1] for d in done], dtype=np.float64),
            iterations=np.asarray([d[2] for d in done], dtype=np.int64),
            converged_reasons=[d[3] for d in done],
            loss_histories=[list(d[4]) for d in done],
            evals=evals)
