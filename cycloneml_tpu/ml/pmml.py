"""PMML model export.

Analog of the reference's PMML support (ref: mllib/src/main/scala/org/apache/
spark/mllib/pmml/PMMLExportable.scala + pmml/export/
{GeneralizedLinearPMMLModelExport, LogisticRegressionPMMLModelExport,
KMeansPMMLModelExport}.scala — built on JPMML there; a direct PMML 4.2 XML
writer here, same document structure). Covered model families match the
reference's factory (PMMLModelExportFactory.scala:35): linear regression
(incl. the ridge/lasso parameterizations), binary logistic regression,
linear SVM, and k-means.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import List, Optional

import numpy as np

PMML_NS = "http://www.dmg.org/PMML-4_2"


def _root(description: str) -> ET.Element:
    root = ET.Element("PMML", {"version": "4.2", "xmlns": PMML_NS})
    header = ET.SubElement(root, "Header",
                           {"description": description})
    ET.SubElement(header, "Application",
                  {"name": "CycloneML-TPU", "version": "0.1"})
    return root


def _data_dictionary(root: ET.Element, n_features: int,
                     target: Optional[str] = None,
                     categorical_target: bool = False) -> List[str]:
    names = [f"field_{i}" for i in range(n_features)]
    dd = ET.SubElement(root, "DataDictionary",
                       {"numberOfFields": str(n_features + (1 if target else 0))})
    for n in names:
        ET.SubElement(dd, "DataField",
                      {"name": n, "optype": "continuous", "dataType": "double"})
    if target:
        ET.SubElement(dd, "DataField",
                      {"name": target,
                       "optype": ("categorical" if categorical_target
                                  else "continuous"),
                       "dataType": ("string" if categorical_target
                                    else "double")})
    return names


def _mining_schema(parent: ET.Element, names: List[str],
                   target: Optional[str] = None) -> None:
    ms = ET.SubElement(parent, "MiningSchema")
    for n in names:
        ET.SubElement(ms, "MiningField", {"name": n, "usageType": "active"})
    if target:
        ET.SubElement(ms, "MiningField",
                      {"name": target, "usageType": "predicted"})


def _regression_table(parent: ET.Element, names: List[str],
                      coef: np.ndarray, intercept: float,
                      target_category: Optional[str] = None) -> None:
    attrs = {"intercept": repr(float(intercept))}
    if target_category is not None:
        attrs["targetCategory"] = target_category
    table = ET.SubElement(parent, "RegressionTable", attrs)
    for n, c in zip(names, np.asarray(coef, dtype=float)):
        ET.SubElement(table, "NumericPredictor",
                      {"name": n, "coefficient": repr(float(c))})


def linear_regression_to_pmml(model) -> str:
    """(ref GeneralizedLinearPMMLModelExport.scala)"""
    coef = np.asarray(model.coefficients)
    root = _root("linear regression")
    names = _data_dictionary(root, coef.shape[0], target="target")
    rm = ET.SubElement(root, "RegressionModel",
                       {"modelName": "linear regression",
                        "functionName": "regression"})
    _mining_schema(rm, names, "target")
    _regression_table(rm, names, coef, model.intercept)
    return ET.tostring(root, encoding="unicode")


def _binary_classification_pmml(model, name: str, norm_method: str,
                                category0_intercept: float) -> str:
    """The shared two-table binary exporter (ref:
    BinaryClassificationPMMLModelExport.scala — the reference uses ONE
    class parameterized exactly like this for logistic and SVM)."""
    coef = np.asarray(model.coefficients)
    root = _root(name)
    names = _data_dictionary(root, coef.shape[0], target="target",
                             categorical_target=True)
    rm = ET.SubElement(root, "RegressionModel",
                       {"modelName": name,
                        "functionName": "classification",
                        "normalizationMethod": norm_method})
    _mining_schema(rm, names, "target")
    _regression_table(rm, names, coef, model.intercept, target_category="1")
    # the category-0 table carries the decision threshold as its intercept
    # (the reference's thresholdTable; 0.0 for logistic)
    _regression_table(rm, names, np.zeros_like(coef), category0_intercept,
                      target_category="0")
    return ET.tostring(root, encoding="unicode")


def logistic_regression_to_pmml(model) -> str:
    """(ref factory case at PMMLModelExportFactory.scala:49-53: binary only,
    logit normalization; the category-0 intercept encodes the decision
    threshold in margin space, -log(1/t - 1) — 0.0 at the default 0.5)"""
    try:
        t = float(model.get("threshold"))
    except KeyError:
        t = 0.5
    t = min(max(t, 1e-12), 1 - 1e-12)
    return _binary_classification_pmml(model, "logistic regression",
                                       "logit", -float(np.log(1.0 / t - 1.0)))


def linear_svc_to_pmml(model) -> str:
    """(ref factory case at PMMLModelExportFactory.scala:45-48:
    NormalizationMethod.NONE with the model threshold)"""
    return _binary_classification_pmml(model, "linear SVM", "none",
                                       float(model.get("threshold")))


def kmeans_to_pmml(model) -> str:
    """(ref KMeansPMMLModelExport.scala — ClusteringModel with squared
    euclidean compare function)"""
    centers = np.asarray(model._centers, dtype=float)
    k, d = centers.shape
    root = _root("k-means clustering")
    names = _data_dictionary(root, d)
    cm = ET.SubElement(root, "ClusteringModel",
                       {"modelName": "k-means", "functionName": "clustering",
                        "modelClass": "centerBased",
                        "numberOfClusters": str(k)})
    _mining_schema(cm, names)
    comp = ET.SubElement(cm, "ComparisonMeasure", {"kind": "distance"})
    ET.SubElement(comp, "squaredEuclidean")
    for n in names:
        ET.SubElement(cm, "ClusteringField",
                      {"field": n, "compareFunction": "absDiff"})
    for i in range(k):
        cl = ET.SubElement(cm, "Cluster", {"name": f"cluster_{i}"})
        arr = ET.SubElement(cl, "Array", {"n": str(d), "type": "real"})
        arr.text = " ".join(repr(float(v)) for v in centers[i])
    return ET.tostring(root, encoding="unicode")


def to_pmml(model, path: Optional[str] = None) -> str:
    """Dispatch on model type (ref PMMLExportable.toPMML); optionally write
    to ``path``."""
    name = type(model).__name__
    if name == "LinearRegressionModel":
        xml = linear_regression_to_pmml(model)
    elif name == "LogisticRegressionModel":
        xml = logistic_regression_to_pmml(model)
    elif name == "KMeansModel":
        xml = kmeans_to_pmml(model)
    elif name == "LinearSVCModel":
        xml = linear_svc_to_pmml(model)
    else:
        raise TypeError(f"PMML export not supported for {name} "
                        "(reference covers GLM/ridge/lasso — all "
                        "LinearRegressionModel here — logistic, linear "
                        "SVM, and k-means)")
    if path is not None:
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(xml)
    return xml
