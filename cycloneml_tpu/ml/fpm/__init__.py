from cycloneml_tpu.ml.fpm.fpm import (
    FPGrowth, FPGrowthModel, PrefixSpan,
)

__all__ = ["FPGrowth", "FPGrowthModel", "PrefixSpan"]
