"""Frequent-pattern mining: FPGrowth, AssociationRules, PrefixSpan.

Re-design of the reference (ref: ml/fpm/FPGrowth.scala:129 wrapping
mllib/fpm/FPGrowth.scala — parallel FP-growth (PFP) with group-dependent
conditional transactions; mllib/fpm/AssociationRules.scala single-consequent
rules with lift; mllib/fpm/PrefixSpan.scala:62 prefix-projected sequential
patterns).

These are object-data (control-plane) algorithms: transactions are ragged
item lists, not dense blocks, so they run on the host tier
(``PartitionedDataset``), exactly where the reference runs them (CPU
executors). PFP sharding: items are hashed into groups; each partition emits
group-conditional transactions; each group's FP-tree is mined independently
(the ``group_by_key``→mine step ≈ the reference's shuffle) — the TPU plays
no role here and shouldn't.
"""

from __future__ import annotations

import math
from collections import Counter, defaultdict
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.param import ParamValidators as PV
from cycloneml_tpu.ml.shared import Params
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable


# -- FP-tree ------------------------------------------------------------------

class _FPNode:
    __slots__ = ("item", "count", "children", "parent")

    def __init__(self, item, parent):
        self.item = item
        self.count = 0
        self.children: Dict = {}
        self.parent = parent


class _FPTree:
    """Prefix tree over rank-ordered transactions (ref mllib/fpm/FPTree.scala)."""

    def __init__(self):
        self.root = _FPNode(None, None)
        self.summaries: Dict[object, List[_FPNode]] = defaultdict(list)

    def add(self, items: Sequence, count: int = 1) -> None:
        node = self.root
        for it in items:
            child = node.children.get(it)
            if child is None:
                child = _FPNode(it, node)
                node.children[it] = child
                self.summaries[it].append(child)
            child.count += count
            node = child

    def _conditional_base(self, item) -> List[Tuple[List, int]]:
        out = []
        for node in self.summaries[item]:
            path = []
            p = node.parent
            while p is not None and p.item is not None:
                path.append(p.item)
                p = p.parent
            if path:
                out.append((list(reversed(path)), node.count))
        return out

    def extract(self, min_count: int, validate=lambda it: True):
        """Yield (itemset_suffix_list, support_count)."""
        for item, nodes in self.summaries.items():
            count = sum(n.count for n in nodes)
            if count >= min_count and validate(item):
                yield [item], count
                cond = _FPTree()
                for path, c in self._conditional_base(item):
                    cond.add(path, c)
                for suffix, c in cond.extract(min_count):
                    yield suffix + [item], c


# -- FPGrowth -----------------------------------------------------------------

class _FPGrowthParams(Params):
    def _declare_fp_params(self):
        self._param("itemsCol", "items column name", default="items")
        self._param("minSupport", "minimum itemset support",
                    PV.in_range(0.0, 1.0), default=0.3)
        self._param("minConfidence", "minimum rule confidence",
                    PV.in_range(0.0, 1.0), default=0.8)
        self._param("numPartitions", "mining parallelism (0 = input's)",
                    default=0)
        self._param("predictionCol", "prediction column", default="prediction")


class FPGrowth(Estimator, _FPGrowthParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_fp_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_items_col(self, v):
        return self.set("itemsCol", v)

    def set_min_support(self, v):
        return self.set("minSupport", v)

    def set_min_confidence(self, v):
        return self.set("minConfidence", v)

    def _fit(self, frame: MLFrame) -> "FPGrowthModel":
        items = frame[self.get("itemsCol")]
        transactions = [list(t) for t in items if t is not None]
        return self._fit_transactions(frame.ctx, transactions)

    def _fit_transactions(self, ctx, transactions: List[List]) -> "FPGrowthModel":
        from cycloneml_tpu.dataset.dataset import PartitionedDataset

        n = len(transactions)
        if n == 0:
            raise ValueError("empty input")
        min_count = int(math.ceil(self.get("minSupport") * n))
        min_count = max(min_count, 1)
        num_groups = self.get("numPartitions") or max(
            ctx.mesh_runtime.data_parallelism, 1)

        data = PartitionedDataset.from_sequence(ctx, transactions, num_groups)

        # pass 1: item frequencies (≈ genFreqItems' reduceByKey)
        def count_part(part):
            c = Counter()
            for t in part:
                c.update(set(t))
            return c
        counts = Counter()
        for c in data._run_per_partition(count_part):
            counts.update(c)
        freq = {it: c for it, c in counts.items() if c >= min_count}
        # rank: descending frequency, ties by repr for determinism
        rank = {it: r for r, (it, _) in enumerate(
            sorted(freq.items(), key=lambda kv: (-kv[1], repr(kv[0]))))}

        # pass 2: group-conditional transactions → per-group FP-trees
        # (≈ genCondTransactions + partitionBy(gid) + mine per group)
        def mine_part(part):
            # part: list of (gid, filtered_transaction)
            trees: Dict[int, _FPTree] = defaultdict(_FPTree)
            for gid, t in part:
                trees[gid].add(t)
            out = []
            for gid, tree in trees.items():
                out.extend(
                    (tuple(s), c) for s, c in tree.extract(
                        min_count,
                        validate=lambda it, g=gid: rank[it] % num_groups == g))
            return out

        def cond_transactions(part):
            out = []
            for t in part:
                filtered = sorted({it for it in t if it in rank},
                                  key=lambda it: rank[it])
                seen = set()
                for i in range(len(filtered) - 1, -1, -1):
                    gid = rank[filtered[i]] % num_groups
                    if gid not in seen:
                        seen.add(gid)
                        out.append((gid, filtered[:i + 1]))
            return out

        grouped = data.map_partitions(lambda p: cond_transactions(list(p)))
        # route each conditional transaction to its group's partition so each
        # group is mined exactly once
        def route(ps):
            buckets = [[] for _ in range(num_groups)]
            for p in ps:
                for gid, t in p:
                    buckets[gid].append((gid, t))
            return buckets
        routed = grouped._derive(route, num_groups)
        mined: List[Tuple[Tuple, int]] = []
        for part_out in routed._run_per_partition(lambda p: mine_part(list(p))):
            mined.extend(part_out)

        itemsets = [(list(s), c) for s, c in mined]
        itemsets.sort(key=lambda ic: (-ic[1], len(ic[0]), repr(ic[0])))
        model = FPGrowthModel(itemsets, n, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model


class FPGrowthModel(Model, _FPGrowthParams, MLWritable, MLReadable):
    def __init__(self, freq_itemsets: Optional[List[Tuple[List, int]]] = None,
                 num_training_records: int = 0, uid=None):
        super().__init__(uid)
        self._declare_fp_params()
        self.freq_itemsets = freq_itemsets or []
        self.num_training_records = num_training_records
        self._rules: Optional[List[dict]] = None

    @property
    def association_rules(self) -> List[dict]:
        """Single-consequent rules with confidence+lift+support
        (ref mllib/fpm/AssociationRules.scala)."""
        if self._rules is None:
            self._rules = _association_rules(
                self.freq_itemsets, self.num_training_records,
                self.get("minConfidence"))
        return self._rules

    def _transform(self, frame: MLFrame) -> MLFrame:
        rules = [(frozenset(r["antecedent"]), r["consequent"])
                 for r in self.association_rules]
        preds = []
        for t in frame[self.get("itemsCol")]:
            have = set(t) if t is not None else set()
            out = []
            for ante, cons in rules:
                if ante <= have:
                    for c in cons:
                        if c not in have and c not in out:
                            out.append(c)
            preds.append(out)
        return frame.with_column(self.get("predictionCol"),
                                 np.array(preds, dtype=object))

    def _save_data(self, path: str) -> None:
        import json
        import os
        with open(os.path.join(path, "itemsets.json"), "w") as f:
            json.dump({"n": self.num_training_records,
                       "sets": [[list(map(str, s)), c]
                                for s, c in self.freq_itemsets]}, f)

    def _load_data(self, path: str, meta) -> None:
        import json
        import os
        with open(os.path.join(path, "itemsets.json")) as f:
            d = json.load(f)
        self.num_training_records = d["n"]
        self.freq_itemsets = [(s, c) for s, c in d["sets"]]


def _association_rules(itemsets: List[Tuple[List, int]], n: int,
                       min_confidence: float) -> List[dict]:
    support = {frozenset(s): c for s, c in itemsets}
    rules = []
    for s, c in itemsets:
        if len(s) < 2:
            continue
        fs = frozenset(s)
        for item in s:
            ante = fs - {item}
            ante_count = support.get(ante)
            if not ante_count:
                continue
            conf = c / ante_count
            if conf >= min_confidence:
                cons_count = support.get(frozenset([item]))
                lift = (conf / (cons_count / n)) if cons_count else float("nan")
                rules.append({
                    "antecedent": sorted(ante, key=repr),
                    "consequent": [item],
                    "confidence": conf,
                    "lift": lift,
                    "support": c / n,
                })
    rules.sort(key=lambda r: (-r["confidence"], repr(r["antecedent"])))
    return rules


# -- PrefixSpan ---------------------------------------------------------------

class PrefixSpan(Params):
    """Sequential pattern mining by prefix projection
    (ref mllib/fpm/PrefixSpan.scala:62; ml/fpm/PrefixSpan.scala wrapper).

    Sequences are lists of itemsets: ``[["a"], ["a","b"], ["c"]]``.
    ``find_frequent_sequential_patterns`` returns (pattern, freq) pairs where
    a pattern is a list of itemsets.
    """

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._param("minSupport", "minimum sequence support",
                    PV.in_range(0.0, 1.0), default=0.1)
        self._param("maxPatternLength", "max number of items per pattern",
                    PV.gt(0), default=10)
        self._param("maxLocalProjDBSize", "projected-db size cutoff",
                    default=32000000)
        self._param("sequenceCol", "sequence column", default="sequence")
        for k, v in kwargs.items():
            self.set(k, v)

    def set_min_support(self, v):
        return self.set("minSupport", v)

    def set_max_pattern_length(self, v):
        return self.set("maxPatternLength", v)

    def find_frequent_sequential_patterns(self, frame_or_sequences):
        if isinstance(frame_or_sequences, MLFrame):
            seqs = [s for s in frame_or_sequences[self.get("sequenceCol")]
                    if s is not None]
        else:
            seqs = list(frame_or_sequences)
        n = len(seqs)
        if n == 0:
            raise ValueError("empty input")
        min_count = max(int(math.ceil(self.get("minSupport") * n)), 1)
        max_len = self.get("maxPatternLength")

        # canonicalize: itemsets as frozensets; item order by repr
        db = [[frozenset(s) for s in seq] for seq in seqs]
        all_items = sorted({it for seq in db for s in seq for it in s},
                           key=repr)
        results: List[Tuple[List[Tuple], int]] = []
        self._mine([], list(range(n)), db, all_items, min_count, max_len,
                   results)
        results.sort(key=lambda pc: (-pc[1], len(pc[0]), repr(pc[0])))
        return [([sorted(s, key=repr) for s in pat], c) for pat, c in results]

    @staticmethod
    def _matches(pattern: List[FrozenSet], seq: List[FrozenSet]) -> bool:
        """True iff ∃ j1<…<jk with pattern[m] ⊆ seq[jm] (the reference's
        subsequence-of-itemsets semantics)."""
        j = 0
        for pset in pattern:
            while j < len(seq) and not pset <= seq[j]:
                j += 1
            if j == len(seq):
                return False
            j += 1
        return True

    # Recursion over candidate extensions: S-extension starts a new itemset
    # with one item; I-extension grows the last itemset (items canonically
    # after its current members, so each multi-item itemset is generated
    # exactly once). Support is re-counted against the parent's support set,
    # which shrinks monotonically — semantics identical to the reference's
    # prefix projection, simpler bookkeeping (no partial-postfix encoding).
    def _mine(self, prefix: List[FrozenSet], support_idx: List[int], db,
              all_items, min_count: int, max_len: int, results) -> None:
        n_items = sum(len(s) for s in prefix)
        if n_items >= max_len:
            return
        candidates = []
        for item in all_items:
            candidates.append(prefix + [frozenset([item])])  # S-extension
        if prefix:
            last = prefix[-1]
            last_max = max(map(repr, last))
            for item in all_items:
                if item not in last and repr(item) > last_max:
                    candidates.append(prefix[:-1] + [last | {item}])
        for cand in candidates:
            sup = [i for i in support_idx if self._matches(cand, db[i])]
            if len(sup) >= min_count:
                results.append(([tuple(s) for s in cand], len(sup)))
                self._mine(cand, sup, db, all_items, min_count, max_len,
                           results)
