from cycloneml_tpu.ml.recommendation.als import ALS, ALSModel

__all__ = ["ALS", "ALSModel"]
