"""Alternating least squares matrix factorization.

Re-design of the reference ALS (ref: ml/recommendation/ALS.scala:606, 1,829
LoC — block-partitioned factors ``makeBlocks:1605``, per-block normal
equations ``computeFactors:1689`` built from rank-1 ``dspr`` updates
(``NormalEquation:872``, ``add:897``), ``CholeskySolver:770``,
``NNLSSolver:804``; implicit feedback per Hu/Koren/Volinsky with the YᵀY
trick). TPU-first formulation:

- ratings live as COO arrays (user, item, rating) row-sharded over the mesh —
  the analog of the reference's in/out blocks without the custom
  shuffle: each half-step builds EVERY entity's normal equations with one
  ``segment_sum`` of v vᵀ outer products (an (nnz,r,r) tensor contraction XLA
  fuses), psums them across shards (replacing the block all-to-all exchange),
  and solves all entities at once with a **batched Cholesky** on the MXU.
- explicit: A_u = Σ v vᵀ + λ·n_u·I (ALS-WR scaling, as the reference),
  b_u = Σ r·v.
- implicit: A_u = YᵀY + Σ (c−1) v vᵀ + λ·n_u·I with c = 1+α|r|,
  b_u = Σ c·v for observed p=1 (ref the ``YtY`` path in computeFactors).
- nonnegative=True replaces the solve with batched projected Newton steps
  (clamped); the reference's NNLSSolver:804 is a host active-set method —
  same constraint, device-friendly iteration.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.ml.base import Estimator, Model
from cycloneml_tpu.ml.param import ParamValidators as V
from cycloneml_tpu.ml.shared import HasMaxIter, HasPredictionCol, HasRegParam, HasSeed
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class _ALSParams(HasMaxIter, HasRegParam, HasPredictionCol, HasSeed):
    def _declare_als_params(self):
        self._p_max_iter(10)
        self._p_reg_param(0.1)
        self._p_prediction_col()
        self._p_seed(0)
        self.rankParam = self._param("rank", "factor dimension (> 0)", V.gt(0), default=10)
        self.userCol = self._param("userCol", "user id column", default="user")
        self.itemCol = self._param("itemCol", "item id column", default="item")
        self.ratingCol = self._param("ratingCol", "rating column", default="rating")
        self.implicitPrefs = self._param("implicitPrefs",
                                         "implicit preference mode", default=False)
        self.alpha = self._param("alpha", "implicit confidence scale (>= 0)",
                                 V.gt_eq(0.0), default=1.0)
        self.nonnegative = self._param("nonnegative",
                                       "constrain factors >= 0", default=False)
        self.coldStartStrategy = self._param(
            "coldStartStrategy", "nan or drop for unseen ids",
            V.in_array(["nan", "drop"]), default="nan")
        # the reference's checkpointInterval truncates RDD lineage
        # (ALS.scala setCheckpointInterval); here it snapshots the factor
        # matrices so a killed fit resumes mid-training (SURVEY §5.4)
        self.checkpointDir = self._param(
            "checkpointDir", "directory for mid-training factor checkpoints",
            default="")
        self.checkpointInterval = self._param(
            "checkpointInterval", "iterations between checkpoints",
            V.gt(0), default=10)
        # bounds the per-shard vvᵀ intermediate: ratings are scanned in
        # chunks of ~this many bytes of (chunk, rank, rank) outer products,
        # so memory scales with entities + chunk, never with nnz (the
        # reference streams blocks for the same reason, ALS.scala:1689)
        self.aggregationChunkBytes = self._param(
            "aggregationChunkBytes",
            "byte budget for the per-chunk outer-product intermediate",
            V.gt(0), default=256 << 20)
        # factor-sharded (blocked) solve: ratings are hash-partitioned by
        # destination entity so each shard owns its entities' normal
        # equations outright — the (n_dst, r, r) accumulator and the factor
        # matrices are SHARDED over the mesh instead of replicated (the
        # TPU-native analog of the reference's in/out factor blocks,
        # ALS.scala:1605 makeBlocks). "auto" switches over when the
        # replicated accumulator would exceed factorShardingThresholdBytes.
        self.shardFactors = self._param(
            "shardFactors", "auto | never | always",
            V.in_array(["auto", "never", "always"]), default="auto")
        self.factorShardingThresholdBytes = self._param(
            "factorShardingThresholdBytes",
            "replicated-accumulator size above which auto mode shards",
            V.gt(0), default=1 << 30)


class ALS(Estimator, _ALSParams, MLWritable, MLReadable):
    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_als_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_rank(self, v):
        return self.set("rank", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_reg_param(self, v):
        return self.set("regParam", v)

    def set_implicit_prefs(self, v):
        return self.set("implicitPrefs", v)

    def _fit(self, frame: MLFrame) -> "ALSModel":
        users_raw = np.asarray(frame[self.get("userCol")]).astype(np.int64)
        items_raw = np.asarray(frame[self.get("itemCol")]).astype(np.int64)
        ratings = np.asarray(frame[self.get("ratingCol")]).astype(np.float64)

        user_ids, users = np.unique(users_raw, return_inverse=True)
        item_ids, items = np.unique(items_raw, return_inverse=True)
        n_users, n_items = len(user_ids), len(item_ids)
        rank = self.get("rank")

        u_fac, i_fac = self._train(users, items, ratings, n_users, n_items, rank,
                                   frame.ctx)
        model = ALSModel(user_ids, item_ids, u_fac, i_fac, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        return model

    def _checkpoint_setup(self, rank, n_users, n_items, ratings):
        """Shared checkpoint plumbing for both trainers: returns
        ``(ck, ck_fp, start_iter, saved_u, saved_i)`` with factors in
        ENTITY order (or None when starting fresh). The fingerprint binds
        the directory to this dataset+hyperparameters — resuming foreign
        factors silently returns the wrong model (or crashes on shape)."""
        if not self.get("checkpointDir"):
            return None, None, 0, None, None
        import hashlib
        from cycloneml_tpu.util.checkpoint import TrainingCheckpointer
        ck = TrainingCheckpointer(self.get("checkpointDir"))
        ck_fp = hashlib.sha1(repr((
            rank, n_users, n_items, len(ratings),
            float(np.sum(ratings)), self.get("implicitPrefs"),
            self.get("regParam"), self.get("alpha"),
            self.get("nonnegative"), self.get("seed"),
        )).encode()).hexdigest()[:16]
        latest = ck.latest_step()
        if latest is None:
            return ck, ck_fp, 0, None, None
        saved_fp = ck.metadata(latest).get("fingerprint")
        if saved_fp != ck_fp:
            raise ValueError(
                f"checkpoint dir {ck.directory!r} holds factors for "
                f"a DIFFERENT ALS run (fingerprint {saved_fp} != "
                f"{ck_fp}); clear the directory or use a new one")
        saved = ck.restore(latest)
        start_iter = int(saved["iteration"])
        if start_iter > self.get("maxIter"):
            # equality is fine: the checkpoint IS the requested model
            raise ValueError(
                f"checkpoint is at iteration {start_iter} but "
                f"maxIter={self.get('maxIter')}; returning it as-is "
                "would be an over-trained model — raise maxIter or "
                "clear the checkpoint directory")
        logger.info("ALS resuming from checkpoint iteration %d", start_iter)
        return ck, ck_fp, start_iter, saved["u_fac"], saved["i_fac"]

    def _train(self, users, items, ratings, n_users, n_items, rank, ctx):
        import jax
        import jax.numpy as jnp

        rt = ctx.mesh_runtime
        mode = self.get("shardFactors")
        acc_bytes = max(n_users, n_items) * rank * rank * 4
        if mode == "always" or (
                mode == "auto"
                and acc_bytes > self.get("factorShardingThresholdBytes")):
            return self._train_blocked(users, items, ratings, n_users,
                                       n_items, rank, ctx)
        implicit = self.get("implicitPrefs")
        reg = self.get("regParam")
        alpha = self.get("alpha")
        nonneg = self.get("nonnegative")
        from cycloneml_tpu.dataset.instance import compute_dtype
        dtype = compute_dtype()  # f32 on TPU; f64 under the x64 test config

        # shard COO triplets over the mesh with zero-weight padding, row
        # count shaped so each shard splits evenly into scan chunks: the
        # per-shard chunk count k bounds the (chunk, rank, rank) vvᵀ
        # intermediate at ~aggregationChunkBytes — memory proportional to
        # entities + one chunk, NEVER to nnz (VERDICT r1 item 5; the
        # reference streams factor blocks for the same reason,
        # ALS.scala:1689 computeFactors)
        nnz = len(ratings)
        shards = rt.data_parallelism
        shard0 = -(-max(nnz, 1) // shards)
        budget = int(self.get("aggregationChunkBytes"))
        n_chunks = max(1, -(-shard0 * rank * rank * np.dtype(dtype).itemsize
                            // budget))
        chunk = max(8, -(-shard0 // n_chunks))
        chunk += (-chunk) % 8  # sublane-friendly
        shard_rows = chunk * n_chunks
        pad = shard_rows * shards - nnz
        u_arr = np.concatenate([users, np.zeros(pad, np.int32)]).astype(np.int32)
        i_arr = np.concatenate([items, np.zeros(pad, np.int32)]).astype(np.int32)
        r_arr = np.concatenate([ratings, np.zeros(pad)]).astype(dtype)
        m_arr = np.concatenate([np.ones(nnz), np.zeros(pad)]).astype(dtype)
        u_dev = rt.device_put_sharded_rows(u_arr)
        i_dev = rt.device_put_sharded_rows(i_arr)
        r_dev = rt.device_put_sharded_rows(r_arr)
        m_dev = rt.device_put_sharded_rows(m_arr)

        from cycloneml_tpu.parallel import collectives

        hi = jax.lax.Precision.HIGHEST

        def make_half_step(n_dst: int):
            """Build + solve normal equations for every destination entity
            given source factors: one psum'd SPMD program. The local shard
            scans its ratings chunk-by-chunk, accumulating into the
            (n_dst, rank, rank) normal-equation tensor."""
            # alpha only matters under implicit mode — normalize it out of
            # the cache key for explicit fits so an alpha sweep doesn't
            # defeat the program cache
            local = _normal_eq_local(n_dst, rank, n_chunks, implicit,
                                     float(alpha) if implicit else 0.0)
            agg = collectives.tree_aggregate(local, rt, u_dev, i_dev, r_dev, m_dev)

            @jax.jit
            def solve(aggregated, yty):
                a, b, cnt = aggregated["A"], aggregated["b"], aggregated["n"]
                # ALS-WR: λ scaled by each entity's rating count (ref solver
                # call sites in computeFactors:1689)
                lam = reg * jnp.maximum(cnt, 1.0)
                eye = jnp.eye(rank, dtype=a.dtype)
                a = a + lam[:, None, None] * eye[None, :, :]
                if implicit:
                    a = a + yty[None, :, :]
                if nonneg:
                    return _batched_pnewton(a, b)
                return jnp.linalg.solve(a, b[..., None])[..., 0]

            return agg, solve

        rng = np.random.RandomState(self.get("seed"))
        # reference init: abs(normal)/sqrt(rank) scaled unit-ish factors
        u_fac = jnp.asarray(np.abs(rng.normal(size=(n_users, rank))) / np.sqrt(rank),
                            dtype=dtype)
        i_fac = jnp.asarray(np.abs(rng.normal(size=(n_items, rank))) / np.sqrt(rank),
                            dtype=dtype)

        agg_users, solve_users = make_half_step(n_users)
        agg_items, solve_items = make_half_step(n_items)

        @jax.jit
        def yty_of(f):
            return jnp.dot(f.T, f, precision=hi)

        ck, ck_fp, start_iter, saved_u, saved_i = self._checkpoint_setup(
            rank, n_users, n_items, ratings)
        if saved_u is not None:
            u_fac = jnp.asarray(saved_u, dtype)
            i_fac = jnp.asarray(saved_i, dtype)

        zero_yty = jnp.zeros((rank, rank), dtype=dtype)
        for it in range(start_iter, self.get("maxIter")):
            yty = yty_of(i_fac) if implicit else zero_yty
            out = agg_users(u_dev, i_dev, r_dev, m_dev, i_fac, yty)
            # block per half-step: at most one collective program in flight —
            # concurrent shard_map executions abort/deadlock the virtual-device
            # CPU backend, and on TPU the next step depends on this one anyway
            u_fac = jax.block_until_ready(solve_users(out, yty))
            yty = yty_of(u_fac) if implicit else zero_yty
            # swap dst/src: destination = items, source = users
            out = agg_items(i_dev, u_dev, r_dev, m_dev, u_fac, yty)
            i_fac = jax.block_until_ready(solve_items(out, yty))
            if ck is not None and (it + 1) % self.get("checkpointInterval") == 0 \
                    and (it + 1) < self.get("maxIter"):
                ck.save(it + 1, {"u_fac": np.asarray(u_fac),
                                 "i_fac": np.asarray(i_fac),
                                 "iteration": it + 1},
                        metadata={"fingerprint": ck_fp})

        return np.asarray(u_fac, dtype=np.float64), np.asarray(i_fac, dtype=np.float64)

    def _train_blocked(self, users, items, ratings, n_users, n_items, rank,
                       ctx):
        """Factor-sharded ALS (the MovieLens-25M-and-beyond path).

        Ratings are hash-partitioned by DESTINATION entity (dst % n_shards),
        one layout per half-step orientation — the TPU-native analog of the
        reference's dual in/out block structure (ALS.scala:1605 makeBlocks,
        :1689 computeFactors). Every contribution to entity e lives on e's
        shard, so the (n_dst, r, r) normal-equation tensor, its batched
        Cholesky/LU solve, and the factor matrices themselves are all
        SHARDED over the mesh — per-device memory drops by n_shards vs the
        replicated path, and no psum of the accumulator ever happens. The
        only communication per half-step is one all-gather of the (much
        smaller) source factor shards, riding ICI.

        Factor layout: entity e lives at global row (e % D) * n_loc + e // D
        (shard-major); host-side views translate at the boundaries.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
        from cycloneml_tpu.parallel.collectives import shard_map_compat

        rt = ctx.mesh_runtime
        if rt.mesh.devices.shape[2] != 1:
            raise ValueError("blocked ALS shards over (replica, data) and "
                             "requires model_parallelism == 1")
        D = rt.data_parallelism
        implicit = self.get("implicitPrefs")
        reg = self.get("regParam")
        alpha = self.get("alpha")
        nonneg = self.get("nonnegative")
        from cycloneml_tpu.dataset.instance import compute_dtype
        dtype = compute_dtype()  # f32 on TPU; f64 under the x64 test config
        budget = int(self.get("aggregationChunkBytes"))

        n_loc_u = -(-n_users // D)
        n_loc_i = -(-n_items // D)

        def partitioned_layout(dst, src, n_loc_src):
            """(D, shard_nnz) arrays: entries routed to shard dst % D with
            local dst slot dst // D; src ids pre-permuted into the
            shard-major factor layout so the gathered factor tensor is
            indexed flat."""
            shard = dst % D
            order = np.argsort(shard, kind="stable")
            counts = np.bincount(shard, minlength=D)
            n_chunks = max(1, -(-int(counts.max()) * rank * rank
                                * np.dtype(dtype).itemsize // budget))
            chunk = max(8, -(-int(counts.max()) // n_chunks))
            chunk += (-chunk) % 8
            shard_nnz = chunk * n_chunks
            d_l = np.zeros((D, shard_nnz), np.int32)
            s_l = np.zeros((D, shard_nnz), np.int32)
            r_l = np.zeros((D, shard_nnz), dtype)
            m_l = np.zeros((D, shard_nnz), dtype)
            dst_s, src_s, rat_s = dst[order], src[order], ratings[order]
            off = 0
            for s in range(D):
                c = int(counts[s])
                d_l[s, :c] = dst_s[off:off + c] // D
                sv = src_s[off:off + c]
                s_l[s, :c] = (sv % D) * n_loc_src + sv // D
                r_l[s, :c] = rat_s[off:off + c]
                m_l[s, :c] = 1.0
                off += c
            put = rt.device_put_sharded_rows
            return (put(d_l.reshape(-1)), put(s_l.reshape(-1)),
                    put(r_l.reshape(-1)), put(m_l.reshape(-1)), n_chunks)

        lay_u = partitioned_layout(users, items, n_loc_i)   # dst = users
        lay_i = partitioned_layout(items, users, n_loc_u)   # dst = items

        row = P((REPLICA_AXIS, DATA_AXIS))
        hi = jax.lax.Precision.HIGHEST

        def make_half_step(n_loc_dst, n_chunks):
            def local(d_i, s_i, r_c, m_c, src_loc):
                # one all-gather of the source factor shards (ICI), then a
                # bounded chunked scan scatter-adds vvᵀ into THIS shard's
                # (n_loc_dst, r, r) accumulator — never psum'd
                g = jax.lax.all_gather(src_loc, DATA_AXIS)
                g = jax.lax.all_gather(g, REPLICA_AXIS)
                src_all = g.reshape(-1, rank)
                yty = (jnp.dot(src_loc.T, src_loc, precision=hi)
                       if implicit else jnp.zeros((rank, rank), src_loc.dtype))
                if implicit:
                    yty = jax.lax.psum(yty, DATA_AXIS)
                    yty = jax.lax.psum(yty, REPLICA_AXIS)

                def body(carry, ch):
                    a, b, cnt = carry
                    di, si, rc, mc = ch
                    v = src_all[si]
                    if implicit:
                        c_minus_1 = (alpha * jnp.abs(rc)) * mc
                        p = (rc > 0).astype(v.dtype) * mc
                        outer = jnp.einsum("bi,bj->bij", v * c_minus_1[:, None],
                                           v, precision=hi)
                        bvec = v * ((1.0 + c_minus_1) * p)[:, None]
                    else:
                        outer = jnp.einsum("bi,bj->bij", v * mc[:, None], v,
                                           precision=hi)
                        bvec = v * (rc * mc)[:, None]
                    return (a.at[di].add(outer), b.at[di].add(bvec),
                            cnt.at[di].add(mc)), None

                zeros = (jnp.zeros((n_loc_dst, rank, rank), src_loc.dtype),
                         jnp.zeros((n_loc_dst, rank), src_loc.dtype),
                         jnp.zeros((n_loc_dst,), src_loc.dtype))
                nloc = d_i.shape[0]
                chunks = tuple(a.reshape(n_chunks, nloc // n_chunks)
                               for a in (d_i, s_i, r_c, m_c))
                (a_s, b_s, cnt), _ = jax.lax.scan(body, zeros, chunks)

                lam = reg * jnp.maximum(cnt, 1.0)
                eye = jnp.eye(rank, dtype=a_s.dtype)
                a_s = a_s + lam[:, None, None] * eye[None, :, :]
                if implicit:
                    a_s = a_s + yty[None, :, :]
                if nonneg:
                    return _batched_pnewton(a_s, b_s)
                return jnp.linalg.solve(a_s, b_s[..., None])[..., 0]

            return jax.jit(shard_map_compat(
                local, rt.mesh, (row,) * 5, row))

        step_u = make_half_step(n_loc_u, lay_u[4])
        step_i = make_half_step(n_loc_i, lay_i[4])

        def to_layout(fac, n_loc):
            """(n, r) entity-order → (D * n_loc, r) shard-major device array."""
            out = np.zeros((D * n_loc, rank), dtype)
            ids = np.arange(fac.shape[0])
            out[(ids % D) * n_loc + ids // D] = fac
            return rt.device_put_sharded_rows(out)

        def from_layout(arr, n):
            ids = np.arange(n)
            return np.asarray(arr)[(ids % D) * n_loc_from(arr) + ids // D]

        def n_loc_from(arr):
            return arr.shape[0] // D

        rng = np.random.RandomState(self.get("seed"))
        u0 = np.abs(rng.normal(size=(n_users, rank))) / np.sqrt(rank)
        i0 = np.abs(rng.normal(size=(n_items, rank))) / np.sqrt(rank)

        ck, ck_fp, start_iter, saved_u, saved_i = self._checkpoint_setup(
            rank, n_users, n_items, ratings)
        if saved_u is not None:
            u0, i0 = saved_u, saved_i

        u_fac = to_layout(u0.astype(dtype), n_loc_u)
        i_fac = to_layout(i0.astype(dtype), n_loc_i)
        for it in range(start_iter, self.get("maxIter")):
            # one collective program in flight at a time (see _train note)
            u_fac = jax.block_until_ready(
                step_u(lay_u[0], lay_u[1], lay_u[2], lay_u[3], i_fac))
            i_fac = jax.block_until_ready(
                step_i(lay_i[0], lay_i[1], lay_i[2], lay_i[3], u_fac))
            if ck is not None and (it + 1) % self.get("checkpointInterval") == 0 \
                    and (it + 1) < self.get("maxIter"):
                ck.save(it + 1, {"u_fac": from_layout(u_fac, n_users),
                                 "i_fac": from_layout(i_fac, n_items),
                                 "iteration": it + 1},
                        metadata={"fingerprint": ck_fp})

        return (from_layout(u_fac, n_users).astype(np.float64),
                from_layout(i_fac, n_items).astype(np.float64))


@__import__("functools").lru_cache(maxsize=64)
def _normal_eq_local(n_dst: int, rank: int, n_chunks: int, implicit: bool,
                     alpha: float):
    """Per-shard normal-equation builder (ref NormalEquation.add:897 dspr
    loop, computeFactors:1689 block streaming): scans the shard's COO
    ratings in ``n_chunks`` chunks, each contributing one bounded
    (chunk, rank, rank) vvᵀ batch segment-summed into the (n_dst, rank,
    rank) accumulator — peak memory ∝ entities + one chunk, never nnz.
    lru-cached so repeated fits feed tree_aggregate a stable fn identity
    (program-cache hit instead of an XLA recompile)."""
    import jax
    import jax.numpy as jnp
    hi = jax.lax.Precision.HIGHEST

    def local(dst_idx, src_idx, r, mask, src_fac, yty):
        def body(carry, ch):
            a, b, cnt = carry
            d_i, s_i, r_c, m_c = ch
            v = src_fac[s_i]                       # (chunk, rank)
            if implicit:
                c_minus_1 = (alpha * jnp.abs(r_c)) * m_c
                p = (r_c > 0).astype(v.dtype) * m_c
                outer = jnp.einsum("bi,bj->bij", v * c_minus_1[:, None], v,
                                   precision=hi)
                bvec = v * ((1.0 + c_minus_1) * p)[:, None]
            else:
                outer = jnp.einsum("bi,bj->bij", v * m_c[:, None], v,
                                   precision=hi)
                bvec = v * (r_c * m_c)[:, None]
            # scatter-add straight into the (donated) scan carry: per-chunk
            # work stays O(chunk·r²) — a dense segment_sum + carry add would
            # read/write the full (n_dst, r, r) accumulator every chunk
            return (a.at[d_i].add(outer), b.at[d_i].add(bvec),
                    cnt.at[d_i].add(m_c)), None

        zeros = (jnp.zeros((n_dst, rank, rank), src_fac.dtype),
                 jnp.zeros((n_dst, rank), src_fac.dtype),
                 jnp.zeros((n_dst,), src_fac.dtype))
        nloc = dst_idx.shape[0]
        chunks = (dst_idx.reshape(n_chunks, nloc // n_chunks),
                  src_idx.reshape(n_chunks, nloc // n_chunks),
                  r.reshape(n_chunks, nloc // n_chunks),
                  mask.reshape(n_chunks, nloc // n_chunks))
        (a_sum, b_sum, cnt), _ = jax.lax.scan(body, zeros, chunks)
        return {"A": a_sum, "b": b_sum, "n": cnt}

    return local


def _batched_pnewton(a, b, iters: int = 40):
    """Batched projected-Newton NNLS: x ← max(0, x − H⁻¹∇) with damped steps.
    Device-friendly replacement for the reference's host NNLSSolver:804."""
    import jax
    import jax.numpy as jnp

    x0 = jnp.maximum(jnp.linalg.solve(a, b[..., None])[..., 0], 0.0)

    def body(x, _):
        grad = jnp.einsum("bij,bj->bi", a, x) - b
        step = jnp.linalg.solve(a, grad[..., None])[..., 0]
        x1 = jnp.maximum(x - 0.7 * step, 0.0)
        return x1, None

    x, _ = jax.lax.scan(body, x0, None, length=iters)
    return x


class ALSModel(Model, _ALSParams, MLWritable, MLReadable):
    def __init__(self, user_ids: Optional[np.ndarray] = None,
                 item_ids: Optional[np.ndarray] = None,
                 user_factors: Optional[np.ndarray] = None,
                 item_factors: Optional[np.ndarray] = None, uid=None):
        super().__init__(uid)
        self._declare_als_params()
        self.user_ids = user_ids
        self.item_ids = item_ids
        self.user_factors = user_factors
        self.item_factors = item_factors

    @property
    def rank(self) -> int:
        return self.user_factors.shape[1]

    def _lookup(self, raw_ids: np.ndarray, ids: np.ndarray) -> np.ndarray:
        pos = np.searchsorted(ids, raw_ids)
        pos = np.clip(pos, 0, len(ids) - 1)
        ok = ids[pos] == raw_ids
        return np.where(ok, pos, -1)

    def _transform(self, frame: MLFrame) -> MLFrame:
        users = np.asarray(frame[self.get("userCol")]).astype(np.int64)
        items = np.asarray(frame[self.get("itemCol")]).astype(np.int64)
        up = self._lookup(users, self.user_ids)
        ip = self._lookup(items, self.item_ids)
        known = (up >= 0) & (ip >= 0)
        pred = np.full(len(users), np.nan)
        pred[known] = np.einsum(
            "bi,bi->b", self.user_factors[up[known]], self.item_factors[ip[known]])
        out = frame.with_column(self.get("predictionCol"), pred)
        if self.get("coldStartStrategy") == "drop":
            out = out.filter_rows(~np.isnan(pred))
        return out

    def recommend_for_all_users(self, num_items: int) -> MLFrame:
        """Top-N items per user via one factor matmul (ref
        recommendForAllUsers — blocked BLAS-3 there, single MXU matmul here)."""
        scores = self.user_factors @ self.item_factors.T
        top = np.argsort(-scores, axis=1)[:, :num_items]
        rows_user = np.repeat(self.user_ids, num_items)
        rows_item = self.item_ids[top.ravel()]
        rows_score = np.take_along_axis(scores, top, axis=1).ravel()
        from cycloneml_tpu.context import CycloneContext
        return MLFrame(CycloneContext.get_or_create(), {
            "user": rows_user, "item": rows_item, "rating": rows_score})

    def recommend_for_all_items(self, num_users: int) -> MLFrame:
        scores = self.item_factors @ self.user_factors.T
        top = np.argsort(-scores, axis=1)[:, :num_users]
        from cycloneml_tpu.context import CycloneContext
        return MLFrame(CycloneContext.get_or_create(), {
            "item": np.repeat(self.item_ids, num_users),
            "user": self.user_ids[top.ravel()],
            "rating": np.take_along_axis(scores, top, axis=1).ravel()})

    def _save_data(self, path: str) -> None:
        save_arrays(path, user_ids=self.user_ids, item_ids=self.item_ids,
                    user_factors=self.user_factors, item_factors=self.item_factors)

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self.user_ids = arrs["user_ids"]
        self.item_ids = arrs["item_ids"]
        self.user_factors = arrs["user_factors"]
        self.item_factors = arrs["item_factors"]
