"""Histogram-based decision-tree engine (TPU-first redesign).

The reference grows CART trees with per-partition bin aggregation merged by
``reduceByKey`` per node group (ref: ml/tree/impl/RandomForest.scala:83,
``findBestSplits:463``; bin seqOp in DTStatsAggregator). That design exists
to stream sparse rows on CPUs. On TPU the same math is three dense device
programs per tree level, vmapped over all trees of a forest at once:

1. **binize** — features are bucketized once into int32 bin ids against
   quantile thresholds (ref ``findSplits`` sampling scheme), so every later
   pass touches only a compact ``(rows, features)`` int tensor.
2. **histogram** — each row scatter-adds its stat channels into a flat
   ``(nodes × features × bins, channels)`` table; the per-shard tables are
   merged with one hierarchical ``psum`` (the reference's reduceByKey) and
   the driver receives the complete level histogram.
3. **reassign** — the driver's chosen splits go back as four small arrays
   and a gather program advances every row to its child node.

Split selection (impurity math, min-instance/weight/gain constraints,
per-node feature subsets) is vectorized numpy on the driver — it is
O(nodes × features × bins), independent of the number of rows.

Trees are stored compactly (explicit child pointers, nodes allocated only
when created) rather than as 2^depth heaps, so deep unbalanced trees cost
memory proportional to their real node count.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from cycloneml_tpu.parallel import collectives
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# Split finding (quantile binning)
# ---------------------------------------------------------------------------

def find_splits(x_sample: np.ndarray, max_bins: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-feature continuous split thresholds from a driver-side sample
    (ref RandomForest.findSplits — quantiles over a bounded sample).

    Returns ``(thresholds [d, max_bins-1] float64 padded with +inf,
    n_bins [d] int32)``; feature f uses thresholds[f, :n_bins[f]-1] and its
    binned values live in [0, n_bins[f]).
    """
    n, d = x_sample.shape
    s_max = max_bins - 1
    thresholds = np.full((d, s_max), np.inf, dtype=np.float64)
    n_bins = np.ones(d, dtype=np.int32)
    for f in range(d):
        vals = np.unique(x_sample[:, f])
        if len(vals) <= 1:
            continue
        if len(vals) <= max_bins:
            th = (vals[:-1] + vals[1:]) / 2.0
        else:
            qs = np.quantile(x_sample[:, f], np.linspace(0, 1, max_bins + 1)[1:-1])
            th = np.unique(qs)
        th = th[:s_max]
        thresholds[f, :len(th)] = th
        n_bins[f] = len(th) + 1
    return thresholds, n_bins


# ---------------------------------------------------------------------------
# Forest data container
# ---------------------------------------------------------------------------

@dataclass
class ForestData:
    """Fitted ensemble as padded flat node tables, one row group per tree.

    ``feature[t, i] < 0`` marks a leaf. ``prediction[t, i]`` is the class
    stat vector (weighted class counts) for classification or ``[mean]`` for
    regression. Heap-free: ``left``/``right`` are explicit node indices.
    """
    feature: np.ndarray      # [T, N] int32
    threshold: np.ndarray    # [T, N] float64
    left: np.ndarray         # [T, N] int32
    right: np.ndarray        # [T, N] int32
    prediction: np.ndarray   # [T, N, C]
    impurity: np.ndarray     # [T, N]
    gain: np.ndarray         # [T, N]
    count: np.ndarray        # [T, N]  raw instance count reaching the node
    weight: np.ndarray       # [T, N]  weighted count
    n_nodes: np.ndarray      # [T] int32
    tree_weights: np.ndarray  # [T]
    num_features: int
    is_classification: bool

    @property
    def num_trees(self) -> int:
        return self.feature.shape[0]

    def tree_depth(self, t: int) -> int:
        depth = np.zeros(self.feature.shape[1], dtype=np.int64)
        maxd = 0
        for i in range(int(self.n_nodes[t])):
            if self.feature[t, i] >= 0:
                for c in (self.left[t, i], self.right[t, i]):
                    depth[c] = depth[i] + 1
                    maxd = max(maxd, int(depth[c]))
        return maxd

    # -- prediction ---------------------------------------------------------
    def predict_leaf_values(self, x: np.ndarray) -> np.ndarray:
        """Leaf value vector per (row, tree): [n, T, C]."""
        n = x.shape[0]
        T, N, C = self.prediction.shape
        out = np.empty((n, T, C), dtype=np.float64)
        max_depth = max((self.tree_depth(t) for t in range(T)), default=0)
        rows = np.arange(n)
        for t in range(T):
            node = np.zeros(n, dtype=np.int64)
            feat, thr = self.feature[t], self.threshold[t]
            lc, rc = self.left[t], self.right[t]
            for _ in range(max_depth):
                f = feat[node]
                interior = f >= 0
                if not interior.any():
                    break
                xv = x[rows, np.clip(f, 0, self.num_features - 1)]
                nxt = np.where(xv <= thr[node], lc[node], rc[node])
                node = np.where(interior, nxt, node)
            out[:, t, :] = self.prediction[t][node]
        return out

    def predict_raw(self, x: np.ndarray) -> np.ndarray:
        """Classification: sum of per-tree class probability votes [n, C]
        (ref RandomForestClassificationModel.predictRaw — normalized votes).
        Regression: weighted sum of tree means [n, 1]."""
        leaf = self.predict_leaf_values(np.asarray(x, dtype=np.float64))
        if self.is_classification:
            tot = np.maximum(leaf.sum(axis=2, keepdims=True), 1e-300)
            return (leaf / tot * self.tree_weights[None, :, None]).sum(axis=1)
        return (leaf[..., 0] * self.tree_weights[None, :]).sum(axis=1, keepdims=True)

    # -- introspection --------------------------------------------------------
    def feature_importances(self) -> np.ndarray:
        """Gain×count importances, normalized per tree then averaged
        (ref: ml/tree/treeModels.scala TreeEnsembleModel.featureImportances)."""
        imp = np.zeros(self.num_features, dtype=np.float64)
        for t in range(self.num_trees):
            one = np.zeros(self.num_features, dtype=np.float64)
            for i in range(int(self.n_nodes[t])):
                f = self.feature[t, i]
                if f >= 0:
                    one[f] += self.gain[t, i] * self.count[t, i]
            s = one.sum()
            if s > 0:
                imp += one / s
        s = imp.sum()
        return imp / s if s > 0 else imp

    def debug_string(self, t: int = 0) -> str:
        lines: List[str] = []

        def rec(i: int, indent: int) -> None:
            pad = "  " * indent
            f = int(self.feature[t, i])
            if f < 0:
                lines.append(f"{pad}Predict: {self._leaf_value(t, i)}")
            else:
                thr = self.threshold[t, i]
                lines.append(f"{pad}If (feature {f} <= {thr})")
                rec(int(self.left[t, i]), indent + 1)
                lines.append(f"{pad}Else (feature {f} > {thr})")
                rec(int(self.right[t, i]), indent + 1)

        rec(0, 0)
        return "\n".join(lines)

    def _leaf_value(self, t: int, i: int) -> float:
        p = self.prediction[t, i]
        if self.is_classification:
            return float(np.argmax(p))
        return float(p[0])

    # -- persistence ----------------------------------------------------------
    def to_arrays(self) -> Dict[str, np.ndarray]:
        return {
            "tree_feature": self.feature, "tree_threshold": self.threshold,
            "tree_left": self.left, "tree_right": self.right,
            "tree_prediction": self.prediction, "tree_impurity": self.impurity,
            "tree_gain": self.gain, "tree_count": self.count,
            "tree_weight": self.weight, "tree_n_nodes": self.n_nodes,
            "tree_weights": self.tree_weights,
            "tree_num_features": np.array(self.num_features),
            "tree_is_classification": np.array(self.is_classification),
        }

    @classmethod
    def from_arrays(cls, a: Dict[str, np.ndarray]) -> "ForestData":
        return cls(feature=a["tree_feature"], threshold=a["tree_threshold"],
                   left=a["tree_left"], right=a["tree_right"],
                   prediction=a["tree_prediction"], impurity=a["tree_impurity"],
                   gain=a["tree_gain"], count=a["tree_count"],
                   weight=a["tree_weight"], n_nodes=a["tree_n_nodes"],
                   tree_weights=a["tree_weights"],
                   num_features=int(a["tree_num_features"]),
                   is_classification=bool(a["tree_is_classification"]))


# ---------------------------------------------------------------------------
# Driver-side tree bookkeeping
# ---------------------------------------------------------------------------

class _TreeBuilder:
    """Growable node table for one tree (explicit child pointers)."""

    def __init__(self, n_channels: int):
        self.feature: List[int] = []
        self.threshold: List[float] = []
        self.left: List[int] = []
        self.right: List[int] = []
        self.prediction: List[np.ndarray] = []
        self.impurity: List[float] = []
        self.gain: List[float] = []
        self.count: List[float] = []
        self.weight: List[float] = []
        self.C = n_channels

    def add_node(self) -> int:
        self.feature.append(-1)
        self.threshold.append(0.0)
        self.left.append(-1)
        self.right.append(-1)
        self.prediction.append(np.zeros(self.C))
        self.impurity.append(0.0)
        self.gain.append(0.0)
        self.count.append(0.0)
        self.weight.append(0.0)
        return len(self.feature) - 1


def _num_features_per_node(strategy: str, d: int, num_trees: int,
                           is_classification: bool) -> int:
    """ref RandomForestParams featureSubsetStrategy semantics."""
    s = strategy.lower()
    if s == "auto":
        if num_trees == 1:
            return d
        return (int(math.ceil(math.sqrt(d))) if is_classification
                else max(1, int(math.ceil(d / 3.0))))
    if s == "all":
        return d
    if s == "sqrt":
        return int(math.ceil(math.sqrt(d)))
    if s == "log2":
        return max(1, int(math.ceil(math.log2(max(d, 2)))))
    if s == "onethird":
        return max(1, int(math.ceil(d / 3.0)))
    try:
        v = float(strategy)
    except ValueError:
        raise ValueError(f"unsupported featureSubsetStrategy {strategy!r}")
    if v >= 1.0 and v == int(v):
        return min(d, int(v))
    if 0.0 < v < 1.0:
        return max(1, int(math.ceil(v * d)))
    raise ValueError(f"unsupported featureSubsetStrategy {strategy!r}")


def _impurity_and_pred(stats: np.ndarray, kind: str):
    """stats [..., C] channel layout: classification C=1+K (count, class
    weights); regression C=4 (count, w, wy, wy2). Returns (impurity, raw
    count, weighted count)."""
    if kind == "variance":
        cnt, w, wy, wy2 = (stats[..., i] for i in range(4))
        # float32 cumsum cancellation can leave tiny nonzero wy on empty
        # bins — mask on weight, don't divide by ~0
        mask = w > 1e-12
        safe = np.where(mask, w, 1.0)
        mean = wy / safe
        imp = np.where(mask, np.maximum(wy2 / safe - mean * mean, 0.0), 0.0)
        return imp, cnt, w
    cls = stats[..., 1:]
    w = cls.sum(axis=-1)
    safe = np.where(w > 1e-12, w, 1.0)
    p = cls / safe[..., None]
    if kind == "entropy":
        imp = -(p * np.log(np.maximum(p, 1e-300))).sum(axis=-1)
    else:  # gini
        imp = 1.0 - (p * p).sum(axis=-1)
    return imp, stats[..., 0], w


# ---------------------------------------------------------------------------
# Binned dataset (device side, row-sharded)
# ---------------------------------------------------------------------------

class BinnedDataset:
    """Bucketized features on device, reusable across trees/boosting rounds."""

    def __init__(self, ctx, bins, thresholds: np.ndarray, n_bins: np.ndarray,
                 n_rows: int, n_features: int,
                 valid_mask: "np.ndarray | None" = None):
        self.ctx = ctx
        self.bins = bins                    # [n_pad, d] int32, row-sharded
        self.thresholds = thresholds        # [d, B-1] float64 host
        self.n_bins = n_bins                # [d] host
        self.max_bins = int(n_bins.max())
        self.n_rows = n_rows
        self.n_features = n_features
        # real-row positions in padded space: chunked loaders interleave
        # padding per shard, so [:n_rows] slicing is NOT equivalent
        self.valid_idx = (np.nonzero(valid_mask)[0] if valid_mask is not None
                          else np.arange(n_rows))
        # compiled-program caches shared across grow_forest calls (GBT runs
        # many rounds over the same binned data — recompiling per round
        # would dominate fit time)
        self._hist_cache: Dict[tuple, object] = {}
        self._reassign_cache: Dict[tuple, object] = {}

    @classmethod
    def from_instance_dataset(cls, ds, max_bins: int, seed: int,
                              sample_cap: int = 10000) -> "BinnedDataset":
        import jax
        import jax.numpy as jnp

        x_host = ds.unpad(np.asarray(ds.x, dtype=np.float64))
        if ds.n_rows > sample_cap:
            rng = np.random.RandomState(seed)
            idx = rng.choice(ds.n_rows, size=sample_cap, replace=False)
            sample = x_host[idx]
        else:
            sample = x_host
        thresholds, n_bins = find_splits(sample, max_bins)

        th_dev = jnp.asarray(thresholds)

        def binize(x):
            # per-feature searchsorted: bin = #thresholds <= value
            def one(col, th):
                # side="left": bin = #thresholds < v, so v <= th[b] ⇔ bin <= b
                # — matches the raw-feature rule "value <= threshold goes left"
                return jnp.searchsorted(th, col, side="left").astype(jnp.int32)
            # follow the thresholds' dtype (f64 under x64 tests, f32 on
            # TPU) instead of requesting float64 outright — the latter is
            # a silent downcast on default TPU configs (graftlint JX004)
            return jax.vmap(one, in_axes=(1, 0), out_axes=1)(
                x.astype(th_dev.dtype), th_dev)

        rt = ds.ctx.mesh_runtime
        bins = jax.jit(binize, out_shardings=rt.data_sharding(extra_axes=1))(ds.x)
        return cls(ds.ctx, bins, thresholds, n_bins, ds.n_rows,
                   ds.n_features, valid_mask=ds._valid_mask)


# ---------------------------------------------------------------------------
# The forest grower
# ---------------------------------------------------------------------------

@dataclass
class ForestConfig:
    task: str = "classification"          # or "regression"
    num_classes: int = 2
    impurity: str = "gini"                 # gini|entropy|variance
    max_depth: int = 5
    min_instances_per_node: int = 1
    min_weight_fraction_per_node: float = 0.0
    min_info_gain: float = 0.0
    num_trees: int = 1
    feature_subset_strategy: str = "all"
    subsampling_rate: float = 1.0
    bootstrap: bool = False
    seed: int = 17


def grow_forest(binned: BinnedDataset, y: np.ndarray, w: np.ndarray,
                cfg: ForestConfig) -> ForestData:
    """Level-synchronous forest growth over the mesh.

    ``y``/``w`` are host arrays of length n_rows (labels are residuals for
    GBT rounds). One histogram psum per level covers ALL trees at once.
    """
    import jax
    import jax.numpy as jnp

    ctx, rt = binned.ctx, binned.ctx.mesh_runtime
    d, B, T = binned.n_features, binned.max_bins, cfg.num_trees
    classification = cfg.task == "classification"
    K = cfg.num_classes if classification else 0
    C = (1 + K) if classification else 4
    kind = cfg.impurity

    n_pad = binned.bins.shape[0]
    n = binned.n_rows

    # -- per-(row, tree) bootstrap counts (ref BaggedPoint: Poisson(rate) with
    # bootstrap, Bernoulli(rate) without) -------------------------------------
    rng = np.random.RandomState(cfg.seed)
    if T == 1 and not cfg.bootstrap and cfg.subsampling_rate >= 1.0:
        cnt_host = np.ones((n_pad, 1), dtype=np.float32)
    elif cfg.bootstrap:
        cnt_host = rng.poisson(cfg.subsampling_rate, size=(n_pad, T)).astype(np.float32)
    else:
        cnt_host = (rng.rand(n_pad, T) < cfg.subsampling_rate).astype(np.float32)
    vi = binned.valid_idx
    keep = np.zeros(n_pad, dtype=bool)
    keep[vi] = True
    cnt_host[~keep] = 0.0

    y_host = np.zeros(n_pad, dtype=np.float64)
    y_host[vi] = y
    w_host = np.zeros(n_pad, dtype=np.float64)
    w_host[vi] = w

    # stat channels per (row, tree): [n_pad, T, C]
    if classification:
        onehot = np.zeros((n_pad, K), dtype=np.float64)
        onehot[vi, np.clip(y.astype(np.int64), 0, K - 1)] = 1.0
        chans = np.concatenate(
            [cnt_host[:, :, None].astype(np.float64),
             onehot[:, None, :] * (w_host[:, None] * cnt_host.astype(np.float64))[:, :, None]],
            axis=2)
    else:
        ww = w_host[:, None] * cnt_host.astype(np.float64)
        chans = np.stack([cnt_host.astype(np.float64), ww,
                          ww * y_host[:, None], ww * y_host[:, None] ** 2], axis=2)

    chans_dev = rt.device_put_sharded_rows(chans.astype(np.float32))
    pos = rt.device_put_sharded_rows(
        np.where(cnt_host > 0, 0, -1).astype(np.int32))   # [n_pad, T]

    # -- compiled level programs (cached on BinnedDataset across calls) -------
    hist_cache = binned._hist_cache

    def hist_fn(A: int):
        key = (A, T, C)
        if key not in hist_cache:
            def local(bins_s, chans_s, pos_s):
                def one_tree(ch_t, pos_t):
                    active = pos_t >= 0
                    safe = jnp.where(active, pos_t, 0)
                    idx = (safe[:, None] * (d * B)
                           + jnp.arange(d, dtype=jnp.int32)[None, :] * B
                           + bins_s)                         # [b, d]
                    vals = jnp.where(active[:, None], ch_t, 0.0)  # [b, C]
                    vals = jnp.broadcast_to(vals[:, None, :],
                                            (vals.shape[0], d, C))
                    tbl = jnp.zeros((A * d * B, C), dtype=jnp.float32)
                    return tbl.at[idx.reshape(-1)].add(vals.reshape(-1, C))
                return jax.vmap(one_tree, in_axes=(1, 1))(chans_s, pos_s)
            hist_cache[key] = collectives.tree_aggregate(
                local, rt, binned.bins, chans_dev, pos)
        return hist_cache[key]  # call with (bins, chans, pos)

    if (T,) not in binned._reassign_cache:
        @jax.jit
        def reassign_fn(bins_a, pos_a, featA, binA, posL, posR):
            def one_tree(pos_t, f_t, b_t, l_t, r_t):
                active = pos_t >= 0
                safe = jnp.where(active, pos_t, 0)
                f = f_t[safe]                              # [b]
                split = f >= 0
                xv = jnp.take_along_axis(
                    bins_a, jnp.clip(f, 0, d - 1)[:, None], axis=1)[:, 0]
                nxt = jnp.where(xv <= b_t[safe], l_t[safe], r_t[safe])
                new = jnp.where(split, nxt, -1)            # settled → leaf
                return jnp.where(active, new, pos_t).astype(jnp.int32)
            return jax.vmap(one_tree, in_axes=(1, 0, 0, 0, 0),
                            out_axes=1)(pos_a, featA, binA, posL, posR)
        binned._reassign_cache[(T,)] = reassign_fn
    reassign = binned._reassign_cache[(T,)]

    # -- driver bookkeeping ----------------------------------------------------
    trees = [_TreeBuilder(K if classification else 1) for _ in range(T)]
    # active[t] = list of node ids at the current level, position-indexed
    active: List[List[int]] = [[tb.add_node()] for tb in trees]
    n_feat_subset = _num_features_per_node(
        cfg.feature_subset_strategy, d, T, classification)
    total_weight = float((w_host * cnt_host.mean(axis=1)).sum()) if T > 1 else float(
        (w_host * cnt_host[:, 0]).sum())
    # per-node min weight uses the full training weight (ref minWeightFractionPerNode)
    min_w = cfg.min_weight_fraction_per_node * max(total_weight, 1e-300)

    valid_split_mask = np.zeros((d, B), dtype=bool)        # [d, B] bins that exist
    for f in range(d):
        valid_split_mask[f, : max(int(binned.n_bins[f]) - 1, 0)] = True

    depth = 0
    while depth <= cfg.max_depth:
        A = max(len(a) for a in active)
        if A == 0:
            break
        A_pad = 1 << (A - 1).bit_length()
        hist = np.asarray(hist_fn(A_pad)(binned.bins, chans_dev, pos),
                          dtype=np.float64)                # [T, A_pad*d*B, C]
        hist = hist.reshape(T, A_pad, d, B, C)

        featA = np.full((T, A_pad), -1, dtype=np.int32)
        binA = np.zeros((T, A_pad), dtype=np.int32)
        posL = np.full((T, A_pad), -1, dtype=np.int32)
        posR = np.full((T, A_pad), -1, dtype=np.int32)
        next_active: List[List[int]] = [[] for _ in range(T)]
        any_split = False

        for t in range(T):
            if not active[t]:
                continue
            nodes = active[t]
            h = hist[t, :len(nodes)]                        # [a, d, B, C]
            parent = h.sum(axis=2)[:, 0, :]                 # [a, C] (same ∀ features)
            p_imp, p_cnt, p_w = _impurity_and_pred(parent, kind)

            cum = np.cumsum(h, axis=2)                      # left stats per split
            left_s = cum[:, :, :-1, :]                      # split after bin b
            right_s = parent[:, None, None, :] - left_s
            l_imp, l_cnt, l_w = _impurity_and_pred(left_s, kind)
            r_imp, r_cnt, r_w = _impurity_and_pred(right_s, kind)
            safe_w = np.maximum(p_w, 1e-300)[:, None, None]
            gain = (p_imp[:, None, None]
                    - (l_w * l_imp + r_w * r_imp) / safe_w)

            ok = (valid_split_mask[None, :, :-1]
                  & (l_cnt >= cfg.min_instances_per_node)
                  & (r_cnt >= cfg.min_instances_per_node)
                  & (l_w >= min_w) & (r_w >= min_w))
            if n_feat_subset < d:
                frng = np.random.RandomState(
                    (cfg.seed + 31 * depth + 131 * t) % (2 ** 31))
                sel = np.zeros((len(nodes), d), dtype=bool)
                for a_i in range(len(nodes)):
                    sel[a_i, frng.choice(d, size=n_feat_subset, replace=False)] = True
                ok &= sel[:, :, None]
            gain = np.where(ok, gain, -np.inf)

            for a_i, node_id in enumerate(nodes):
                tb = trees[t]
                tb.count[node_id] = float(p_cnt[a_i])
                tb.weight[node_id] = float(p_w[a_i])
                tb.impurity[node_id] = float(p_imp[a_i])
                if classification:
                    tb.prediction[node_id] = parent[a_i, 1:].copy()
                else:
                    m = parent[a_i, 2] / max(parent[a_i, 1], 1e-300)
                    tb.prediction[node_id] = np.array([m])

                g = gain[a_i]
                best = np.unravel_index(np.argmax(g), g.shape)
                best_gain = g[best]
                splittable = (depth < cfg.max_depth
                              and np.isfinite(best_gain)
                              and best_gain >= cfg.min_info_gain
                              and best_gain > 1e-12
                              and p_imp[a_i] > 0.0)
                if not splittable:
                    continue
                f_best, b_best = int(best[0]), int(best[1])
                tb.feature[node_id] = f_best
                tb.threshold[node_id] = float(binned.thresholds[f_best, b_best])
                tb.gain[node_id] = float(best_gain)
                lid, rid = tb.add_node(), tb.add_node()
                tb.left[node_id], tb.right[node_id] = lid, rid
                featA[t, a_i] = f_best
                binA[t, a_i] = b_best
                posL[t, a_i] = len(next_active[t])
                next_active[t].append(lid)
                posR[t, a_i] = len(next_active[t])
                next_active[t].append(rid)
                any_split = True

        if not any_split:
            break
        pos = reassign(binned.bins, pos,
                       jnp.asarray(featA), jnp.asarray(binA),
                       jnp.asarray(posL), jnp.asarray(posR))
        active = next_active
        depth += 1

    return _pack(trees, d, classification)


def _pack(trees: List["_TreeBuilder"], d: int, classification: bool) -> ForestData:
    T = len(trees)
    N = max(len(tb.feature) for tb in trees)
    C = trees[0].C

    def pad2(lists, dtype, fill=0):
        out = np.full((T, N), fill, dtype=dtype)
        for t, ls in enumerate(lists):
            out[t, :len(ls)] = ls
        return out

    pred = np.zeros((T, N, C), dtype=np.float64)
    for t, tb in enumerate(trees):
        for i, p in enumerate(tb.prediction):
            pred[t, i] = p
    return ForestData(
        feature=pad2([tb.feature for tb in trees], np.int32, -1),
        threshold=pad2([tb.threshold for tb in trees], np.float64),
        left=pad2([tb.left for tb in trees], np.int32, -1),
        right=pad2([tb.right for tb in trees], np.int32, -1),
        prediction=pred,
        impurity=pad2([tb.impurity for tb in trees], np.float64),
        gain=pad2([tb.gain for tb in trees], np.float64),
        count=pad2([tb.count for tb in trees], np.float64),
        weight=pad2([tb.weight for tb in trees], np.float64),
        n_nodes=np.array([len(tb.feature) for tb in trees], dtype=np.int32),
        tree_weights=np.ones(T, dtype=np.float64),
        num_features=d,
        is_classification=classification,
    )
