"""Shared tree params (ref: ml/tree/treeParams.scala — DecisionTreeParams,
TreeEnsembleParams, RandomForestParams, GBTParams). Same names, docs,
defaults, and validators as the reference's Param declarations."""

from __future__ import annotations

from cycloneml_tpu.ml.param import ParamValidators as V, Params
from cycloneml_tpu.ml.shared import HasSeed


class _DecisionTreeParams(HasSeed):
    def _declare_tree_params(self, impurity_allowed, impurity_default):
        self._p_seed(17)
        self.maxDepth = self._param(
            "maxDepth", "maximum tree depth (>= 0); depth 0 is one leaf",
            V.in_range(0, 30), default=5)
        self.maxBins = self._param(
            "maxBins", "max number of bins for discretizing continuous "
            "features (>= 2)", V.gt_eq(2), default=32)
        self.minInstancesPerNode = self._param(
            "minInstancesPerNode", "minimum number of instances each child "
            "must have after split (>= 1)", V.gt_eq(1), default=1)
        self.minWeightFractionPerNode = self._param(
            "minWeightFractionPerNode", "minimum fraction of the weighted "
            "sample count each child must have after split",
            V.in_range(0.0, 0.5, True, False), default=0.0)
        self.minInfoGain = self._param(
            "minInfoGain", "minimum information gain for a split",
            V.gt_eq(0.0), default=0.0)
        self.maxMemoryInMB = self._param(
            "maxMemoryInMB", "memory budget for histogram aggregation "
            "(accepted for API parity; the dense engine sizes itself)",
            V.gt_eq(0), default=256)
        self.cacheNodeIds = self._param(
            "cacheNodeIds", "node-id caching (always on: assignments live "
            "on device)", default=False)
        self.checkpointInterval = self._param(
            "checkpointInterval", "checkpoint interval for node-id cache",
            default=10)
        self.impurity = self._param(
            "impurity", "impurity criterion", V.in_array(impurity_allowed),
            default=impurity_default)

    def set_max_depth(self, v):
        return self.set("maxDepth", v)

    def set_max_bins(self, v):
        return self.set("maxBins", v)

    def set_min_instances_per_node(self, v):
        return self.set("minInstancesPerNode", v)

    def set_min_info_gain(self, v):
        return self.set("minInfoGain", v)

    def set_impurity(self, v):
        return self.set("impurity", v)

    def set_seed(self, v):
        return self.set("seed", v)


class _TreeEnsembleParams(_DecisionTreeParams):
    def _declare_ensemble_params(self, subset_default):
        self.subsamplingRate = self._param(
            "subsamplingRate", "fraction of training data per tree",
            V.in_range(0.0, 1.0, False, True), default=1.0)
        self.featureSubsetStrategy = self._param(
            "featureSubsetStrategy", "features to consider per split: auto, "
            "all, onethird, sqrt, log2, n (int), or fraction (0,1]",
            default=subset_default)

    def set_subsampling_rate(self, v):
        return self.set("subsamplingRate", v)

    def set_feature_subset_strategy(self, v):
        return self.set("featureSubsetStrategy", v)


class _RandomForestParams(_TreeEnsembleParams):
    def _declare_rf_params(self):
        self._declare_ensemble_params("auto")
        self.numTrees = self._param(
            "numTrees", "number of trees (>= 1)", V.gt_eq(1), default=20)
        self.bootstrap = self._param(
            "bootstrap", "whether to bootstrap-sample rows per tree",
            default=True)

    def set_num_trees(self, v):
        return self.set("numTrees", v)

    def set_bootstrap(self, v):
        return self.set("bootstrap", v)


class _GBTParams(_TreeEnsembleParams):
    def _declare_gbt_params(self, loss_allowed, loss_default):
        self._declare_ensemble_params("all")
        self.maxIter = self._param(
            "maxIter", "number of boosting rounds (>= 0)", V.gt_eq(0),
            default=20)
        self.stepSize = self._param(
            "stepSize", "learning rate in (0, 1]",
            V.in_range(0.0, 1.0, False, True), default=0.1)
        self.lossType = self._param(
            "lossType", "loss function", V.in_array(loss_allowed),
            default=loss_default)
        self.validationTol = self._param(
            "validationTol", "early-stopping tolerance on validation error",
            V.gt_eq(0.0), default=0.01)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_step_size(self, v):
        return self.set("stepSize", v)

    def set_loss_type(self, v):
        return self.set("lossType", v)
