"""Tree-ensemble engine shared by classification and regression estimators
(ref: ml/tree/ — the impl/ package and treeParams.scala)."""

from cycloneml_tpu.ml.tree.impl import (
    BinnedDataset, ForestConfig, ForestData, find_splits, grow_forest,
)
from cycloneml_tpu.ml.tree.params import (
    _DecisionTreeParams, _GBTParams, _RandomForestParams, _TreeEnsembleParams,
)

__all__ = [
    "BinnedDataset", "ForestConfig", "ForestData", "find_splits",
    "grow_forest", "_DecisionTreeParams", "_GBTParams", "_RandomForestParams",
    "_TreeEnsembleParams",
]
