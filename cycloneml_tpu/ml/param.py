"""ML parameter system.

Mirrors the reference's per-instance ``Param``/``ParamMap`` semantics
(ref: mllib/src/main/scala/org/apache/spark/ml/param/params.scala): typed
params with docs and validators, per-instance default vs. user-set maps,
``copy``/``extractParamMap``, and JSON persistence of values — the contract
``DefaultParamsWriter`` relies on.
"""

from __future__ import annotations

import json
import uuid
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

import numpy as np

T = TypeVar("T")


class Param(Generic[T]):
    """A param with self-contained documentation (≈ params.scala Param)."""

    def __init__(self, parent: str, name: str, doc: str,
                 is_valid: Optional[Callable[[T], bool]] = None):
        self.parent = parent
        self.name = name
        self.doc = doc
        self.is_valid = is_valid or (lambda v: True)

    def validate(self, value: T) -> None:
        if not self.is_valid(value):
            raise ValueError(f"{self.parent}_{self.name} given invalid value {value!r}")

    def __repr__(self) -> str:
        return f"{self.parent}__{self.name}"

    def __hash__(self) -> int:
        return hash((self.parent, self.name))

    def __eq__(self, other) -> bool:
        return isinstance(other, Param) and (self.parent, self.name) == (other.parent, other.name)

    # JSON codecs used by model persistence
    def json_encode(self, value: T) -> str:
        if isinstance(value, np.ndarray):
            return json.dumps(value.tolist())
        return json.dumps(value)

    def json_decode(self, s: str) -> T:
        return json.loads(s)


class ParamValidators:
    """Factory of common validators (≈ params.scala ParamValidators)."""

    @staticmethod
    def gt(lower: float) -> Callable:
        return lambda v: v > lower

    @staticmethod
    def gt_eq(lower: float) -> Callable:
        return lambda v: v >= lower

    @staticmethod
    def lt(upper: float) -> Callable:
        return lambda v: v < upper

    @staticmethod
    def lt_eq(upper: float) -> Callable:
        return lambda v: v <= upper

    @staticmethod
    def in_range(lo: float, hi: float, lower_inclusive: bool = True,
                 upper_inclusive: bool = True) -> Callable:
        def check(v):
            ok_lo = v >= lo if lower_inclusive else v > lo
            ok_hi = v <= hi if upper_inclusive else v < hi
            return ok_lo and ok_hi
        return check

    @staticmethod
    def in_array(allowed: List) -> Callable:
        return lambda v: v in allowed

    @staticmethod
    def array_length_gt(lower: int) -> Callable:
        return lambda v: len(v) > lower


class ParamMap:
    """A map of param → value (≈ params.scala ParamMap)."""

    def __init__(self, initial: Optional[Dict[Param, Any]] = None):
        self._map: Dict[Param, Any] = dict(initial or {})

    def put(self, param: Param, value: Any) -> "ParamMap":
        param.validate(value)
        self._map[param] = value
        return self

    def get(self, param: Param, default: Any = None) -> Any:
        return self._map.get(param, default)

    def contains(self, param: Param) -> bool:
        return param in self._map

    def remove(self, param: Param) -> Any:
        return self._map.pop(param, None)

    def copy(self) -> "ParamMap":
        return ParamMap(self._map)

    def items(self):
        return self._map.items()

    def __len__(self) -> int:
        return len(self._map)

    def __iter__(self):
        return iter(self._map)

    def __add__(self, other: "ParamMap") -> "ParamMap":
        m = self.copy()
        m._map.update(other._map)
        return m


class Params:
    """Base trait for components that take parameters (≈ params.scala Params).

    Subclasses declare params as class attributes built in ``_declare_params``
    or module scope; per-instance state lives in ``_param_map`` (user-set) and
    ``_default_param_map`` (defaults) exactly like the reference's paramMap /
    defaultParamMap split, which persistence depends on.
    """

    def __init__(self, uid: Optional[str] = None):
        self.uid = uid or f"{type(self).__name__}_{uuid.uuid4().hex[:12]}"
        self._params: Dict[str, Param] = {}
        self._param_map = ParamMap()
        self._default_param_map = ParamMap()

    # -- param declaration ---------------------------------------------------
    def _param(self, name: str, doc: str, is_valid: Optional[Callable] = None,
               default: Any = None) -> Param:
        p = Param(type(self).__name__, name, doc, is_valid)
        self._params[name] = p
        if default is not None:
            self._set_default(p, default)
        return p

    def _set_default(self, param: Param, value: Any) -> None:
        if value is not None:
            self._default_param_map.put(param, value)

    # -- access ---------------------------------------------------------------
    @property
    def params(self) -> List[Param]:
        return sorted(self._params.values(), key=lambda p: p.name)

    def get_param(self, name: str) -> Param:
        if name not in self._params:
            raise KeyError(f"Param {name} does not exist on {self.uid}")
        return self._params[name]

    def is_set(self, param: Param) -> bool:
        return self._param_map.contains(param)

    def is_defined(self, param: Param) -> bool:
        return self._param_map.contains(param) or self._default_param_map.contains(param)

    def has_default(self, param: Param) -> bool:
        return self._default_param_map.contains(param)

    def get_or_default(self, param: Param) -> Any:
        if self._param_map.contains(param):
            return self._param_map.get(param)
        if self._default_param_map.contains(param):
            return self._default_param_map.get(param)
        raise KeyError(f"Param {param} is not set and has no default")

    def get_default(self, param: Param) -> Any:
        return self._default_param_map.get(param)

    def set(self, param, value) -> "Params":
        if isinstance(param, str):
            param = self.get_param(param)
        self._param_map.put(param, value)
        return self

    def clear(self, param: Param) -> "Params":
        self._param_map.remove(param)
        return self

    def extract_param_map(self, extra: Optional[ParamMap] = None) -> ParamMap:
        m = self._default_param_map.copy() + self._param_map
        if extra is not None:
            m = m + extra
        return m

    # convenience: obj.get('maxIter')
    def get(self, name: str) -> Any:
        return self.get_or_default(self.get_param(name))

    # -- copy -----------------------------------------------------------------
    def copy(self, extra: Optional[ParamMap] = None) -> "Params":
        import copy as _copy
        that = _copy.copy(self)
        that._param_map = self._param_map.copy()
        that._default_param_map = self._default_param_map.copy()
        # re-point params at the clone: Param identity is (parent, name) so
        # the shared class-level declarations remain valid
        if extra is not None:
            for p, v in extra.items():
                if p.name in that._params:
                    that._param_map.put(that._params[p.name], v)
        return that

    def _copy_values(self, to: "Params", extra: Optional[ParamMap] = None) -> "Params":
        """Copy explicitly-set param values from this instance to ``to`` (≈ copyValues)."""
        m = self._param_map.copy() + (extra or ParamMap())
        for p, v in m.items():
            if p.name in to._params:
                to.set(to.get_param(p.name), v)
        return to

    # -- persistence helpers ---------------------------------------------------
    def _params_to_json(self) -> Dict[str, Any]:
        out = {}
        for name, p in self._params.items():
            if self._param_map.contains(p):
                v = self._param_map.get(p)
                out[name] = json.loads(p.json_encode(v))
        return out

    def _default_params_to_json(self) -> Dict[str, Any]:
        out = {}
        for name, p in self._params.items():
            if self._default_param_map.contains(p):
                v = self._default_param_map.get(p)
                out[name] = json.loads(p.json_encode(v))
        return out

    def _set_params_from_json(self, d: Dict[str, Any], default: bool = False) -> None:
        for name, v in d.items():
            if name in self._params:
                if default:
                    self._default_param_map.put(self._params[name], v)
                else:
                    self._param_map.put(self._params[name], v)

    def explain_param(self, param: Param) -> str:
        value = "undefined"
        if self.is_defined(param):
            value = repr(self.get_or_default(param))
        default = ""
        if self.has_default(param):
            default = f" (default: {self.get_default(param)!r})"
        return f"{param.name}: {param.doc}{default} (current: {value})"

    def explain_params(self) -> str:
        return "\n".join(self.explain_param(p) for p in self.params)
