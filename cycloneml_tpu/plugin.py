"""Application plugin API.

Analog of the reference's plugin framework (ref: core/.../api/plugin/
SparkPlugin.java:37, DriverPlugin.java:33, ExecutorPlugin.java:32 and the
PluginContainer that loads ``spark.plugins``). The executor side collapses
into the driver on TPU (SPMD steps, no task executors), so one hook set
covers both: ``init`` at context start, ``shutdown`` at stop, plus the event
bus and metrics registry for instrumentation — the same surfaces the
reference hands plugins (listener bus registration, metric registration).
"""

from __future__ import annotations

import importlib
from typing import Any, Dict, List

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class CyclonePlugin:
    """Subclass and list the class path in ``cyclone.plugins``."""

    def init(self, ctx, extra_conf: Dict[str, str]) -> None:
        """Called once after the mesh is up (≈ DriverPlugin.init)."""

    def shutdown(self) -> None:
        """Called at context stop (≈ DriverPlugin.shutdown)."""

    def registered_metrics(self) -> Dict[str, Any]:
        """Optional name → callable gauges merged into the registry
        (≈ registering with the plugin MetricRegistry)."""
        return {}


def load_plugins(ctx, class_paths: List[str]) -> List[CyclonePlugin]:
    """Instantiate 'pkg.module.Class' paths (ref: Utils.loadExtensions)."""
    out: List[CyclonePlugin] = []
    for path in class_paths:
        path = path.strip()
        if not path:
            continue
        mod_name, _, cls_name = path.rpartition(".")
        try:
            cls = getattr(importlib.import_module(mod_name), cls_name)
            plugin: CyclonePlugin = cls()
            plugin.init(ctx, ctx.conf.get_all())
        except Exception:
            # a broken plugin must not take down the app (the reference
            # logs and continues likewise)
            logger.exception("failed to load plugin %s", path)
            continue
        # init succeeded: the plugin owns resources now, so it must reach
        # the shutdown list even if its metric registration breaks
        out.append(plugin)
        try:
            for name, fn in (plugin.registered_metrics() or {}).items():
                ctx.metrics.registry.gauge(f"plugin.{name}", fn)
        except Exception:
            logger.exception("plugin %s metric registration failed", path)
        logger.info("loaded plugin %s", path)
    return out
