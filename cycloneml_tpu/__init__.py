"""cycloneml_tpu — a TPU-native distributed ML framework.

A ground-up JAX/XLA/pjit/Pallas re-design with the capabilities of
wmeddie/CycloneML (an Apache Spark 3.3 fork): distributed datasets over a
device mesh, an MLlib-compatible estimator/pipeline API, a BLAS offload
boundary compiled to XLA:TPU, tree-aggregate gradient reductions as
``jax.lax.psum`` over ICI, and a host control plane for dispatch, heartbeat
and checkpointing. See SURVEY.md at the repo root for the reference map.
"""

__version__ = "0.1.0"

from cycloneml_tpu.conf import CycloneConf
from cycloneml_tpu.context import CycloneContext

__all__ = ["CycloneConf", "CycloneContext", "__version__"]
