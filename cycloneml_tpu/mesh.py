"""Device-mesh runtime.

TPU-native replacement for the reference's driver bring-up + executor
registration (ref: SparkContext.scala:83 → SparkEnv.createDriverEnv →
CoarseGrainedSchedulerBackend registration, SURVEY §3.1). There is no
executor fleet to register: the "cluster" is a ``jax.sharding.Mesh`` over all
attached devices; gang scheduling (ref: BarrierTaskContext.scala:43) is
inherent — every jitted step is an SPMD program over the whole mesh.

Master-URL grammar (≈ SparkContext.scala:3058 master parsing):
  ``local-mesh[N]``   N host-platform devices (test fixture; requires
                      ``--xla_force_host_platform_device_count=N``)
  ``local-mesh[*]``   all visible devices of the default platform
  ``tpu``             all attached TPU devices
  ``multihost``       ``jax.distributed.initialize()`` then all global devices

The mesh is laid out ``(replica, data)``: ``data`` is the intra-slice axis
whose collectives ride ICI; ``replica`` crosses slices/hosts over DCN and is
1 on a single slice. ``tree_aggregate`` maps to a psum over ``data`` followed
by a psum over ``replica`` — the hierarchical ICI-then-DCN reduction that
replaces the reference's log-depth ``treeAggregate`` (ref: RDD.scala:1223).

Multi-process masters route through :mod:`cycloneml_tpu.multihost`:
``bootstrap`` owns the ``jax.distributed`` lifecycle (version-compat
``is_initialized``, CPU-smoke gloo collectives, coordinator preflight,
barriered teardown) and ``hierarchy`` builds the device grid so replica
rows align with process (DCN) boundaries — ``n_replicas=None`` defaults
to one replica row per process.
"""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

DATA_AXIS = "data"
REPLICA_AXIS = "replica"
MODEL_AXIS = "model"

_LOCAL_MESH_RE = re.compile(r"local-mesh\[(\d+|\*)\]")
_MULTIHOST_RE = re.compile(r"multihost\[([^,\]]+),(\d+),(\d+)\]")


_comp_cache_enabled = False


def _enable_compilation_cache(jax) -> None:
    """Persist compiled XLA executables on disk across processes.

    TPU compiles are the dominant fixed cost (tens of seconds per program
    through a remote backend), and every new process would otherwise pay
    them again — the reference ships pre-compiled JVM bytecode and never
    has this problem, so matching its warm-start behavior requires the
    persistent cache. Off-switch: CYCLONE_NO_COMPILATION_CACHE=1.
    """
    global _comp_cache_enabled
    if _comp_cache_enabled or __import__("os").environ.get(
            "CYCLONE_NO_COMPILATION_CACHE"):
        return
    import os
    path = os.environ.get(
        "CYCLONE_COMPILATION_CACHE_DIR",
        os.path.expanduser("~/.cache/cycloneml_tpu/xla-cache"))
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _comp_cache_enabled = True
    except Exception as e:  # cache is an optimization, never a hard failure
        logger.info("persistent compilation cache unavailable: %s", e)


def _disable_compilation_cache(jax) -> None:
    global _comp_cache_enabled
    if not _comp_cache_enabled:
        return
    try:
        jax.config.update("jax_compilation_cache_dir", None)
        _comp_cache_enabled = False
    except Exception:
        pass


class MeshRuntime:
    """Owns the global device mesh and sharding helpers."""

    def __init__(self, master: str = "tpu",
                 n_replicas: Optional[int] = None,
                 model_parallelism: int = 1):
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        self._jax = jax
        devices = self._resolve_devices(master)
        if devices and devices[0].platform != "cpu":
            # TPU/accelerator only: XLA:CPU AOT cache entries record compile-
            # machine features that the loader may refuse or execute with
            # different codegen (observed: prefer-no-scatter mismatch causing
            # reduction-order drift in tests); CPU compiles are cheap anyway
            _enable_compilation_cache(jax)
        else:
            # a reset()+rebuild onto CPU must also UNDO a previously enabled
            # cache, or the CPU mesh inherits the TPU mesh's cache dir and
            # hits the exact AOT hazard above
            _disable_compilation_cache(jax)
        from cycloneml_tpu.multihost import hierarchy
        dev_grid, n_replicas = hierarchy.build_device_grid(
            devices, n_replicas, model_parallelism)
        self.mesh = Mesh(dev_grid, (REPLICA_AXIS, DATA_AXIS, MODEL_AXIS))
        self.master = master
        self.n_devices = len(devices)
        self.n_replicas = n_replicas
        topo = hierarchy.describe(dev_grid)
        self.n_processes = topo["n_processes"]
        self.dcn_aligned = topo["dcn_aligned"]
        self.platform = devices[0].platform
        self._P = PartitionSpec
        self._NamedSharding = NamedSharding
        logger.info("Mesh up: %d %s devices over %d process(es), shape %s",
                    self.n_devices, self.platform, self.n_processes,
                    dict(zip(self.mesh.axis_names, self.mesh.devices.shape)))

    @property
    def is_multihost(self) -> bool:
        """True when the mesh spans processes — collectives over the
        ``replica`` axis cross DCN (or its CPU-smoke stand-in)."""
        return self.n_processes > 1

    @property
    def process_index(self) -> int:
        from cycloneml_tpu.multihost import bootstrap
        return bootstrap.process_index()

    @staticmethod
    def _resolve_devices(master: str):
        import jax

        from cycloneml_tpu.multihost import bootstrap
        m = _LOCAL_MESH_RE.fullmatch(master)
        if m is not None:
            want = m.group(1)
            # LOCAL devices by definition: under an initialized
            # jax.distributed runtime (e.g. a survivor rebuilding after
            # host loss) jax.devices() still lists the dead peers'
            # devices — a local mesh must never include them
            devices = jax.local_devices()
            if want != "*":
                want_n = int(want)
                if len(devices) < want_n:
                    raise RuntimeError(
                        f"local-mesh[{want_n}] needs {want_n} devices but only "
                        f"{len(devices)} are visible; set XLA_FLAGS="
                        f"--xla_force_host_platform_device_count={want_n}")
                devices = devices[:want_n]
            return devices
        if master == "multihost":
            bootstrap.initialize()  # env/cloud auto-detection
            return bootstrap.global_devices()
        m = _MULTIHOST_RE.fullmatch(master)
        if m is not None:
            # explicit form for local-cluster-style testing and bare-metal
            # pods: multihost[<coordinator host:port>,<num_procs>,<proc_id>]
            # (≈ the reference's local-cluster[n,c,m] master,
            # SparkContext.scala:3058 — real separate processes, one mesh)
            bootstrap.initialize(coordinator_address=m.group(1),
                                 num_processes=int(m.group(2)),
                                 process_id=int(m.group(3)))
            return bootstrap.global_devices()
        if master == "tpu":
            try:
                return jax.devices("tpu")
            except RuntimeError:
                logger.warning("no TPU attached; falling back to default platform")
                return jax.devices()
        raise ValueError(f"cannot parse master URL: {master!r}")

    # -- sharding helpers ------------------------------------------------------
    def data_sharding(self, extra_axes: int = 1):
        """Shard leading (row/block) dim over replica+data, replicate the rest."""
        spec = self._P((REPLICA_AXIS, DATA_AXIS), *([None] * extra_axes))
        return self._NamedSharding(self.mesh, spec)

    def replicated(self):
        return self._NamedSharding(self.mesh, self._P())

    def model_sharding(self, axis_index: int, ndim: int):
        """Shard dimension ``axis_index`` over the model axis (feature-dim TP
        for coefficient/Gram objects that exceed one device's HBM,
        SURVEY §5.7(a))."""
        spec = [None] * ndim
        spec[axis_index] = MODEL_AXIS
        return self._NamedSharding(self.mesh, self._P(*spec))

    @property
    def data_parallelism(self) -> int:
        return (self.mesh.devices.shape[0] * self.mesh.devices.shape[1])

    def device_put_sharded_rows(self, arr: np.ndarray):
        """Place a host array on the mesh, rows sharded over replica×data."""
        import jax
        return jax.device_put(arr, self.data_sharding(arr.ndim - 1))

    def device_put_replicated(self, tree):
        import jax
        return jax.device_put(tree, self.replicated())


def safe_fit_parallelism(requested: int, stacked_width: int = 0) -> int:
    """Effective parallelism for concurrent estimator fits on the active
    mesh; returns the width the caller may actually use (and report).

    THREAD pools are still capped: every jitted step is a gang-scheduled
    SPMD program over the WHOLE mesh; two programs dispatched concurrently
    from different threads interleave their per-device executions and
    deadlock XLA's collective rendezvous (observed: OneVsRest(parallelism=4)
    hanging the suite on local-mesh[8] once shard_map was un-broken; now
    mechanized as graftlint JX007). A >1 width is returned only on
    single-device meshes, where no cross-device rendezvous exists — though
    the in-repo estimators no longer build pools at all (they stack or run
    serially, and call this for the cap log + effective-width report); the
    reference's ``parallelism`` param parallelizes independent Spark jobs
    across a cluster, a resource this mesh model does not have.

    STACKED fits are the sanctioned parallel path: ``stacked_width > 0``
    declares that the caller runs that many models as ONE vmapped SPMD
    program — a single gang-scheduled dispatch with a leading model axis
    (docs/multi-model.md), so no cross-program rendezvous exists and full
    model-parallelism is safe on any mesh size. The stacked width is
    returned so callers can report the effective parallelism they achieved.
    """
    if stacked_width > 0:
        return stacked_width
    if requested <= 1:
        return requested
    rt = active()
    if rt is not None and rt.n_devices > 1:
        logger.info(
            "capping thread-pool fit parallelism %d -> 1: concurrent SPMD "
            "dispatch onto a shared %d-device mesh would deadlock its "
            "collectives; stacked fits (vmapped model axis, one program) "
            "are the sanctioned parallel path", requested, rt.n_devices)
        return 1
    return requested


def probe_device_count(master: str) -> Optional[int]:
    """Devices a master URL would select, WITHOUT building a mesh — lets
    callers validate a resource request before tearing down the active mesh.
    None when unknowable up-front (multihost initializes on construction);
    a master that definitively cannot be built (e.g. local-mesh[8] with 4
    visible devices) RAISES, so callers fail before any teardown."""
    if master == "multihost":
        return None
    return len(MeshRuntime._resolve_devices(master))


_active: Optional[MeshRuntime] = None


_active_lock = __import__("threading").Lock()

# monotonic mesh GENERATION: bumped by every reset() (rebuild, elastic
# reshape, decommission). Compiled aggregation programs capture the epoch
# they were built under and collectives._instrument_dispatch refuses to
# dispatch a program across a bump (StaleProgramError) — the RUNTIME twin
# of graftlint JX017's static cross-mesh check: on CPU a stale program
# silently runs on the old virtual devices and on TPU it dies deep inside
# XLA; the guard turns both into one classified, actionable error.
_mesh_epoch = 0


def mesh_epoch() -> int:
    """Current mesh generation (advances on every teardown/rebuild)."""
    return _mesh_epoch


def get_or_create(master: str = "tpu", **kw) -> MeshRuntime:
    global _active
    with _active_lock:
        if _active is None:
            _active = MeshRuntime(master, **kw)
        elif _active.master != master:
            raise RuntimeError(
                f"A mesh is already active for master {_active.master!r}; "
                f"cannot re-initialise for {master!r}. Stop all contexts and "
                "call mesh.reset() first.")
        return _active


def active() -> Optional[MeshRuntime]:
    return _active


def reset() -> None:
    global _active, _mesh_epoch
    _active = None
    _mesh_epoch += 1
    from cycloneml_tpu.parallel import collectives
    collectives.clear_program_cache()
