"""``python -m cycloneml_tpu.observe.doctor`` — the doctor's CLI.

Diagnoses an exported Chrome trace (or a flight-recorder dump JSON)
OFFLINE: no live process sources are consulted, so the same file
produces a byte-identical ``--json`` report on every run (the
determinism gate in scripts/doctor_demo.py pins this).

    python -m cycloneml_tpu.observe.doctor trace.json
    python -m cycloneml_tpu.observe.doctor trace.json --json
    python -m cycloneml_tpu.observe.doctor dump.json \\
        --set cyclone.doctor.overlapMin=0.5

Exit code: 0 on a healthy report, 2 when any warning/critical finding
fires (info-only reports stay 0) — so `make doctor` can gate.
Import-light: reads JSON, never imports jax.
"""

import argparse
import json
import sys
from typing import Any, List, Optional


def _coerce(raw: str) -> Any:
    if raw.lower() in ("true", "false"):
        return raw.lower() == "true"
    for cast in (int, float):
        try:
            return cast(raw)
        except ValueError:
            continue
    return raw


def _load_spans(path: str):
    from cycloneml_tpu.observe.export import spans_from_chrome_trace
    with open(path, "r", encoding="utf-8") as fh:
        obj = json.load(fh)
    if isinstance(obj, dict) and "traceEvents" in obj:
        return spans_from_chrome_trace(obj), "trace"
    if isinstance(obj, dict) and "spans" in obj:
        # a flight-recorder dump: spans are serialized dicts
        from cycloneml_tpu.observe.tracing import Span
        spans = []
        for d in obj["spans"]:
            s = Span(str(d.get("span_id", "")), str(d.get("parent_id", "")),
                     d.get("kind", ""), d.get("name", ""),
                     int(d.get("tid", 0)), dict(d.get("attrs", {})))
            s.t0 = float(d.get("t0", 0.0))
            s.t1 = float(d.get("t1", s.t0))
            spans.append(s)
        return spans, "flight"
    raise SystemExit(f"doctor: {path} is neither a Chrome trace "
                     f"(traceEvents) nor a flight dump (spans)")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m cycloneml_tpu.observe.doctor",
        description="diagnose an exported trace / flight dump offline")
    ap.add_argument("trace", help="Chrome trace or flight-dump JSON file")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the canonical-JSON report (byte-stable)")
    ap.add_argument("--set", action="append", default=[], metavar="K=V",
                    help="cyclone.doctor.* / skew / SLO conf override")
    ns = ap.parse_args(argv)

    conf = None
    if ns.set:
        from cycloneml_tpu.conf import CycloneConf
        conf = CycloneConf()
        for kv in ns.set:
            key, _, raw = kv.partition("=")
            if not _ or not key:
                raise SystemExit(f"doctor: --set expects K=V, got {kv!r}")
            conf.set(key, _coerce(raw))

    spans, source = _load_spans(ns.trace)
    from cycloneml_tpu.observe.diagnose import diagnose
    report = diagnose(spans=spans, skew=None, cache_stats=None,
                      serving_stats=None, conf=conf, source=source)
    if ns.as_json:
        sys.stdout.write(report.to_json() + "\n")
    else:
        sys.stdout.write(report.render_text() + "\n")
    worst = any(f.severity in ("warning", "critical")
                for f in report.findings)
    return 2 if worst else 0


if __name__ == "__main__":
    sys.exit(main())
