"""Regression sentinel: an append-only bench-history ledger plus robust
drift detection over it.

The five committed BENCH_r01-r05 runs document a 13.9 -> 190 G ops/s
trajectory with no machinery watching it — a perf regression today lands
silently. This module closes that gap:

- ``rows_from_bench(block, meta)`` flattens one BENCH JSON block into
  gateable metric rows (headline throughput + the nested sub-metrics in
  ``GATED``), each joined to the run's ``meta`` identity (run_id /
  git sha / logical timestamp — NEVER wall clock) and hardware meta.
- ``append(path, rows)`` appends canonical-JSON rows (sorted keys, tight
  separators: the autoscale-sim byte-determinism idiom) to
  ``artifacts/bench_history.jsonl``, idempotently keyed by
  ``(run_id, metric)`` — re-running a backfill adds nothing.
- ``detect(rows, cfg)`` judges the NEWEST row of each metric against the
  median + MAD of up to ``window`` preceding comparable rows (same
  metric + hardware), with per-direction thresholds: drift past
  ``median +/- max(mad_factor*MAD, rel_tol*median)`` in the bad
  direction is a ``regression`` verdict, in the good direction an
  ``improvement``; too little history is ``insufficient-history``.
- ``gate(verdicts)`` maps verdicts to a process exit code: any
  regression is nonzero.

Deterministic on purpose: rows are ordered by ``(t_logical, file
order)``, verdicts by metric name, and nothing here reads a clock.
Import-light: no jax, no numpy.
"""

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from cycloneml_tpu.conf import (REGRESS_MAD_FACTOR, REGRESS_MIN_RUNS,
                                REGRESS_REL_TOL, REGRESS_WINDOW)

SCHEMA_VERSION = 1

# (nested block, field, direction) of every gated sub-metric; the
# headline ``value`` row is always emitted under the block's own metric
# name. Absent blocks are skipped — old BENCH files stay ingestible.
GATED = (
    ("serving", "requests_per_s", "higher"),
    ("serving", "p99_ms", "lower"),
    ("ovr", "ovr_stacked_speedup", "higher"),
)


@dataclass
class DriftConfig:
    window: int = 5
    mad_factor: float = 4.0
    rel_tol: float = 0.05
    min_runs: int = 3
    # MAD-term ceiling as a fraction of |median|: a fast-improving
    # history (r02->r05 is 13.9x) has a MAD so large that
    # mad_factor*MAD exceeds the median itself, and a gate whose
    # threshold is wider than the measurement can never fire. Capping
    # keeps the gate honest on non-stationary history.
    cap_fraction: float = 0.5

    @classmethod
    def from_conf(cls, conf) -> "DriftConfig":
        return cls(window=conf.get(REGRESS_WINDOW),
                   mad_factor=conf.get(REGRESS_MAD_FACTOR),
                   rel_tol=conf.get(REGRESS_REL_TOL),
                   min_runs=conf.get(REGRESS_MIN_RUNS))


def _median(values: List[float]) -> float:
    vs = sorted(values)
    n = len(vs)
    mid = n // 2
    return vs[mid] if n % 2 else (vs[mid - 1] + vs[mid]) / 2.0


def canonical_row(row: Dict[str, Any]) -> str:
    """One ledger line: canonical JSON, byte-stable across runs."""
    return json.dumps(row, sort_keys=True, separators=(",", ":"))


def rows_from_bench(block: Dict[str, Any],
                    meta: Optional[Dict[str, Any]] = None
                    ) -> List[Dict[str, Any]]:
    """Flatten one parsed BENCH block into ledger rows. ``meta``
    overrides the block's own ``meta`` (backfills synthesize identity
    for pre-meta BENCH files)."""
    meta = dict(meta if meta is not None else block.get("meta", {}))
    hw = block.get("hardware")
    hw_key = ({"platform": hw.get("platform"),
               "device": hw.get("device_kind", hw.get("device")),
               "n_devices": hw.get("n_devices")} if isinstance(hw, dict)
              else None)
    base = {"schema": SCHEMA_VERSION,
            "run_id": str(meta.get("run_id", "")),
            "git_sha": str(meta.get("git_sha", "")),
            "t_logical": int(meta.get("t_logical", 0)),
            "hw": hw_key}
    rows: List[Dict[str, Any]] = []
    if "metric" in block and "value" in block:
        rows.append(dict(base, metric=str(block["metric"]),
                         value=float(block["value"]),
                         unit=str(block.get("unit", "")),
                         direction="higher"))
    for sub, fld, direction in GATED:
        inner = block.get(sub)
        if isinstance(inner, dict) and isinstance(
                inner.get(fld), (int, float)):
            rows.append(dict(base, metric=f"{sub}.{fld}",
                             value=float(inner[fld]), unit="",
                             direction=direction))
    return rows


def load(path: str) -> List[Dict[str, Any]]:
    """Ledger rows in file order; corrupt lines are skipped (the ledger
    is append-only — one torn tail line must not poison history)."""
    rows: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return rows
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict) and "metric" in row:
                rows.append(row)
    return rows


def append(path: str, rows: List[Dict[str, Any]]) -> int:
    """Append rows not already present (keyed by run_id + metric);
    returns how many were written."""
    existing = {(r.get("run_id"), r.get("metric")) for r in load(path)}
    fresh = [r for r in rows
             if (r.get("run_id"), r.get("metric")) not in existing]
    if not fresh:
        return 0
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    with open(path, "a", encoding="utf-8") as fh:
        for r in fresh:
            fh.write(canonical_row(r) + "\n")
    return len(fresh)


def _comparable(row: Dict[str, Any], cand: Dict[str, Any]) -> bool:
    if row.get("metric") != cand.get("metric"):
        return False
    hw_a, hw_b = row.get("hw"), cand.get("hw")
    # rows without hardware meta (pre-meta backfills) compare to anything
    if hw_a is None or hw_b is None:
        return True
    return hw_a == hw_b


def detect(rows: List[Dict[str, Any]],
           cfg: Optional[DriftConfig] = None) -> List[Dict[str, Any]]:
    """One verdict per metric, judging its newest row against history."""
    cfg = cfg or DriftConfig()
    by_metric: Dict[str, List[Dict[str, Any]]] = {}
    for row in sorted(rows, key=lambda r: int(r.get("t_logical", 0))):
        by_metric.setdefault(str(row.get("metric")), []).append(row)
    verdicts: List[Dict[str, Any]] = []
    for metric in sorted(by_metric):
        series = by_metric[metric]
        cand = series[-1]
        history = [r for r in series[:-1] if _comparable(r, cand)]
        window = history[-cfg.window:]
        base: Dict[str, Any] = {
            "metric": metric, "value": float(cand.get("value", 0.0)),
            "run_id": cand.get("run_id", ""),
            "direction": cand.get("direction", "higher"),
            "window_n": len(window)}
        if len(window) < cfg.min_runs:
            verdicts.append(dict(base, verdict="insufficient-history",
                                 median=None, threshold=None))
            continue
        values = [float(r.get("value", 0.0)) for r in window]
        med = _median(values)
        mad = _median([abs(v - med) for v in values])
        threshold = max(cfg.mad_factor * mad, cfg.rel_tol * abs(med))
        if med:
            threshold = max(min(threshold, cfg.cap_fraction * abs(med)),
                            cfg.rel_tol * abs(med))
        value = float(cand.get("value", 0.0))
        higher = cand.get("direction", "higher") != "lower"
        delta = value - med if higher else med - value
        if delta < -threshold:
            verdict = "regression"
        elif delta > threshold:
            verdict = "improvement"
        else:
            verdict = "ok"
        verdicts.append(dict(base, verdict=verdict,
                             median=round(med, 6),
                             mad=round(mad, 6),
                             threshold=round(threshold, 6)))
    return verdicts


def gate(verdicts: List[Dict[str, Any]]) -> Tuple[int, List[str]]:
    """(exit code, regressed metric names): nonzero iff any regression."""
    bad = [v["metric"] for v in verdicts if v.get("verdict") == "regression"]
    return (1 if bad else 0), bad
