"""Observability: step-level tracing, per-fit profiles, Chrome-trace export.

See docs/observability.md for the span taxonomy, the metric catalogue and
how to capture a trace from a fit. Import-light on purpose: nothing here
touches jax, so the analysis tooling and pure-host paths can import it
freely.
"""

from cycloneml_tpu.observe import (attribution, costs, flight, regress, skew,
                                   tracing)
from cycloneml_tpu.observe.attribution import Scope, UsageLedger, UsageReporter
from cycloneml_tpu.observe.costs import ProgramCost
from cycloneml_tpu.observe.diagnose import (DiagnosisReport, Finding,
                                            diagnose)
from cycloneml_tpu.observe.export import (chrome_trace, export_chrome_trace,
                                          merged_chrome_trace, process_lanes,
                                          span_kinds, spans_from_chrome_trace,
                                          validate_chrome_trace)
from cycloneml_tpu.observe.profile import FitProfile
from cycloneml_tpu.observe.tracing import (Span, Tracer, active,
                                           current_span_id, disable, enable,
                                           full_active, instant, span)

__all__ = [
    "attribution", "Scope", "UsageLedger", "UsageReporter",
    "tracing", "costs", "flight", "skew", "regress", "Span", "Tracer",
    "FitProfile", "ProgramCost", "enable", "disable", "active",
    "full_active", "span", "instant", "current_span_id", "chrome_trace",
    "export_chrome_trace", "merged_chrome_trace", "process_lanes",
    "validate_chrome_trace", "span_kinds", "spans_from_chrome_trace",
    "diagnose", "DiagnosisReport", "Finding",
]
