"""Usage attribution: per-job / per-tenant metering over the telemetry waists.

The rest of the observability plane answers "what is the mesh doing";
this module answers "who is it doing it for". A :func:`scope` pushes a
``(job, tenant)`` attribution context onto a thread-local stack; every
existing narrow waist — ``_instrument_dispatch``, the chunked
L-BFGS/line-search loops, oocore staging, serving lanes, the supervisor
and autoscaler — charges the active scope without new instrumentation
sites. Rollups accumulate in one process-global :class:`UsageLedger`
(bounded, every ``_rows`` access under ``_lock`` — the JX011
discipline), ride shipped span batches cross-host so the master's
``TraceCollector`` can merge per-host ledgers, and surface as periodic
``UsageReport`` events (status store / ``/api/v1/usage`` / web UI /
history replay), labeled Prometheus gauges, and ``FitProfile.job_usage``.

Cost discipline matches the flight recorder: attribution off means every
site pays ONE module-global read (:data:`_ledger` is ``None`` →
:data:`NOOP_WINDOW`); an active ledger with no scope on the calling
thread pays that read plus one thread-local peek. The ``usage`` BENCH
block pins both numbers. Cross-thread work (oocore staging threads,
serving batcher workers, the autoscaler daemon) CAPTURES the
constructing/submitting thread's scope and charges it explicitly — the
same retroactive idiom ``Tracer.record_span`` uses for serving lanes.

FLOPs / bytes-accessed / HBM-peak are not measured twice: the window
joins the ``program`` identity its site already computes onto the PR-5
``observe.costs`` registry (one harvest per program, shared with
tracing).
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: ledger row key charged when work carries no scope (explicit charges
#: from un-scoped control-plane actions; the dispatch hot path skips
#: charging entirely instead — see :func:`dispatch_window`)
UNSCOPED = "(unscoped)"
#: row key absorbing evicted scopes, so per-scope sums keep matching the
#: global totals even after the bounded ledger rotates
EVICTED = "(evicted)"
#: snapshot key of the process-global totals row
TOTALS = "_totals"

#: fields that merge by max, not sum (a peak is a high-water mark)
_MAX_FIELDS = frozenset(("hbmPeakBytes",))

#: per-scope gauge surface: ledger fields exported as labeled Prometheus
#: gauges when a registry is attached (bounded by the ledger bound)
_GAUGE_FIELDS = ("deviceSeconds", "flops", "bytesAccessed", "hbmPeakBytes",
                 "h2dBytes", "requests", "sheds", "cacheHits")


def _zero_row(key: str, tenant: str) -> Dict[str, Any]:
    # h2dBytes is charged from the staged host arrays' OWN nbytes (see
    # oocore/stream.ShardStream._stage), so narrow tiers bill at their
    # true itemsize — an fp8 shard charges 1 byte/element, never the
    # bf16 width it replaced. cacheHits counts shard-set cache attaches
    # (oocore/cache.py): a hit re-streams zero spill-write bytes.
    return {"scope": key, "tenant": tenant,
            "deviceSeconds": 0.0, "dispatches": 0,
            "flops": 0.0, "bytesAccessed": 0.0, "hbmPeakBytes": 0,
            "h2dBytes": 0, "requests": 0, "rows": 0,
            "servingSeconds": 0.0, "sheds": 0, "cacheHits": 0,
            "reshapes": 0, "recoveries": 0, "autoscaleActions": 0,
            "models": {}}


class Scope:
    """Immutable attribution identity: a job id plus an optional tenant.

    The ledger key is ``tenant/job`` (or bare ``job``), so two tenants'
    identically-named jobs stay separate rows.
    """

    __slots__ = ("job", "tenant", "key")

    def __init__(self, job: Any, tenant: str = ""):
        self.job = str(job)
        self.tenant = str(tenant or "")
        self.key = f"{self.tenant}/{self.job}" if self.tenant else self.job

    def __repr__(self) -> str:
        return f"Scope({self.key!r})"


class _ScopeStack(threading.local):
    def __init__(self):
        self.stack: List[Scope] = []


_scopes = _ScopeStack()


def current_scope() -> Optional[Scope]:
    """Innermost scope on the calling thread, or None."""
    stack = _scopes.stack
    return stack[-1] if stack else None


@contextlib.contextmanager
def scope(job: Any, tenant: str = ""):
    """Attribute everything dispatched inside the block to ``job``
    (optionally under ``tenant``). Nests; the innermost scope wins.
    Cheap enough to use unconditionally — pushing while attribution is
    disabled costs a list append."""
    sc = Scope(job, tenant)
    _scopes.stack.append(sc)
    try:
        yield sc
    finally:
        _scopes.stack.pop()


@contextlib.contextmanager
def adopt(sc: Optional[Scope]):
    """Re-enter a captured scope on another thread (the cross-thread
    leg: capture ``current_scope()`` where work is SUBMITTED, adopt it
    where work RUNS). ``None`` adopts nothing and charges fall through
    to whatever the running thread has."""
    if sc is None:
        yield None
        return
    _scopes.stack.append(sc)
    try:
        yield sc
    finally:
        _scopes.stack.pop()


class UsageLedger:
    """Bounded per-scope usage rollups plus one global totals row.

    Lock discipline (JX011): every ``_rows`` / ``_totals`` access holds
    ``_lock``; snapshots are deep copies so readers never alias live
    rows. Bounded like the status store's event lists: past
    ``max_scopes`` the oldest scope row folds into :data:`EVICTED`
    (additively — per-scope sums still match the totals row) and its
    gauges unregister. Per-scope ``models`` sub-tables are bounded by
    ``max_models`` with an ``(other)`` overflow bucket.
    """

    def __init__(self, max_scopes: int = 256, max_models: int = 64,
                 registry=None):
        self._lock = threading.Lock()
        self._rows: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._totals = _zero_row(TOTALS, "")
        self.max_scopes = max(2, int(max_scopes))
        self.max_models = max(1, int(max_models))
        self._registry = registry
        self.scopes_evicted = 0

    # -- charging ---------------------------------------------------------

    def charge(self, scope: Optional[Scope], **fields) -> None:
        """Add ``fields`` to the scope's row AND the totals row (so the
        global ledger is always the sum of what was handed out).
        ``hbmPeakBytes`` merges by max. ``scope=None`` charges the
        :data:`UNSCOPED` row."""
        key = scope.key if scope is not None else UNSCOPED
        tenant = scope.tenant if scope is not None else ""
        with self._lock:
            row, created, evicted = self._row_locked(key, tenant)
            self._add(row, fields)
            self._add(self._totals, fields)
        self._sync_gauges(key, tenant, created, evicted)

    def charge_model(self, scope: Optional[Scope], model: str,
                     **fields) -> None:
        """Serving-lane charge: ``fields`` land on the scope row (and
        totals) AND on the scope's per-model sub-row."""
        key = scope.key if scope is not None else UNSCOPED
        tenant = scope.tenant if scope is not None else ""
        with self._lock:
            row, created, evicted = self._row_locked(key, tenant)
            self._add(row, fields)
            self._add(self._totals, fields)
            models = row["models"]
            m = models.get(model)
            if m is None:
                if len(models) >= self.max_models:
                    model = "(other)"
                m = models.setdefault(model, {})
            self._add(m, fields)
        self._sync_gauges(key, tenant, created, evicted)

    @staticmethod
    def _add(row: Dict[str, Any], fields: Dict[str, Any]) -> None:
        for k, v in fields.items():
            if k in _MAX_FIELDS:
                if v > row.get(k, 0):
                    row[k] = v
            else:
                row[k] = row.get(k, 0) + v

    def _row_locked(self, key: str, tenant: str):
        """Caller holds ``_lock``. Returns (row, created?,
        (evicted_key, evicted_tenant) | None) — the victim's tenant
        travels out so gauge unregistration rebuilds the SAME labeled
        name registration used."""
        row = self._rows.get(key)
        if row is not None:
            return row, False, None
        row = _zero_row(key, tenant)
        self._rows[key] = row
        evicted = None
        if len(self._rows) > self.max_scopes:
            for victim in self._rows:
                if victim not in (key, EVICTED):
                    break
            else:   # pragma: no cover — bound >= 2 makes this unreachable
                return row, True, None
            old = self._rows.pop(victim)
            sink, _, _ = self._row_locked(EVICTED, "")
            self._fold_locked(sink, old)
            self.scopes_evicted += 1
            evicted = (victim, str(old.get("tenant", "")))
        return row, True, evicted

    @classmethod
    def _fold_locked(cls, dst: Dict[str, Any], src: Dict[str, Any]) -> None:
        cls._add(dst, {k: v for k, v in src.items()
                       if isinstance(v, (int, float)) and not
                       isinstance(v, bool)})
        for model, sub in src.get("models", {}).items():
            cls._add(dst.setdefault("models", {}).setdefault(model, {}), sub)

    # -- reading ----------------------------------------------------------

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Deep copy of every scope row plus the totals row under
        :data:`TOTALS` — the shape ``UsageReport`` events, shipped span
        batches and the REST route all carry."""
        with self._lock:
            out = {k: self._copy_row(r) for k, r in self._rows.items()}
            out[TOTALS] = self._copy_row(self._totals)
        return out

    def totals(self) -> Dict[str, Any]:
        with self._lock:
            return self._copy_row(self._totals)

    def row(self, key: str) -> Dict[str, Any]:
        """Copy of one scope's row, or a zero row for an unknown key
        (so bracket-delta consumers never special-case 'not charged
        yet')."""
        with self._lock:
            r = self._rows.get(key)
            return self._copy_row(r) if r is not None else _zero_row(key, "")

    def peek(self, key: str, fld: str) -> float:
        """One field of one row — the gauge-callback read."""
        with self._lock:
            r = self._rows.get(key)
            return float(r.get(fld, 0)) if r is not None else 0.0

    @staticmethod
    def _copy_row(row: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(row)
        out["models"] = {m: dict(sub) for m, sub in row["models"].items()}
        return out

    # -- labeled Prometheus gauges ---------------------------------------

    def _sync_gauges(self, key: str, tenant: str, created: bool,
                     evicted: Optional[str]) -> None:
        """Register/unregister per-scope gauges OUTSIDE the ledger lock
        (the registry has its own; nesting the two would order-invert
        against a scrape that polls back into ``peek``)."""
        reg = self._registry
        if reg is None or not (created or evicted):
            return
        if created:
            for fld in _GAUGE_FIELDS:
                reg.gauge(self._gauge_name(fld, key, tenant),
                          lambda k=key, f=fld: self.peek(k, f))
        if evicted:
            ekey, etenant = evicted
            for fld in _GAUGE_FIELDS:
                reg.remove(self._gauge_name(fld, ekey, etenant))

    @staticmethod
    def _gauge_name(fld: str, key: str, tenant: str) -> str:
        esc = key.replace("\\", "\\\\").replace('"', '\\"')
        labels = f'scope="{esc}"'
        if tenant:
            t = tenant.replace("\\", "\\\\").replace('"', '\\"')
            labels += f',tenant="{t}"'
        return f"usage.{fld}{{{labels}}}"


def merge_snapshots(snapshots: Iterable[Dict[str, Dict[str, Any]]]
                    ) -> Dict[str, Dict[str, Any]]:
    """Merge per-host ledger snapshots (the collector's cross-host
    rollup): additive fields sum per scope key, peaks take the max,
    per-model sub-tables merge the same way."""
    out: Dict[str, Dict[str, Any]] = {}
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        for key, row in snap.items():
            if not isinstance(row, dict):
                continue
            dst = out.setdefault(key, _zero_row(
                key, str(row.get("tenant", ""))))
            UsageLedger._fold_locked(dst, row)
    return out


def usage_delta(before: Dict[str, Any], after: Dict[str, Any]
                ) -> Dict[str, Any]:
    """Additive-field delta of one scope row across a bracket (the
    ``FitProfile.job_usage`` shape). Peaks keep the bracket-end value —
    a high-water mark has no meaningful difference."""
    out: Dict[str, Any] = {}
    for k, v in after.items():
        if k in _MAX_FIELDS:
            if v:
                out[k] = v
        elif isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        else:
            d = v - before.get(k, 0)
            if d:
                out[k] = d
    return out


# -- the module-global switch (one read on every hot path) ----------------

_ledger: Optional[UsageLedger] = None


def enable(conf=None, registry=None) -> UsageLedger:
    """Install the process-global ledger (idempotent — an existing one
    is kept, the way ``tracing.enable`` behaves). Bounds come from
    ``cyclone.usage.*`` conf."""
    global _ledger
    if _ledger is not None:
        return _ledger
    from cycloneml_tpu.conf import USAGE_MAX_MODELS, USAGE_MAX_SCOPES
    max_scopes = int(conf.get(USAGE_MAX_SCOPES)) if conf is not None else 256
    max_models = int(conf.get(USAGE_MAX_MODELS)) if conf is not None else 64
    _ledger = UsageLedger(max_scopes=max_scopes, max_models=max_models,
                          registry=registry)
    return _ledger


def disable() -> None:
    global _ledger
    _ledger = None


def active() -> Optional[UsageLedger]:
    return _ledger


def charge(sc: Optional[Scope], **fields) -> None:
    """Charge ``fields`` to ``sc`` (or the calling thread's scope, or
    :data:`UNSCOPED`). One global read when attribution is off."""
    led = _ledger
    if led is None:
        return
    led.charge(sc if sc is not None else current_scope(), **fields)


def charge_model(sc: Optional[Scope], model: str, **fields) -> None:
    led = _ledger
    if led is None:
        return
    led.charge_model(sc if sc is not None else current_scope(), model,
                     **fields)


class _NoopWindow:
    """Shared do-nothing window: no clock read, no allocation. The
    ``live`` flag lets a site extend its cost-harvest condition
    (``tracing.full_active() or win.live``) without consulting this
    module twice."""

    __slots__ = ()
    live = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate_program(self, pid) -> None:
        pass


NOOP_WINDOW = _NoopWindow()


class _Window:
    """Live dispatch window: times the block, charges device-seconds +
    one dispatch, and joins an annotated program id onto the costs
    registry for FLOPs / bytes-accessed / HBM-peak."""

    __slots__ = ("_ledger", "_scope", "_pid", "_t0")
    live = True

    def __init__(self, ledger: UsageLedger, sc: Scope):
        self._ledger = ledger
        self._scope = sc
        self._pid = None
        self._t0 = 0.0

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def annotate_program(self, pid) -> None:
        self._pid = pid

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        fields: Dict[str, Any] = {"deviceSeconds": dt, "dispatches": 1}
        if self._pid:
            from cycloneml_tpu.observe import costs
            c = costs.lookup(self._pid)
            if c:
                if c.get("flops_total"):
                    fields["flops"] = float(c["flops_total"])
                if c.get("bytes_accessed_total"):
                    fields["bytesAccessed"] = float(c["bytes_accessed_total"])
                if c.get("peak_bytes"):
                    fields["hbmPeakBytes"] = int(c["peak_bytes"])
        self._ledger.charge(self._scope, **fields)
        return False


def dispatch_window(sc: Optional[Scope] = None):
    """The hot-path entry: a context manager around one device dispatch.

    Attribution off → the shared :data:`NOOP_WINDOW` after ONE global
    read; no scope on the thread (and none passed) → same, after one
    thread-local peek. Only a scoped dispatch under an active ledger
    pays the two clock reads."""
    led = _ledger
    if led is None:
        return NOOP_WINDOW
    if sc is None:
        sc = current_scope()
        if sc is None:
            return NOOP_WINDOW
    return _Window(led, sc)


# -- periodic reporting ---------------------------------------------------

class UsageReporter:
    """Posts cumulative ``UsageReport`` snapshots (and, when a
    ``telemetry_fn`` is wired, ``TelemetryStatsUpdated`` drop-counter
    rollups) to the listener bus on a period, plus a final flush on
    ``stop()``. Stop latch discipline: the posting path re-checks the
    latch under the same lock acquisition (the JX022 idiom), so a
    report can never land on a stopped bus."""

    def __init__(self, bus, interval_s: float = 2.0, host: str = "",
                 telemetry_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self._bus = bus
        self.interval_s = max(0.05, float(interval_s))
        self.host = host
        self._telemetry_fn = telemetry_fn
        self._lock = threading.Lock()
        self._stopped = False
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "UsageReporter":
        with self._lock:
            if self._stopped:
                raise RuntimeError("usage reporter is stopped")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="cyclone-usage-report",
                    daemon=True)
                self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._wake.wait(self.interval_s):
            try:
                self.flush()
            except Exception:   # a broken report must not kill the loop
                logger.exception("usage: report failed")

    def flush(self) -> None:
        """Post one report now (no-op when attribution is off or the
        reporter is stopped)."""
        led = _ledger
        events = []
        if led is not None:
            from cycloneml_tpu.util.events import UsageReport
            events.append(UsageReport(usage=led.snapshot(), host=self.host))
        if self._telemetry_fn is not None:
            from cycloneml_tpu.util.events import TelemetryStatsUpdated
            try:
                stats = self._telemetry_fn()
            except Exception:
                logger.exception("usage: telemetry stats sample failed")
                stats = None
            if stats:
                events.append(TelemetryStatsUpdated(stats=stats))
        with self._lock:
            if self._stopped:
                return
            for ev in events:
                try:
                    self._bus.post(ev)
                except Exception:
                    pass    # a stopping bus must not fail the reporter

    def stop(self) -> None:
        """Final flush, then latch. Idempotent."""
        try:
            self.flush()
        except Exception:
            pass
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            thread, self._thread = self._thread, None
        self._wake.set()
        if thread is not None:
            thread.join(timeout=5)
