"""Chrome-trace (Trace Event Format) export — single- and multi-process.

Writes spans as the JSON Object Format chrome://tracing and Perfetto both
load: ``{"traceEvents": [...], "displayTimeUnit": "ms"}`` with complete
(``ph: "X"``) events for spans, instant (``ph: "i"``) events for
annotations, counter (``ph: "C"``) samples, and metadata (``ph: "M"``)
``process_name``/``thread_name`` events so Perfetto labels every lane by
host and thread instead of bare pids. Timestamps are wall-clock
microseconds (each tracer anchors its monotonic clock to ``time.time`` at
construction); the top-level ``otherData`` object carries the trace id,
host label and the ring's ``spans_dropped`` count.

``merged_chrome_trace`` folds several processes' shipped span batches
(``observe/collect.py``) into ONE trace: every host gets its own process
lane, its span/parent ids are qualified as ``host/sN`` so they stay unique
across processes, and its timestamps are corrected by the collector's
per-host clock-offset estimate (heartbeat RTT midpoints; docs/
observability.md has the math and its error bound).

``validate_chrome_trace`` is the schema check ``make obs-demo`` and the
tier-1 tests run over an exported file — it pins the invariants Perfetto
needs rather than trusting the writer.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence, Union

REQUIRED_TOP = "traceEvents"
DURATION_PH = "X"
INSTANT_PH = "i"
COUNTER_PH = "C"
METADATA_PH = "M"


def _qualify(sid: str, host: str) -> str:
    """Host-qualify a span id for a merged trace; ids that already carry a
    host label (a remote parent propagated through the deploy env) pass
    through untouched."""
    if not sid or "/" in sid:
        return sid
    return f"{host}/{sid}"


def _span_event(kind: str, name: str, ts_us: float, dur_us: float,
                pid: int, tid: int, args: Dict[str, Any]) -> Dict[str, Any]:
    if kind == "counter":
        # Perfetto renders "C" events as a per-name counter track —
        # the HBM / cumulative-FLOPs timeline next to the spans
        return {"name": name, "cat": "counter", "ph": COUNTER_PH,
                "ts": ts_us, "pid": pid, "tid": tid,
                "args": {"value": args.get("value", 0)}}
    if kind == "instant":
        return {"name": name, "cat": "instant", "ph": INSTANT_PH,
                "ts": ts_us, "pid": pid, "tid": tid, "s": "t", "args": args}
    return {"name": name, "cat": kind, "ph": DURATION_PH, "ts": ts_us,
            # zero-duration X events render invisibly; floor at 1ns
            "dur": max(dur_us, 0.001), "pid": pid, "tid": tid, "args": args}


def _metadata_events(pid: int, process_name: str,
                     tid_names: Dict[int, str]) -> List[Dict[str, Any]]:
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": METADATA_PH, "pid": pid, "tid": 0,
        "args": {"name": process_name},
    }]
    for tid, tname in sorted(tid_names.items()):
        events.append({"name": "thread_name", "ph": METADATA_PH,
                       "pid": pid, "tid": tid, "args": {"name": tname}})
    return events


def _events_for_spans(spans, base: float, pid: int,
                      host: Optional[str] = None) -> List[Dict[str, Any]]:
    """Render Span objects to events. ``base`` maps perf_counter readings
    onto wall time; ``host`` (merged traces) qualifies span/parent ids."""
    events: List[Dict[str, Any]] = []
    for s in spans:
        ts_us = (base + s.t0) * 1e6
        sid = s.span_id
        parent = s.parent_id
        if host is not None:
            sid = _qualify(sid, host)
            parent = _qualify(parent, host)
        args = {"span_id": sid}
        if parent:
            args["parent_id"] = parent
        args.update(s.attrs)
        events.append(_span_event(s.kind, s.name, ts_us,
                                  (s.t1 - s.t0) * 1e6, pid, s.tid, args))
    return events


def chrome_trace(tracer, spans=None,
                 other: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Render a tracer's spans (or an explicit ``spans`` window — the
    flight recorder's dump path) to a Trace Event Format object."""
    pid = os.getpid()
    label = f"cycloneml-tpu (pid {pid})"
    events = _metadata_events(pid, label, tracer.thread_names())
    events.extend(_events_for_spans(
        spans if spans is not None else tracer.snapshot(),
        tracer.epoch_wall - tracer.epoch_perf, pid))
    meta: Dict[str, Any] = {"trace_id": tracer.trace_id,
                            "spans_dropped": tracer.dropped}
    if other:
        meta.update(other)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": meta}


def merged_chrome_trace(records: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """ONE trace from several processes' span records.

    Each record: ``{"host": label, "spans": [wire span dicts with
    wall-clock t0/t1], "offset_s": clock offset vs the collector,
    "offset_err_s": its error bound, "trace_id": ..., "dropped": ...,
    "tid_names": {tid: name}, "pid": source OS pid}``. Hosts get synthetic
    lane pids (1..N, collector order) — OS pids can collide across hosts —
    with the real pid kept in the process_name label. Per-host timestamps
    are corrected onto the collector's clock (``t - offset_s``); the
    correction is a constant per host, so per-lane ordering is preserved.
    """
    events: List[Dict[str, Any]] = []
    meta: Dict[str, Any] = {"hosts": {}}
    trace_ids = set()
    for lane, rec in enumerate(records, start=1):
        host = str(rec.get("host") or f"proc{lane}")
        offset = float(rec.get("offset_s") or 0.0)
        src_pid = rec.get("pid")
        label = f"{host} (pid {src_pid})" if src_pid else host
        tid_names = {int(k): str(v)
                     for k, v in (rec.get("tid_names") or {}).items()}
        events.extend(_metadata_events(lane, label, tid_names))
        for w in rec.get("spans", []):
            args = {"span_id": _qualify(str(w.get("id", "")), host)}
            parent = _qualify(str(w.get("parent", "")), host)
            if parent:
                args["parent_id"] = parent
            args.update(w.get("attrs") or {})
            t0 = float(w.get("t0", 0.0)) - offset
            t1 = float(w.get("t1", t0)) - offset
            events.append(_span_event(
                str(w.get("kind", "span")), str(w.get("name", "")),
                t0 * 1e6, (t1 - t0) * 1e6, lane, int(w.get("tid", 0)),
                args))
        if rec.get("trace_id"):
            trace_ids.add(str(rec["trace_id"]))
        meta["hosts"][host] = {
            "lane_pid": lane, "pid": src_pid,
            "offset_s": offset,
            "offset_err_s": rec.get("offset_err_s"),
            "trace_id": rec.get("trace_id"),
            "spans_dropped": int(rec.get("dropped") or 0),
        }
    meta["spans_dropped"] = sum(h["spans_dropped"]
                                for h in meta["hosts"].values())
    if len(trace_ids) == 1:
        meta["trace_id"] = next(iter(trace_ids))
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": meta}


def write_chrome_trace(obj: Dict[str, Any], path: str) -> str:
    """Atomic trace write (readers never see a half-written file)."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, default=str)
    os.replace(tmp, path)
    return path


def export_chrome_trace(tracer, path: str) -> str:
    """Write the trace JSON to ``path`` (returns the path)."""
    return write_chrome_trace(chrome_trace(tracer), path)


def validate_chrome_trace(obj_or_path: Union[str, Dict[str, Any]]
                          ) -> List[str]:
    """Return schema violations (empty list = loads in Perfetto).

    Checks: top-level ``traceEvents`` list; every event has ``name``/
    ``ph``/``pid``; duration events carry numeric ``ts`` and ``dur >= 0``;
    instant events carry numeric ``ts``; counter (``"C"``) events carry a
    numeric ``ts`` and an args object of numeric series values; metadata
    (``"M"``) events are ``process_name``/``thread_name``-style with a
    string ``args.name``; ``args`` (when present) is an object.
    """
    if isinstance(obj_or_path, str):
        with open(obj_or_path, encoding="utf-8") as fh:
            try:
                obj = json.load(fh)
            except json.JSONDecodeError as e:
                return [f"not valid JSON: {e}"]
    else:
        obj = obj_or_path
    errors: List[str] = []
    if not isinstance(obj, dict) or REQUIRED_TOP not in obj:
        return [f"top level must be an object with a {REQUIRED_TOP!r} list"]
    events = obj[REQUIRED_TOP]
    if not isinstance(events, list):
        return [f"{REQUIRED_TOP!r} must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        for req in ("name", "ph", "pid"):
            if req not in ev:
                errors.append(f"{where}: missing {req!r}")
        ph = ev.get("ph")
        if ph == METADATA_PH:
            # Perfetto lane labels: args.name is the displayed string
            margs = ev.get("args")
            if not isinstance(margs, dict) or \
                    not isinstance(margs.get("name"), str):
                errors.append(f"{where}: M event needs args.name string")
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"{where}: non-numeric 'ts'")
        if ph == DURATION_PH:
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: X event needs numeric 'dur' >= 0")
        elif ph == COUNTER_PH:
            cargs = ev.get("args")
            if not isinstance(cargs, dict) or not cargs or not all(
                    isinstance(v, (int, float)) for v in cargs.values()):
                errors.append(
                    f"{where}: C event needs an args object of numeric "
                    f"series values")
        elif ph != INSTANT_PH:
            errors.append(f"{where}: unexpected ph {ph!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            errors.append(f"{where}: 'args' must be an object")
    return errors


def span_kinds(obj_or_path: Union[str, Dict[str, Any]]) -> Dict[str, int]:
    """Count events per category — the obs-demo's >= 4 distinct-kinds
    acceptance check reads this. Metadata (``M``) lane labels are not
    spans and are excluded."""
    if isinstance(obj_or_path, str):
        with open(obj_or_path, encoding="utf-8") as fh:
            obj = json.load(fh)
    else:
        obj = obj_or_path
    out: Dict[str, int] = {}
    for ev in obj.get(REQUIRED_TOP, []):
        if isinstance(ev, dict) and ev.get("ph") != METADATA_PH:
            cat = ev.get("cat", "")
            out[cat] = out.get(cat, 0) + 1
    return out


def spans_from_chrome_trace(obj_or_path: Union[str, Dict[str, Any]]):
    """Invert the export: rebuild ``Span`` objects from an exported
    Chrome trace so the doctor can diagnose a trace FILE as readily as a
    live ring. ``args.span_id``/``parent_id`` round-trip; everything
    else in ``args`` becomes ``attrs``; ``ts``/``dur`` (wall-anchored
    microseconds) become ``t0``/``t1`` seconds — absolute epoch differs
    from the original perf_counter readings but every rule the doctor
    runs is duration/interval arithmetic, which the shift preserves.
    Metadata lane labels are not spans and are dropped."""
    from cycloneml_tpu.observe.tracing import Span
    if isinstance(obj_or_path, str):
        with open(obj_or_path, encoding="utf-8") as fh:
            obj = json.load(fh)
    else:
        obj = obj_or_path
    spans = []
    for ev in obj.get(REQUIRED_TOP, []):
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        if ph == METADATA_PH:
            continue
        args = dict(ev.get("args") or {})
        sid = str(args.pop("span_id", ""))
        parent = str(args.pop("parent_id", ""))
        kind = ("counter" if ph == COUNTER_PH
                else "instant" if ph == INSTANT_PH
                else str(ev.get("cat", "")))
        s = Span(sid, parent, kind, str(ev.get("name", "")),
                 int(ev.get("tid", 0)), args)
        s.t0 = float(ev.get("ts", 0.0)) / 1e6
        s.t1 = s.t0 + (float(ev.get("dur", 0.0)) / 1e6
                       if ph == DURATION_PH else 0.0)
        spans.append(s)
    return spans


def process_lanes(obj_or_path: Union[str, Dict[str, Any]]) -> Dict[int, str]:
    """pid -> process_name label from the trace's metadata events (the
    merged-trace acceptance counts these)."""
    if isinstance(obj_or_path, str):
        with open(obj_or_path, encoding="utf-8") as fh:
            obj = json.load(fh)
    else:
        obj = obj_or_path
    out: Dict[int, str] = {}
    for ev in obj.get(REQUIRED_TOP, []):
        if (isinstance(ev, dict) and ev.get("ph") == METADATA_PH
                and ev.get("name") == "process_name"):
            out[int(ev.get("pid", 0))] = str(
                (ev.get("args") or {}).get("name", ""))
    return out
