"""Chrome-trace (Trace Event Format) export.

Writes the tracer's spans as the JSON Object Format chrome://tracing and
Perfetto both load: ``{"traceEvents": [...], "displayTimeUnit": "ms"}``
with complete (``ph: "X"``) events for spans and instant (``ph: "i"``)
events for annotations. Timestamps are wall-clock microseconds (the
tracer anchors its monotonic clock to ``time.time`` at construction), so
traces from cooperating processes line up on one timeline.

``validate_chrome_trace`` is the schema check ``make obs-demo`` and the
tier-1 tests run over an exported file — it pins the invariants Perfetto
needs rather than trusting the writer.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Union

REQUIRED_TOP = "traceEvents"
DURATION_PH = "X"
INSTANT_PH = "i"
COUNTER_PH = "C"
METADATA_PH = "M"


def chrome_trace(tracer) -> Dict[str, Any]:
    """Render a tracer's spans to a Trace Event Format object."""
    pid = os.getpid()
    events: List[Dict[str, Any]] = [{
        "name": "process_name", "ph": METADATA_PH, "pid": pid, "tid": 0,
        "args": {"name": "cycloneml-tpu"},
    }]
    base = tracer.epoch_wall - tracer.epoch_perf
    for s in tracer.snapshot():
        ts_us = (base + s.t0) * 1e6
        args = {"span_id": s.span_id}
        if s.parent_id:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        if s.kind == "counter":
            # Perfetto renders "C" events as a per-name counter track —
            # the HBM / cumulative-FLOPs timeline next to the spans
            events.append({
                "name": s.name, "cat": "counter", "ph": COUNTER_PH,
                "ts": ts_us, "pid": pid, "tid": s.tid,
                "args": {"value": s.attrs.get("value", 0)},
            })
        elif s.kind == "instant":
            events.append({
                "name": s.name, "cat": "instant", "ph": INSTANT_PH,
                "ts": ts_us, "pid": pid, "tid": s.tid, "s": "t",
                "args": args,
            })
        else:
            events.append({
                "name": s.name, "cat": s.kind, "ph": DURATION_PH,
                "ts": ts_us,
                # zero-duration X events render invisibly; floor at 1ns
                "dur": max((s.t1 - s.t0) * 1e6, 0.001),
                "pid": pid, "tid": s.tid, "args": args,
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(tracer, path: str) -> str:
    """Write the trace JSON to ``path`` (returns the path)."""
    obj = chrome_trace(tracer)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(obj, fh, default=str)
    os.replace(tmp, path)  # readers never see a half-written trace
    return path


def validate_chrome_trace(obj_or_path: Union[str, Dict[str, Any]]
                          ) -> List[str]:
    """Return schema violations (empty list = loads in Perfetto).

    Checks: top-level ``traceEvents`` list; every event has ``name``/
    ``ph``/``pid``; duration events carry numeric ``ts`` and ``dur >= 0``;
    instant events carry numeric ``ts``; counter (``"C"``) events carry a
    numeric ``ts`` and an args object of numeric series values; ``args``
    (when present) is an object.
    """
    if isinstance(obj_or_path, str):
        with open(obj_or_path, encoding="utf-8") as fh:
            try:
                obj = json.load(fh)
            except json.JSONDecodeError as e:
                return [f"not valid JSON: {e}"]
    else:
        obj = obj_or_path
    errors: List[str] = []
    if not isinstance(obj, dict) or REQUIRED_TOP not in obj:
        return [f"top level must be an object with a {REQUIRED_TOP!r} list"]
    events = obj[REQUIRED_TOP]
    if not isinstance(events, list):
        return [f"{REQUIRED_TOP!r} must be a list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        for req in ("name", "ph", "pid"):
            if req not in ev:
                errors.append(f"{where}: missing {req!r}")
        ph = ev.get("ph")
        if ph == METADATA_PH:
            continue
        if not isinstance(ev.get("ts"), (int, float)):
            errors.append(f"{where}: non-numeric 'ts'")
        if ph == DURATION_PH:
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                errors.append(f"{where}: X event needs numeric 'dur' >= 0")
        elif ph == COUNTER_PH:
            cargs = ev.get("args")
            if not isinstance(cargs, dict) or not cargs or not all(
                    isinstance(v, (int, float)) for v in cargs.values()):
                errors.append(
                    f"{where}: C event needs an args object of numeric "
                    f"series values")
        elif ph != INSTANT_PH:
            errors.append(f"{where}: unexpected ph {ph!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            errors.append(f"{where}: 'args' must be an object")
    return errors


def span_kinds(obj_or_path: Union[str, Dict[str, Any]]) -> Dict[str, int]:
    """Count events per category — the obs-demo's >= 4 distinct-kinds
    acceptance check reads this."""
    if isinstance(obj_or_path, str):
        with open(obj_or_path, encoding="utf-8") as fh:
            obj = json.load(fh)
    else:
        obj = obj_or_path
    out: Dict[str, int] = {}
    for ev in obj.get(REQUIRED_TOP, []):
        if isinstance(ev, dict) and ev.get("ph") != METADATA_PH:
            cat = ev.get("cat", "")
            out[cat] = out.get(cat, 0) + 1
    return out
