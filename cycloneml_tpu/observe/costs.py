"""XLA cost & HBM accounting: what the compiler thinks a program costs.

PR 3's spans attribute *time*; this module attributes *work*. The
expensive facts about a staged computation — FLOPs, bytes touched, peak
HBM across arguments/outputs/temporaries — are decided once at compile
time and then normally discarded (the staged-computation blind spot of
Frostig et al., SysML 2018). XLA exposes them on the AOT stages:
``jitted.lower(*args).cost_analysis()`` (flops / bytes accessed, works on
every backend) and ``lowered.compile().memory_analysis()``
(argument/output/temp/generated-code bytes — the OOM-relevant per-device
footprint). This module harvests both at the one narrow waist where every
SPMD program is born — the bounded program cache + the ``compile`` span of
``_instrument_dispatch`` and the chunked-optimizer dispatch loops — into a
process-global per-program registry keyed by program-cache identity, and
:class:`~cycloneml_tpu.observe.profile.FitProfile` rolls the entries up
per fit against the roofline model (Williams et al. 2009, PAPERS.md).

Cost discipline mirrors tracing's: with tracing disabled and no explicit
memory budget configured, NO ``cost_analysis`` call ever happens — the
harvest path at every site is one module-global read (pinned by a no-op
test). When harvesting IS on, each program pays one extra AOT
lower+compile: JAX's dispatch cache and its AOT cache are separate, so the
``memory_analysis`` compile is a second XLA compile of the same program
(absorbed by the persistent compilation cache on TPU deployments; ~ms on
CPU). Availability degrades gracefully per backend: CPU reports
cost_analysis + memory_analysis but ``device.memory_stats()`` is ``None``;
fields that a backend cannot report stay ``None`` ("unavailable") rather
than guessed.

The same numbers feed the compile-time memory budget guard: when a
program's predicted peak HBM exceeds ``cyclone.memory.budgetFraction`` ×
device memory, a ``MemoryBudgetExceeded`` event is posted (warn-only by
default; ``cyclone.memory.budgetAction=raise`` escalates) and the chunked
L-BFGS paths shrink ``deviceChunk`` proportionally instead of OOMing.
"""

from __future__ import annotations

import collections
import os
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from cycloneml_tpu.observe import tracing
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

__all__ = [
    "ProgramCost", "MemoryBudgetError", "BudgetVerdict", "OutOfCoreRequired",
    "program_id", "analyze", "ensure", "lookup", "snapshot", "clear",
    "analyze_call_count", "note_execution", "check_budget", "guard_armed",
    "select_chunk", "backend_peaks", "device_memory_limit",
    "memory_stats_available", "register_memory_gauges", "sweep_cost",
    "streamed_sweep_cost", "sample_device_peak",
]


class MemoryBudgetError(RuntimeError):
    """Raised when ``cyclone.memory.budgetAction=raise`` and a program's
    predicted peak HBM exceeds the configured budget."""


class OutOfCoreRequired(RuntimeError):
    """Internal degradation signal: the budget guard walked deviceChunk
    down to 1 and the program STILL exceeds the budget, but the caller
    declared a streaming fallback (``cyclone.oocore.mode=auto``) — the fit
    should re-route through the out-of-core epoch engine instead of
    warn-proceeding or raising. Carries the terminal :class:`BudgetVerdict`
    so the streaming path can log what it degraded from. Estimators catch
    this; it must never escape to user code."""

    def __init__(self, name: str, verdict: "BudgetVerdict"):
        super().__init__(
            f"{name}: {verdict.predicted_bytes} bytes/device predicted over "
            f"the {verdict.budget_bytes}-byte budget at deviceChunk 1 — "
            f"degrading to the out-of-core streaming engine")
        self.name = name
        self.verdict = verdict


@dataclass
class BudgetVerdict:
    """Result of one budget check (``None`` fields = limit unknown)."""

    exceeded: bool
    predicted_bytes: Optional[int]
    budget_bytes: Optional[int]
    limit_bytes: Optional[int]
    fraction: float
    action: str


@dataclass
class ProgramCost:
    """What XLA reports for ONE compiled program.

    ``flops`` / ``bytes_accessed`` are per-partition (XLA analyzes the
    per-device SPMD module); ``flops_total`` / ``bytes_accessed_total``
    scale by the device count — the mesh-wide work one execution performs.
    Memory fields are per-device bytes (the OOM-relevant number);
    ``peak_bytes`` = arguments + outputs + temporaries + generated code −
    aliased. ``None`` anywhere means the backend did not report it.
    """

    program_id: str = ""
    name: str = ""
    n_devices: int = 1
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    flops_total: Optional[float] = None
    bytes_accessed_total: Optional[float] = None
    argument_bytes: Optional[int] = None
    output_bytes: Optional[int] = None
    temp_bytes: Optional[int] = None
    generated_code_bytes: Optional[int] = None
    peak_bytes: Optional[int] = None
    cost_available: bool = False
    memory_available: bool = False

    def to_dict(self) -> Dict[str, Any]:
        import dataclasses
        return dataclasses.asdict(self)


# -- per-program registry (process-global, like the program caches) ------------

_lock = threading.Lock()
# LRU-bounded: program ids embed object identities (compiled programs,
# meshes), so program-cache eviction / mesh rebuilds mint fresh ids — an
# unbounded registry would leak exactly the way BoundedProgramCache
# exists to prevent. Eviction only loses a cost entry for a program that
# would re-harvest on its next traced dispatch.
MAX_REGISTRY_ENTRIES = 512
_registry: "collections.OrderedDict[str, Dict[str, Any]]" = \
    collections.OrderedDict()
_n_analyze_calls = 0
_cumulative_flops = 0.0
# tri-state: None = not probed yet; False = backend has no memory_stats
_mem_stats_ok: Optional[bool] = None


def analyze_call_count() -> int:
    """How many times :func:`analyze` ran — the no-op tests pin that this
    stays flat across untraced fits (the disabled path never lowers)."""
    return _n_analyze_calls


def lookup(pid: str) -> Optional[Dict[str, Any]]:
    with _lock:
        e = _registry.get(pid)
        if e is None:
            return None
        _registry.move_to_end(pid)
        return dict(e)


def snapshot() -> Dict[str, Dict[str, Any]]:
    with _lock:
        return {k: dict(v) for k, v in _registry.items()}


def clear() -> None:
    global _cumulative_flops
    with _lock:
        _registry.clear()
        _cumulative_flops = 0.0


def _describe_part(p: Any) -> str:
    if callable(p):
        return getattr(p, "__qualname__",
                       getattr(p, "__name__", type(p).__name__))
    axis_names = getattr(p, "axis_names", None)
    if axis_names is not None and hasattr(p, "devices"):
        return "mesh[" + ",".join(
            f"{a}={s}" for a, s in zip(axis_names, p.devices.shape)) + "]"
    return repr(p)


def program_id(name: str, key: Any, jitted: Any = None) -> str:
    """Stable-within-process identity string for a program-cache key.

    Readable prefix (the cache key's parts) + a checksum of the full key
    repr, so distinct keys cannot collide on a truncated prefix. Unhashable
    / keyless programs fall back to the jitted object's identity.
    """
    if key is None:
        return f"{name}#anon{(id(jitted) & 0xFFFFFFFF):08x}"
    parts = key if isinstance(key, tuple) else (key,)
    desc = "/".join(_describe_part(p) for p in parts)
    crc = zlib.crc32(repr(parts).encode("utf-8", "replace")) & 0xFFFFFFFF
    return f"{name}/{desc[:80]}#{crc:08x}"


def analyze(jitted: Any, args: tuple, name: str = "",
            pid: str = "") -> ProgramCost:
    """Run XLA's cost + memory analysis over ``jitted`` at ``args``.

    Never raises: every backend gap degrades to ``None`` fields. Pays one
    retrace (``lower``) and — for the memory side — one AOT compile (see
    module docstring for why that compile cannot reuse the dispatch
    cache's executable).
    """
    global _n_analyze_calls
    with _lock:
        _n_analyze_calls += 1
    cost = ProgramCost(program_id=pid, name=name)
    try:
        import jax
        cost.n_devices = jax.device_count()
    except Exception:
        return cost
    try:
        lowered = jitted.lower(*args)
    except Exception:
        logger.debug("cost harvest: lower() failed for %s", name,
                     exc_info=True)
        return cost
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = ca.get("flops")
        nbytes = ca.get("bytes accessed")
        if flops is not None and flops >= 0:
            cost.flops = float(flops)
            cost.flops_total = float(flops) * cost.n_devices
        if nbytes is not None and nbytes >= 0:
            cost.bytes_accessed = float(nbytes)
            cost.bytes_accessed_total = float(nbytes) * cost.n_devices
        cost.cost_available = cost.flops is not None
    except Exception:
        logger.debug("cost harvest: cost_analysis unavailable for %s", name,
                     exc_info=True)
    try:
        ma = lowered.compile().memory_analysis()
        if ma is not None:
            cost.argument_bytes = int(ma.argument_size_in_bytes)
            cost.output_bytes = int(ma.output_size_in_bytes)
            cost.temp_bytes = int(ma.temp_size_in_bytes)
            cost.generated_code_bytes = int(ma.generated_code_size_in_bytes)
            cost.peak_bytes = (cost.argument_bytes + cost.output_bytes
                               + cost.temp_bytes + cost.generated_code_bytes
                               - int(getattr(ma, "alias_size_in_bytes", 0)))
            cost.memory_available = True
    except Exception:
        logger.debug("cost harvest: memory_analysis unavailable for %s",
                     name, exc_info=True)
    return cost


def ensure(name: str, key: Any, jitted: Any, args: tuple) -> str:
    """Harvest-once per program: return the program id, analyzing and
    registering the program on first sight. Callers invoke this ONLY when
    harvesting is on (tracing active or the budget guard armed) — the
    disabled path must never reach here."""
    pid = program_id(name, key, jitted)
    with _lock:
        if pid in _registry:
            _registry.move_to_end(pid)
            return pid
    cost = analyze(jitted, args, name=name, pid=pid)
    with _lock:
        _registry.setdefault(pid, cost.to_dict())
        _registry.move_to_end(pid)
        while len(_registry) > MAX_REGISTRY_ENTRIES:
            _registry.popitem(last=False)
    tr = tracing.active()
    if tr is not None and cost.peak_bytes is not None:
        # one Perfetto counter sample per freshly analyzed program: the
        # predicted-peak timeline next to the spans that ran it
        tr.counter("hbm.predicted_peak_bytes", cost.peak_bytes)
    return pid


def note_execution(tr, pid: str) -> None:
    """Per-dispatch accounting while tracing: bump the cumulative-FLOPs
    counter track and sample live device memory when the backend has it."""
    global _cumulative_flops
    entry = lookup(pid)
    if entry and entry.get("flops_total"):
        with _lock:
            _cumulative_flops += entry["flops_total"]
            cum = _cumulative_flops
        tr.counter("flops.cumulative", cum)
    sample = sample_memory()
    if sample is not None:
        tr.counter("hbm.bytes_in_use", sample)


def sweep_cost(call, *extras, name: str = "sweep") -> ProgramCost:
    """XLA's accounting for ONE optimizer sweep — the canonical
    ``bytes_per_sweep`` measurement (bench.py, ``make bench-bytes`` and the
    tier-1 byte-regression test all read this one implementation).

    ``call`` is a ``tree_aggregate_fn`` call object (``.compiled`` +
    ``.arrays()``); ``extras`` are the replicated arguments the aggregator
    takes after the sharded arrays (standardization vectors, coefficients).
    Lower-only: the program is ANALYZED at its operands' avals, never
    executed — cheap enough for CI, exact enough to be ground truth
    (``bytes_accessed`` is per partition; ``bytes_accessed_total`` is the
    mesh-wide sweep). Explicit calls count toward :func:`analyze_call_count`
    — the zero-cost-when-untraced discipline binds the instrumentation
    sites, not deliberate measurement."""
    compiled = getattr(call, "compiled", call)
    # the program cache hands back the _instrument_dispatch wrapper; the
    # raw jitted program (the thing with .lower) rides its __wrapped__
    compiled = getattr(compiled, "__wrapped__", compiled)
    arrays = call.arrays() if hasattr(call, "arrays") else ()
    return analyze(compiled, (*arrays, *extras), name=name)


def streamed_sweep_cost(prog, shard_args: tuple, n_shards: int,
                        name: str = "oocore.sweep") -> ProgramCost:
    """XLA's accounting for ONE STREAMED optimizer sweep — the out-of-core
    extension of :func:`sweep_cost` (``make bench-oocore`` reads this).

    ``prog`` is the per-shard aggregation program (the
    ``_instrument_dispatch`` wrapper or the raw jitted program) and
    ``shard_args`` one representative operand tuple at the padded shard
    geometry. Work fields (``flops`` / ``bytes_accessed`` and their
    ``*_total`` mesh-wide twins) are scaled by ``n_shards`` — the whole
    epoch's traffic; MEMORY fields stay per-dispatch, because that is the
    point of the streamed sweep: peak HBM is O(shard) no matter how many
    shards the epoch walks. Lower-only, never executes."""
    compiled = getattr(prog, "__wrapped__", prog)
    cost = analyze(compiled, shard_args, name=name)
    k = max(int(n_shards), 1)
    for f in ("flops", "bytes_accessed", "flops_total",
              "bytes_accessed_total"):
        v = getattr(cost, f)
        if v is not None:
            setattr(cost, f, v * k)
    return cost


# -- live device-memory telemetry ----------------------------------------------

def memory_stats_available() -> bool:
    """Whether ``device.memory_stats()`` reports on this backend (TPU/GPU
    yes; CPU returns ``None`` — the availability matrix in
    docs/observability.md)."""
    global _mem_stats_ok
    if _mem_stats_ok is None:
        try:
            import jax
            _mem_stats_ok = jax.devices()[0].memory_stats() is not None
        except Exception:
            _mem_stats_ok = False
    return _mem_stats_ok


def sample_memory() -> Optional[int]:
    """Total ``bytes_in_use`` across devices, or ``None`` when the backend
    does not report (probed once, then one bool read per call on CPU)."""
    if not memory_stats_available():
        return None
    try:
        import jax
        return sum(int((d.memory_stats() or {}).get("bytes_in_use", 0))
                   for d in jax.local_devices())
    except Exception:
        return None


def sample_device_peak() -> Optional[int]:
    """MAX ``bytes_in_use`` over local devices — the admission-relevant
    occupancy: a plain-jit dispatch allocates on one device, so averaging
    the total across an 8-device host would understate the hot device by
    up to 8x. ``None`` when the backend does not report."""
    if not memory_stats_available():
        return None
    try:
        import jax
        return max(int((d.memory_stats() or {}).get("bytes_in_use", 0))
                   for d in jax.local_devices())
    except Exception:
        return None


def register_memory_gauges(registry) -> bool:
    """Install live ``device.memory_stats()`` gauges into a
    :class:`~cycloneml_tpu.util.metrics.MetricsRegistry`.

    Per local device: ``device.<i>.memory.bytes_in_use`` /
    ``.peak_bytes_in_use`` / ``.bytes_limit``, plus the mesh-wide
    ``device.memory.bytes_in_use.total``. Always registers
    ``device.memoryStats.available`` (1/0) so the backend matrix is
    scrape-visible; on backends without memory_stats (CPU) that gauge is
    the only one installed. A gauge whose poll starts raising is skipped
    by the scrape, not fatal (see MetricsRegistry.values).
    """
    registry.gauge("device.memoryStats.available",
                   lambda: 1.0 if memory_stats_available() else 0.0)
    if not memory_stats_available():
        return False
    import jax

    def _stat(dev, k):
        return float((dev.memory_stats() or {}).get(k, float("nan")))

    for i, dev in enumerate(jax.local_devices()):
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            registry.gauge(f"device.{i}.memory.{k}",
                           lambda d=dev, k=k: _stat(d, k))
    registry.gauge("device.memory.bytes_in_use.total",
                   lambda: float(sample_memory() or 0))
    return True


# -- roofline peak table ---------------------------------------------------------

def backend_peaks() -> Tuple[Optional[float], Optional[float]]:
    """(peak matmul flop/s, peak HBM bytes/s) PER DEVICE for the attached
    backend, or (None, None) when no published figure exists (CPU test
    runs — roofline fields then report unavailable). Sources: public TPU
    spec sheets, the same figures the scaling book uses."""
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return None, None
    if "v5 lite" in kind or "v5e" in kind:
        return 197e12, 819e9
    if "v5p" in kind or "v5" in kind:
        return 459e12, 2765e9
    if "v4" in kind:
        return 275e12, 1228e9
    if "v6" in kind or "trillium" in kind:
        return 918e12, 1640e9
    return None, None


# -- compile-time memory budget guard --------------------------------------------

def device_memory_limit(conf=None) -> Optional[int]:
    """Per-device memory bytes the budget guard divides into:
    ``cyclone.memory.deviceBytes`` when set, else ``bytes_limit`` from
    ``memory_stats()``, else (host-platform devices share host RAM) total
    host RAM. ``None`` when nothing is known."""
    if conf is not None:
        try:
            from cycloneml_tpu.conf import MEMORY_DEVICE_BYTES
            override = int(conf.get(MEMORY_DEVICE_BYTES))
            if override > 0:
                return override
        except Exception:
            pass
    if memory_stats_available():
        try:
            import jax
            limit = (jax.devices()[0].memory_stats() or {}).get("bytes_limit")
            if limit:
                return int(limit)
        except Exception:
            pass
    try:
        return os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
    except (ValueError, OSError, AttributeError):
        return None


def guard_armed(conf) -> bool:
    """The guard costs an AOT analysis, so it arms only when someone asked
    for it: an explicit ``cyclone.memory.budgetFraction`` in the conf, or
    FULL tracing already on (the harvest is then already paid). The
    always-on flight-recorder ring (``Tracer.full`` False) does NOT arm it
    — flight mode's whole contract is recording spans at near-zero cost."""
    from cycloneml_tpu.conf import MEMORY_BUDGET_FRACTION
    return (conf.contains_raw(MEMORY_BUDGET_FRACTION.key)
            or tracing.full_active() is not None)


def check_budget(pid: str, conf=None, bus=None,
                 allow_raise: bool = True) -> Optional[BudgetVerdict]:
    """Compare a registered program's predicted peak HBM against the
    configured budget. On excess: post ``MemoryBudgetExceeded`` (to ``bus``
    or the active context's listener bus), warn, and raise ONLY under
    ``cyclone.memory.budgetAction=raise`` — the default mode never throws.
    Callers with a degradation option (the chunked L-BFGS guard) pass
    ``allow_raise=False`` while candidates remain and escalate themselves
    once the options are exhausted, so raise-mode still degrades first.
    Returns ``None`` when the program/conf/limit is unknown."""
    entry = lookup(pid)
    if entry is None or entry.get("peak_bytes") is None:
        return None
    if conf is None or bus is None:
        from cycloneml_tpu.context import active_context
        ctx = active_context()
        if ctx is not None:
            conf = conf if conf is not None else ctx.conf
            bus = bus if bus is not None else ctx.listener_bus
    if conf is None:
        return None
    from cycloneml_tpu.conf import MEMORY_BUDGET_ACTION, MEMORY_BUDGET_FRACTION
    fraction = float(conf.get(MEMORY_BUDGET_FRACTION))
    action = str(conf.get(MEMORY_BUDGET_ACTION))
    limit = device_memory_limit(conf)
    if not limit:
        return None
    budget = int(limit * fraction)
    peak = int(entry["peak_bytes"])
    verdict = BudgetVerdict(exceeded=peak > budget, predicted_bytes=peak,
                            budget_bytes=budget, limit_bytes=limit,
                            fraction=fraction, action=action)
    if not verdict.exceeded:
        return verdict
    logger.warning(
        "memory budget exceeded: program %s predicts %d bytes peak HBM "
        "per device > budget %d (%.3g of %d); action=%s",
        pid, peak, budget, fraction, limit, action)
    if bus is not None:
        from cycloneml_tpu.util.events import MemoryBudgetExceeded
        bus.post(MemoryBudgetExceeded(
            program=pid, predicted_bytes=peak, budget_bytes=budget,
            limit_bytes=limit, fraction=fraction, action=action))
    if action == "raise" and allow_raise:
        raise MemoryBudgetError(
            f"program {pid} predicts {peak} bytes peak HBM per device, "
            f"over the {budget}-byte budget "
            f"({fraction:g} x {limit}); set cyclone.memory.budgetAction="
            f"warn (default) to degrade instead")
    return verdict


def select_chunk(chunk: int, predicted_bytes: int, budget_bytes: int) -> int:
    """FIRST GUESS at a degraded ``deviceChunk`` for an over-budget chunk
    program: proportional scale-down, floored at 1 and always strictly
    below the chunk that was just predicted not to fit. Much of a chunk
    program's footprint is chunk-INDEPENDENT (data arrays, coefficients,
    curvature history), so this guess can still be over budget — callers
    (``device_lbfgs._budget_guarded_chunk``) must re-analyze the rebuilt
    program and iterate (with halving, which guarantees progress) until it
    fits or chunk reaches 1. Chunk size never changes the trajectory
    (pinned by the chunk-size-invariance tests), only the dispatch count."""
    if predicted_bytes <= budget_bytes or chunk <= 1:
        return chunk
    scaled = int(chunk * budget_bytes / max(predicted_bytes, 1))
    return max(1, min(scaled, chunk - 1))
