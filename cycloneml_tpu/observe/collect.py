"""Cross-process trace collection: shipper, collector, clock alignment.

Dapper's model (Sigelman et al. 2010, PAPERS.md) applied to the deploy
fabric: every process records spans into its local tracer; a
:class:`SpanShipper` on each worker drains the ring (bounded, batched,
drop-counted) and ships completed spans over TCP to a
:class:`TraceCollector` on the master, which merges everything into ONE
Chrome trace with a process lane per host
(:func:`~cycloneml_tpu.observe.export.merged_chrome_trace`).

Trace context rides the deploy wire: ``deploy.submit_app`` opens a
``deploy`` span and injects the active collector's launch env
(:meth:`TraceCollector.launch_env`) — trace id, the submit span's
host-qualified id as the remote parent, and the collector address (a
``cyclone.telemetry.collect.address`` conf seed) — into the app env the
Master schedules and the Worker passes to the launched process. The
launched ``CycloneContext`` adopts the context
(``Tracer.set_trace_context``) and starts a shipper, so a master-submitted
step correlates with its worker-side dispatch spans by construction.

Clock alignment: wall clocks differ across hosts, so the collector
estimates a per-host offset from the EXTENDED heartbeat pings
(``parallel/resilience.py``): each round trip yields an NTP-style sample
``offset = (t_send + t_recv)/2 - t_server`` whose error is bounded by
RTT/2 (the true send→server and server→recv legs each lie inside the
measured RTT). The sender records samples here
(:func:`record_offset_sample`); the shipper forwards the recent window
with every batch; the collector takes the **median of the lowest-RTT
samples** — robust to the asymmetric-delay outliers a loaded fabric
produces — and corrects that host's timestamps by a constant, which
preserves per-lane monotonicity. Hosts that never heartbeat merge at
offset 0 with an explicit ``offset_err_s: None``.

Wire protocol: one JSON line per connection on the shared authed TCP
fabric (util/tcp.py — the deploy/heartbeat handshake covers this channel
too): ``{"kind": "spans", "host": ..., "pid": ..., "trace_id": ...,
"dropped": ..., "offset_samples": [[offset_s, rtt_s], ...],
"tid_names": {...}, "spans": [...], "usage": {...}}`` → ``{"ok": true}``.
The optional ``usage`` field is the worker's cumulative attribution
ledger snapshot (``observe/attribution.py``): like ``dropped`` it is a
running total, so the collector folds it by REPLACEMENT per host and
:meth:`TraceCollector.merged_usage` sums per-scope rows across hosts —
the cross-host accounting join.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from cycloneml_tpu.observe import export, tracing
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: offset samples retained process-wide / forwarded per batch
MAX_OFFSET_SAMPLES = 128
SHIPPED_OFFSET_SAMPLES = 32
#: lowest-RTT samples the collector's median runs over
OFFSET_ESTIMATE_K = 5
#: per-host span bound on the collector (drop-counted past it)
MAX_SPANS_PER_HOST = 200_000


# -- clock-offset sample registry (fed by HeartbeatSender._ping) ---------------
_offset_lock = threading.Lock()
_offset_samples: "deque[Tuple[float, float]]" = deque(
    maxlen=MAX_OFFSET_SAMPLES)


def record_offset_sample(offset_s: float, rtt_s: float) -> None:
    """One NTP-style (offset, rtt) sample of this process's clock vs the
    heartbeat server's; |true offset - offset_s| <= rtt_s / 2."""
    with _offset_lock:
        _offset_samples.append((float(offset_s), float(rtt_s)))


def offset_samples(limit: int = SHIPPED_OFFSET_SAMPLES
                   ) -> List[Tuple[float, float]]:
    with _offset_lock:
        samples = list(_offset_samples)
    return samples[-limit:]


def clear_offset_samples() -> None:
    with _offset_lock:
        _offset_samples.clear()


def estimate_offset(samples) -> Tuple[float, Optional[float]]:
    """(offset_s, error_bound_s) from (offset, rtt) samples: the median of
    the :data:`OFFSET_ESTIMATE_K` lowest-RTT samples, bounded by the worst
    RTT/2 among those used. (0.0, None) when there are no samples."""
    samples = [(float(o), float(r)) for o, r in (samples or [])]
    if not samples:
        return 0.0, None
    best = sorted(samples, key=lambda s: s[1])[:OFFSET_ESTIMATE_K]
    offset = statistics.median(o for o, _ in best)
    err = max(r for _, r in best) / 2.0
    return offset, err


# -- span wire encoding --------------------------------------------------------

def encode_spans(spans, wall_base: float) -> List[Dict[str, Any]]:
    """Span objects -> JSON-able wire dicts with WALL-clock t0/t1 (the
    shipper converts; the collector only ever sees absolute times)."""
    out = []
    for s in spans:
        out.append({"id": s.span_id, "parent": s.parent_id, "kind": s.kind,
                    "name": s.name, "t0": wall_base + s.t0,
                    "t1": wall_base + s.t1, "tid": s.tid,
                    "attrs": dict(s.attrs)})
    return out


# -- collector (master side) ---------------------------------------------------

_active_lock = threading.Lock()
_active_collector: Optional["TraceCollector"] = None


def active_collector() -> Optional["TraceCollector"]:
    """The process-global collector (deploy.submit_app injects its launch
    env automatically when one is running)."""
    with _active_lock:
        return _active_collector


class TraceCollector:
    """TCP endpoint receiving span batches; merges every host's spans —
    plus this process's own tracer — into one Chrome trace."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 host_label: str = "master", tracer=None):
        import socketserver
        self.host_label = host_label
        self._tracer = tracer
        self._lock = threading.Lock()
        self._hosts: Dict[str, Dict[str, Any]] = {}
        self.batches = 0
        self.dropped = 0      # spans past the per-host bound
        collector = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    self.request.settimeout(10.0)
                    line = self.rfile.readline(16 * 1024 * 1024)
                    if not line.strip():
                        return
                    reply = collector._ingest(json.loads(line))
                except Exception as e:  # malformed batch must not kill us
                    reply = {"ok": False, "error": repr(e)}
                self.wfile.write((json.dumps(reply) + "\n").encode())

        from cycloneml_tpu.util.tcp import start_tcp_server
        self._server = start_tcp_server(host, port, Handler,
                                        "cyclone-trace-collector")
        self.address = f"{host}:{self._server.server_address[1]}"
        global _active_collector
        with _active_lock:
            if _active_collector is None:
                _active_collector = self
        logger.info("trace collector listening on %s", self.address)

    # -- ingestion -------------------------------------------------------------
    def _ingest(self, msg: dict) -> dict:
        if msg.get("kind") != "spans":
            return {"ok": False, "error": f"unknown kind {msg.get('kind')!r}"}
        host = str(msg.get("host") or "unknown")
        # sanitize BEFORE storing: a malformed batch must fail ITS reply,
        # never poison hosts()/merged_trace() with a deferred ValueError
        # on every later read (the bad record would sit in _hosts forever)
        spans = []
        for w in msg.get("spans") or []:
            try:
                spans.append({
                    "id": str(w.get("id", "")), "parent":
                        str(w.get("parent", "")),
                    "kind": str(w.get("kind", "span")),
                    "name": str(w.get("name", "")),
                    "t0": float(w.get("t0", 0.0)),
                    "t1": float(w.get("t1", 0.0)),
                    "tid": int(w.get("tid", 0)),
                    "attrs": dict(w.get("attrs") or {})})
            except (TypeError, ValueError, AttributeError):
                continue  # skip the torn span, keep the batch
        samples = []
        for pair in msg.get("offset_samples") or []:
            try:
                o, r = pair
                samples.append((float(o), float(r)))
            except (TypeError, ValueError):
                continue
        with self._lock:
            rec = self._hosts.setdefault(host, {
                "host": host, "pid": msg.get("pid"), "trace_id": "",
                # worker-reported drops (ring + ship buffer; a running
                # total, so each batch REPLACES it) are tracked apart
                # from drops the collector itself takes past the
                # per-host bound (a local running sum) — "dropped" in
                # hosts()/the merged header is their sum
                "ship_dropped": 0, "local_dropped": 0,
                "offset_samples": [], "tid_names": {}, "spans": [],
                "usage": {}})
            rec["pid"] = msg.get("pid") or rec["pid"]
            if msg.get("trace_id"):
                rec["trace_id"] = str(msg["trace_id"])
            try:
                rec["ship_dropped"] = int(msg.get("dropped") or 0)
            except (TypeError, ValueError):
                pass
            usage = msg.get("usage")
            if isinstance(usage, dict):
                # cumulative ledger snapshot: REPLACE, like ship_dropped
                rec["usage"] = {str(k): dict(v)
                                for k, v in usage.items()
                                if isinstance(v, dict)}
            rec["offset_samples"].extend(samples)
            rec["offset_samples"] = rec["offset_samples"][-MAX_OFFSET_SAMPLES:]
            try:
                rec["tid_names"].update(
                    {int(k): str(v)
                     for k, v in (msg.get("tid_names") or {}).items()})
            except (TypeError, ValueError):
                pass
            rec["spans"].extend(spans)
            over = len(rec["spans"]) - MAX_SPANS_PER_HOST
            if over > 0:
                # oldest-dropped, same ring discipline as the tracer
                del rec["spans"][:over]
                rec["local_dropped"] += over
                self.dropped += over
            self.batches += 1
        return {"ok": True, "received": len(spans)}

    # -- reading ---------------------------------------------------------------
    def hosts(self) -> Dict[str, Dict[str, Any]]:
        """Per-host ingest state: pid, trace_id, span/drop counts, and the
        current clock-offset estimate."""
        out = {}
        with self._lock:
            items = [(h, dict(rec, spans=len(rec["spans"])))
                     for h, rec in self._hosts.items()]
        for host, rec in items:
            offset, err = estimate_offset(rec.pop("offset_samples"))
            rec["offset_s"], rec["offset_err_s"] = offset, err
            rec["dropped"] = (rec.pop("ship_dropped")
                              + rec.pop("local_dropped"))
            out[host] = rec
        return out

    def _records(self) -> List[Dict[str, Any]]:
        records: List[Dict[str, Any]] = []
        tr = self._tracer if self._tracer is not None else tracing.active()
        if tr is not None:
            # the collector's own process is lane 1, offset 0 by definition
            records.append({
                "host": self.host_label, "pid": os.getpid(),
                "offset_s": 0.0, "offset_err_s": 0.0,
                "trace_id": tr.trace_id, "dropped": tr.dropped,
                "tid_names": tr.thread_names(),
                "spans": encode_spans(tr.snapshot(), tr.wall_base)})
        with self._lock:
            hosts = [dict(rec, spans=list(rec["spans"]),
                          offset_samples=list(rec["offset_samples"]))
                     for rec in self._hosts.values()]
        for rec in sorted(hosts, key=lambda r: r["host"]):
            offset, err = estimate_offset(rec.pop("offset_samples"))
            rec["offset_s"], rec["offset_err_s"] = offset, err
            rec["dropped"] = (rec.pop("ship_dropped")
                              + rec.pop("local_dropped"))
            records.append(rec)
        return records

    def merged_trace(self) -> Dict[str, Any]:
        """ONE Chrome-trace object: a process lane per host, span ids
        host-qualified, timestamps clock-offset corrected."""
        return export.merged_chrome_trace(self._records())

    def merged_usage(self) -> Dict[str, Dict[str, Any]]:
        """Cross-host attribution rollup: every shipped per-host ledger
        snapshot (cumulative, REPLACE-folded per host) plus this
        process's own live ledger, merged per scope key — additive
        fields sum, peaks take the max."""
        from cycloneml_tpu.observe import attribution
        snaps = []
        led = attribution.active()
        if led is not None:
            snaps.append(led.snapshot())
        with self._lock:
            snaps.extend(dict(rec["usage"]) for rec in self._hosts.values()
                         if rec.get("usage"))
        return attribution.merge_snapshots(snaps)

    def ingest_stats(self) -> Dict[str, Any]:
        """Collector-side loss accounting for the telemetry drop-counter
        surface: batches ingested, spans evicted past the per-host bound
        here, and the workers' self-reported delivery loss."""
        with self._lock:
            ship = sum(int(r.get("ship_dropped") or 0)
                       for r in self._hosts.values())
            return {"hosts": len(self._hosts), "batches": self.batches,
                    "ingestDropped": self.dropped, "shipDropped": ship}

    def export(self, path: str) -> str:
        return export.write_chrome_trace(self.merged_trace(), path)

    # -- launch-env propagation ------------------------------------------------
    def launch_env(self, parent_span_id: str = "",
                   trace_id: str = "") -> Dict[str, str]:
        """Env vars that make a launched process join this collector's
        distributed trace: adopted trace id + remote parent
        (``CYCLONE_TRACE_ID``/``CYCLONE_TRACE_PARENT``) and the collector
        address via the normal conf env channel, which also auto-enables
        tracing in the launched context."""
        tr = self._tracer if self._tracer is not None else tracing.active()
        tid = trace_id or (tr.trace_id if tr is not None else "")
        env = {
            "CYCLONE_CONF_cyclone__telemetry__collect__address":
                self.address,
        }
        if tid:
            env["CYCLONE_TRACE_ID"] = tid
        if parent_span_id:
            env["CYCLONE_TRACE_PARENT"] = export._qualify(
                parent_span_id, self.host_label)
        return env

    def stop(self) -> None:
        global _active_collector
        with _active_lock:
            if _active_collector is self:
                _active_collector = None
        self._server.shutdown()
        self._server.server_close()


# -- shipper (worker side) -----------------------------------------------------

class SpanShipper:
    """Periodically drains the active tracer and ships span batches to a
    collector. Bounded and drop-counted: an unreachable collector buffers
    up to ``max_buffer`` wire spans (oldest dropped past it) and retries
    each interval; shipping never blocks a recording site.

    Single-threaded by design: the buffer and cursor are touched ONLY by
    the shipper thread (plus the final flush, which runs after the thread
    is joined) — no lock, no lock-ordering surface.
    """

    def __init__(self, address: str, host_label: str,
                 interval_s: float = 0.5, max_batch: int = 4096,
                 max_buffer: int = 65536, tracer=None):
        host, _, port = str(address).rpartition(":")
        self._addr = (host or "127.0.0.1", int(port))
        self.host_label = host_label
        self.interval_s = max(float(interval_s), 0.01)
        self.max_batch = max(int(max_batch), 1)
        self.max_buffer = max(int(max_buffer), self.max_batch)
        self._tracer = tracer
        self._since = 0
        self._buf: List[Dict[str, Any]] = []
        self.shipped = 0
        self.dropped = 0      # buffer overflow while the collector was away
        # spans the RING evicted before a drain reached them — the only
        # tracer-side loss that is DELIVERY loss. tr.dropped alone counts
        # every ring rotation, which on a long healthy job is huge while
        # actual loss is zero (the cursor passes spans before eviction).
        self.ring_missed = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="cyclone-trace-ship", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._ship_once()
            except Exception:
                logger.exception("span shipper tick failed")

    def _ship_once(self) -> int:
        tr = self._tracer if self._tracer is not None else tracing.active()
        if tr is None:
            return 0
        prev = self._since
        spans, self._since = tr.drain(self._since)
        # positions advanced past vs spans delivered: the difference fell
        # off the ring floor between drains — true delivery loss
        self.ring_missed += max(0, (self._since - prev) - len(spans))
        if spans:
            self._buf.extend(encode_spans(spans, tr.wall_base))
            over = len(self._buf) - self.max_buffer
            if over > 0:
                del self._buf[:over]
                self.dropped += over
        if not self._buf:
            return 0
        # tag batches with this process's cumulative attribution ledger
        # (scope ids + rollups): the collector REPLACE-folds it per host,
        # so usage flows cross-host on the channel spans already ride
        from cycloneml_tpu.observe import attribution
        led = attribution.active()
        usage = led.snapshot() if led is not None else None
        sent = 0
        while self._buf:
            batch, rest = (self._buf[:self.max_batch],
                           self._buf[self.max_batch:])
            msg = {"kind": "spans", "host": self.host_label,
                   "pid": os.getpid(), "trace_id": tr.trace_id,
                   # DELIVERY loss only: ring evictions the cursor missed
                   # plus ship-buffer overflow — NOT tr.dropped, which
                   # counts every rotation of a ring the cursor outruns
                   "dropped": self.ring_missed + self.dropped,
                   "offset_samples": offset_samples(),
                   "tid_names": tr.thread_names(), "spans": batch}
            if usage is not None:
                msg["usage"] = usage
            try:
                reply = self._send(msg)
            except (OSError, ValueError):
                break  # collector away: keep buffering, retry next tick
            if not reply.get("ok"):
                logger.warning("span batch rejected: %s", reply.get("error"))
                break
            self._buf = rest
            sent += len(batch)
            self.shipped += len(batch)
        return sent

    def _send(self, msg: dict) -> dict:
        from cycloneml_tpu.util.tcp import check_not_challenge, connect_authed
        with connect_authed(self._addr[0], self._addr[1], timeout=10) as s:
            s.sendall((json.dumps(msg, default=str) + "\n").encode())
            fh = s.makefile("r")
            try:
                line = fh.readline()
            finally:
                fh.close()
        check_not_challenge(line)
        return json.loads(line) if line.strip() else {}

    def delivery_stats(self) -> Dict[str, Any]:
        """Delivery-loss accounting for the telemetry drop-counter
        surface: spans shipped, ship-buffer overflow, and ring evictions
        the cursor missed (true loss — not ``tr.dropped``, which counts
        every rotation of a ring the cursor outruns)."""
        return {"shipped": self.shipped, "bufferDropped": self.dropped,
                "ringMissed": self.ring_missed, "buffered": len(self._buf)}

    def flush(self) -> int:
        """Final synchronous ship — call AFTER :meth:`stop` (the loop
        thread is then joined, so the single-owner discipline holds)."""
        return self._ship_once()

    def stop(self, flush: bool = True) -> None:
        self._stop.set()
        self._thread.join(timeout=10)
        if self._thread.is_alive():
            # the loop thread is wedged mid-_send (hung collector):
            # flushing NOW would break the single-owner discipline on
            # _buf/_since (double-delivery or a corrupted cursor) —
            # skip, loudly
            logger.warning("span shipper thread still busy after stop; "
                           "skipping the final flush")
            return
        if flush:
            try:
                self._ship_once()
            except Exception:
                logger.exception("final span flush failed")
