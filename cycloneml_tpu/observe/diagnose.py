"""Performance doctor: deterministic, evidence-joined bottleneck diagnosis.

``diagnose(profile | trace | flight-ring) -> DiagnosisReport`` turns five
PRs of sensors into answers. Every rule JOINS evidence the telemetry
plane already records — no new instrumentation:

==================== ==========================================================
finding kind         evidence joined
==================== ==========================================================
bandwidth-bound /    costs registry rollup (``FitProfile.roofline_fraction``
compute-bound        / ``arithmetic_intensity`` vs the ridge point)
recompile-storm      compile spans recurring past warm-up, keyed by
                     program-cache identity (span name)
transfer-stall       non-streaming transfer-span seconds vs dispatch +
                     collective seconds — the runtime twin of JX001
straggler            SkewDetector lane snapshot (latched median+MAD verdicts)
                     and/or per-lane stats recomputed from oocore.stage spans
under-lapped-        stage/compute overlap fraction from the stream spans
streaming            (same interval math as scripts/bench_oocore.py)
serving-pressure     batcher tallies (shed counters, per-model p99) vs
                     ``cyclone.telemetry.slo.servingMs``
precision-churn      precision.fallback instants (the fp8 envelope re-proving
                     itself instead of staying settled)
cache-restream       ShardSetCache stats (LRU thrash: evictions + misses
                     outrunning hits on a re-fit)
fault-pressure       chaos instants (injected faults) + staging retries
==================== ==========================================================

Rules ABSTAIN when their evidence plane is absent (no costs peaks on CPU,
no stream spans, no serving stats) — a clean warm fit diagnoses to ZERO
findings. The report is deterministic: same inputs => byte-identical
canonical JSON (``DiagnosisReport.to_json``), no wall-clock fields, all
orderings explicit. Import-light on purpose: nothing here touches jax.
"""

import json
import statistics
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from cycloneml_tpu.conf import (DOCTOR_FALLBACK_MIN, DOCTOR_MIN_STREAM_SPANS,
                                DOCTOR_OVERLAP_MIN, DOCTOR_RECOMPILE_MIN,
                                DOCTOR_ROOFLINE_FRACTION, DOCTOR_SHED_MIN,
                                DOCTOR_TRANSFER_MIN_COUNT,
                                DOCTOR_TRANSFER_STALL_FRACTION,
                                SKEW_MAD_FACTOR, SKEW_MIN_GAP_MS,
                                SKEW_MIN_SAMPLES, SKEW_REL_FACTOR,
                                SLO_SERVING_MS)
from cycloneml_tpu.observe.profile import FitProfile

# severity rank for the deterministic sort (higher = earlier)
_SEVERITY_RANK = {"critical": 2, "warning": 1, "info": 0}

# sentinel: "look the live source up yourself" (pass None to disable)
_LIVE = object()


@dataclass
class Finding:
    """One convicted bottleneck: the verdict plus the raw numbers that
    convicted it (``evidence``) and the next action (``remedy``)."""

    kind: str
    severity: str                 # "info" | "warning" | "critical"
    score: float                  # rule-relative magnitude, for ranking
    summary: str
    evidence: Dict[str, Any] = field(default_factory=dict)
    remedy: str = ""

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "severity": self.severity,
                "score": self.score, "summary": self.summary,
                "evidence": dict(self.evidence), "remedy": self.remedy}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Finding":
        return cls(kind=d.get("kind", ""), severity=d.get("severity", "info"),
                   score=float(d.get("score", 0.0)),
                   summary=d.get("summary", ""),
                   evidence=dict(d.get("evidence", {})),
                   remedy=d.get("remedy", ""))


@dataclass
class DiagnosisReport:
    """Ranked findings over one analyzed window. No wall-clock fields:
    the same window diagnoses to byte-identical ``to_json`` output."""

    source: str = ""              # "trace" | "profile" | "flight" | "live"
    n_spans: int = 0
    inputs: List[str] = field(default_factory=list)   # evidence planes seen
    findings: List[Finding] = field(default_factory=list)

    @property
    def kinds(self) -> List[str]:
        return [f.kind for f in self.findings]

    def to_dict(self) -> Dict[str, Any]:
        return {"schema": 1, "source": self.source, "n_spans": self.n_spans,
                "inputs": list(self.inputs),
                "findings": [f.to_dict() for f in self.findings]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DiagnosisReport":
        return cls(source=d.get("source", ""),
                   n_spans=int(d.get("n_spans", 0)),
                   inputs=list(d.get("inputs", [])),
                   findings=[Finding.from_dict(f)
                             for f in d.get("findings", [])])

    def to_json(self) -> str:
        """Canonical JSON: sorted keys, tight separators — the
        byte-identical surface the determinism gate pins."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    def render_text(self) -> str:
        lines = [f"doctor: {len(self.findings)} finding(s) over "
                 f"{self.n_spans} span(s) "
                 f"[source={self.source or 'unknown'}; "
                 f"inputs={','.join(self.inputs) or 'none'}]"]
        if not self.findings:
            lines.append("  healthy: every rule abstained or passed")
        for f in self.findings:
            lines.append(f"  [{f.severity}] {f.kind}: {f.summary}")
            ev = json.dumps(f.evidence, sort_keys=True)
            lines.append(f"      evidence: {ev}")
            if f.remedy:
                lines.append(f"      remedy:   {f.remedy}")
        return "\n".join(lines)


@dataclass
class DoctorConfig:
    """Thresholds for every rule; defaults mirror the registered
    ``cyclone.doctor.*`` / skew / SLO conf values."""

    recompile_min: int = 2
    transfer_stall_fraction: float = 0.5
    transfer_min_count: int = 8
    overlap_min: float = 0.30
    min_stream_spans: int = 8
    shed_min: int = 1
    fallback_min: int = 1
    roofline_fraction: float = 0.5
    skew_mad_factor: float = 4.0
    skew_rel_factor: float = 1.5
    skew_min_gap_s: float = 0.010
    skew_min_samples: int = 8
    slo_serving_ms: float = 0.0

    @classmethod
    def from_conf(cls, conf) -> "DoctorConfig":
        return cls(
            recompile_min=conf.get(DOCTOR_RECOMPILE_MIN),
            transfer_stall_fraction=conf.get(DOCTOR_TRANSFER_STALL_FRACTION),
            transfer_min_count=conf.get(DOCTOR_TRANSFER_MIN_COUNT),
            overlap_min=conf.get(DOCTOR_OVERLAP_MIN),
            min_stream_spans=conf.get(DOCTOR_MIN_STREAM_SPANS),
            shed_min=conf.get(DOCTOR_SHED_MIN),
            fallback_min=conf.get(DOCTOR_FALLBACK_MIN),
            roofline_fraction=conf.get(DOCTOR_ROOFLINE_FRACTION),
            skew_mad_factor=conf.get(SKEW_MAD_FACTOR),
            skew_rel_factor=conf.get(SKEW_REL_FACTOR),
            skew_min_gap_s=conf.get(SKEW_MIN_GAP_MS) / 1e3,
            skew_min_samples=conf.get(SKEW_MIN_SAMPLES),
            slo_serving_ms=conf.get(SLO_SERVING_MS),
        )


# -- interval math (the bench_oocore overlap contract) -------------------------

def _merge_intervals(intervals: Sequence[Tuple[float, float]]):
    merged: List[List[float]] = []
    for lo, hi in sorted(intervals):
        if merged and lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return merged


def overlap_fraction(spans) -> Tuple[float, float, float, int, int]:
    """``(frac, stage_s, shard_s, n_stage, n_shard)`` over the stream
    spans: sum |stage ∩ (∪ shard)| / min(sum stage, sum shard)."""
    stage = [(s.t0, s.t1) for s in spans if s.name == "oocore.stage"]
    shard = [(s.t0, s.t1) for s in spans if s.name == "oocore.shard"]
    if not stage or not shard:
        return 0.0, 0.0, 0.0, len(stage), len(shard)
    stage_total = sum(hi - lo for lo, hi in stage)
    shard_total = sum(hi - lo for lo, hi in shard)
    shard_u = _merge_intervals(shard)
    inter = 0.0
    for lo, hi in stage:
        for ulo, uhi in shard_u:
            inter += max(0.0, min(hi, uhi) - max(lo, ulo))
    denom = min(stage_total, shard_total)
    frac = inter / denom if denom > 0 else 0.0
    return frac, stage_total, shard_total, len(stage), len(shard)


def lane_stats_from_spans(spans, n_lanes: int = 64) -> Dict[str, List[float]]:
    """Per-lane staging durations recomputed from ``oocore.stage`` spans
    (same ``shard<i mod N>`` folding the live SkewDetector uses), so a
    trace file alone can answer the straggler question."""
    lanes: Dict[str, List[float]] = {}
    for s in spans:
        if s.name != "oocore.stage":
            continue
        shard = s.attrs.get("shard")
        if shard is None:
            continue
        lane = f"shard{int(shard) % n_lanes}"
        lanes.setdefault(lane, []).append(s.duration_s)
    return lanes


def _straggler_lanes(lanes: Dict[str, List[float]],
                     cfg: DoctorConfig) -> List[Dict[str, Any]]:
    """The SkewDetector's 3-gate median+MAD conviction, replayed over
    trace-derived lane samples."""
    meds = {lane: statistics.median(v) for lane, v in sorted(lanes.items())
            if len(v) >= cfg.skew_min_samples}
    if len(meds) < 2:
        return []
    values = [meds[lane] for lane in sorted(meds)]
    group_med = statistics.median(values)
    mad = statistics.median([abs(v - group_med) for v in values])
    out = []
    for lane in sorted(meds):
        mine = meds[lane]
        if (mine > group_med + cfg.skew_mad_factor * mad
                and mine > cfg.skew_rel_factor * group_med
                and mine - group_med > cfg.skew_min_gap_s):
            out.append({"lane": lane, "lane_median_s": round(mine, 6),
                        "group_median_s": round(group_med, 6),
                        "mad_s": round(mad, 6),
                        "n_samples": len(lanes[lane])})
    return out


# -- rules ---------------------------------------------------------------------

def _rule_roofline(profile: Optional[FitProfile],
                   cfg: DoctorConfig) -> List[Finding]:
    if profile is None or profile.roofline_fraction is None:
        return []     # CPU / no costs peaks: nothing measured, abstain
    frac = profile.roofline_fraction
    if frac < cfg.roofline_fraction:
        return []     # host-bound: the other rules explain why
    intensity = profile.arithmetic_intensity
    bandwidth = intensity is not None and intensity < 1.0
    kind = "bandwidth-bound" if bandwidth else "compute-bound"
    remedy = ("fewer bytes per flop: narrower data tier, fused sweeps, "
              "larger shards" if bandwidth else
              "the fit is at the compute roof: more devices or a cheaper "
              "algorithm, not tuning")
    return [Finding(
        kind=kind, severity="info", score=round(frac, 6),
        summary=f"running at {frac:.0%} of the measured "
                f"{'memory' if bandwidth else 'compute'} ceiling",
        evidence={"roofline_fraction": round(frac, 6),
                  "arithmetic_intensity": (round(intensity, 6)
                                           if intensity is not None else None),
                  "total_flops": profile.total_flops},
        remedy=remedy)]


def _rule_recompile(spans, cfg: DoctorConfig) -> List[Finding]:
    if not spans:
        return []
    counts: Dict[str, int] = {}
    for s in spans:
        if s.kind == "compile":
            counts[s.name] = counts.get(s.name, 0) + 1
    excess = {name: c - 1 for name, c in sorted(counts.items()) if c > 1}
    total_excess = sum(excess.values())
    if total_excess < cfg.recompile_min:
        return []
    return [Finding(
        kind="recompile-storm", severity="warning",
        score=float(total_excess),
        summary=f"{total_excess} recompile(s) past warm-up across "
                f"{len(excess)} program(s)",
        evidence={"excess_compiles": excess,
                  "total_excess": total_excess,
                  "programs_compiled": len(counts)},
        remedy="stabilize shapes/dtypes feeding the program cache: pad "
               "to buckets, pin the data tier, stop rebuilding meshes "
               "mid-fit")]


def _rule_transfer_stall(spans, profile: Optional[FitProfile],
                         cfg: DoctorConfig) -> List[Finding]:
    if spans:
        # streaming staging spans are transfer-kind too; their health is
        # the overlap rule's job, so readback stall excludes oocore.*
        transfers = [s for s in spans if s.kind == "transfer"
                     and not s.name.startswith("oocore.")]
        dispatch_s = sum(s.duration_s for s in spans
                         if s.kind in ("dispatch", "collective"))
        transfer_s = sum(s.duration_s for s in transfers)
        n_transfers = len(transfers)
    elif profile is not None:
        transfer_s = profile.transfer_seconds
        dispatch_s = profile.dispatch_seconds
        n_transfers = profile.transfer_count
    else:
        return []
    if (n_transfers < cfg.transfer_min_count or dispatch_s <= 0
            or transfer_s < cfg.transfer_stall_fraction * dispatch_s):
        return []
    ratio = transfer_s / dispatch_s
    return [Finding(
        kind="transfer-stall", severity="warning", score=round(ratio, 6),
        summary=f"host transfers cost {ratio:.2f}x device dispatch time "
                f"({n_transfers} transfers)",
        evidence={"transfer_seconds": round(transfer_s, 6),
                  "dispatch_seconds": round(dispatch_s, 6),
                  "transfer_count": n_transfers},
        remedy="keep results on device between steps (the JX001 "
               "discipline at runtime): batch readbacks, drop "
               "per-element device_get loops")]


def _rule_straggler(spans, skew_snapshot: Optional[Dict[str, Any]],
                    cfg: DoctorConfig) -> List[Finding]:
    findings: List[Finding] = []
    seen: Dict[str, List[str]] = {}
    if skew_snapshot:
        for group in sorted(skew_snapshot):
            g = skew_snapshot[group]
            bad = [lane for lane in sorted(g.get("lanes", {}))
                   if g["lanes"][lane].get("straggler")]
            if bad:
                seen[group] = bad
                findings.append(Finding(
                    kind="straggler", severity="warning",
                    score=float(len(bad)),
                    summary=f"{len(bad)} latched straggler lane(s) in "
                            f"{group}",
                    evidence={"group": group, "lanes": bad,
                              "group_median_s": round(
                                  g.get("groupMedianS", 0.0), 6),
                              "mad_s": round(g.get("madS", 0.0), 6),
                              "detector": "live"},
                    remedy="one lane is persistently slow (bad spindle / "
                           "hot host): rebalance shards or let the "
                           "speculation layer race it"))
    if spans:
        lanes = lane_stats_from_spans(spans)
        bad = [b for b in _straggler_lanes(lanes, cfg)
               if b["lane"] not in seen.get("oocore.stage", [])]
        if bad:
            findings.append(Finding(
                kind="straggler", severity="warning", score=float(len(bad)),
                summary=f"{len(bad)} straggler lane(s) in oocore.stage "
                        f"span timings",
                evidence={"group": "oocore.stage", "outliers": bad,
                          "n_lanes": len(lanes), "detector": "trace"},
                remedy="one staging lane is persistently slow: rebalance "
                       "shards or let the speculation layer race it"))
    return findings


def _rule_underlap(spans, cfg: DoctorConfig) -> List[Finding]:
    if not spans:
        return []
    frac, stage_s, shard_s, n_stage, n_shard = overlap_fraction(spans)
    if n_stage < cfg.min_stream_spans or n_shard < cfg.min_stream_spans:
        return []
    if frac >= cfg.overlap_min:
        return []
    return [Finding(
        kind="under-lapped-streaming", severity="warning",
        score=round(cfg.overlap_min - frac, 6),
        summary=f"stage/compute overlap {frac:.2f} below the "
                f"{cfg.overlap_min:.2f} gate",
        evidence={"overlap_fraction": round(frac, 6),
                  "stage_seconds": round(stage_s, 6),
                  "compute_seconds": round(shard_s, 6),
                  "n_stage_spans": n_stage, "n_shard_spans": n_shard},
        remedy="the double buffer is not hiding staging: raise the "
               "prefetch depth, shrink shards, or move shards to "
               "faster storage")]


def _rule_serving(serving_stats: Optional[Dict[str, Any]],
                  cfg: DoctorConfig) -> List[Finding]:
    if not serving_stats:
        return []
    totals = serving_stats.get("totals", {})
    shed = int(totals.get("shed", 0))
    worst_p99, worst_model = 0.0, ""
    for name in sorted(serving_stats.get("models", {})):
        p99 = serving_stats["models"][name].get("latencyMs", {}).get("p99")
        if p99 is not None and p99 > worst_p99:
            worst_p99, worst_model = float(p99), name
    over_slo = cfg.slo_serving_ms > 0 and worst_p99 > cfg.slo_serving_ms
    if shed < cfg.shed_min and not over_slo:
        return []
    bits = []
    if shed >= cfg.shed_min:
        bits.append(f"{shed} request(s) shed")
    if over_slo:
        bits.append(f"p99 {worst_p99:.1f}ms over the "
                    f"{cfg.slo_serving_ms:.0f}ms SLO ({worst_model})")
    return [Finding(
        kind="serving-pressure", severity="warning",
        score=float(shed) + (worst_p99 / cfg.slo_serving_ms
                             if over_slo else 0.0),
        summary="; ".join(bits),
        evidence={"shed": shed,
                  "requests": int(totals.get("requests", 0)),
                  "worst_p99_ms": round(worst_p99, 3),
                  "worst_model": worst_model,
                  "slo_serving_ms": cfg.slo_serving_ms},
        remedy="the batcher is saturating: raise maxBatch/window, add "
               "replicas (the autoscaler's job), or shed earlier at "
               "admission")]


def _rule_precision(profile: Optional[FitProfile],
                    cfg: DoctorConfig) -> List[Finding]:
    if profile is None or profile.fp8_fallbacks < cfg.fallback_min:
        return []
    n = profile.fp8_fallbacks
    return [Finding(
        kind="precision-churn", severity="info", score=float(n),
        summary=f"{n} precision fallback(s): the fp8 envelope keeps "
                f"re-proving itself",
        evidence={"fp8_fallbacks": n},
        remedy="the data violates the narrow tier's envelope: pin the "
               "tier explicitly or normalize the offending columns")]


def _rule_cache(cache_stats: Optional[Dict[str, Any]],
                cfg: DoctorConfig) -> List[Finding]:
    if not cache_stats:
        return []
    evicted = int(cache_stats.get("evictionsLru", 0))
    hits = int(cache_stats.get("hits", 0))
    misses = int(cache_stats.get("misses", 0))
    if evicted < 1 or misses <= hits:
        return []
    return [Finding(
        kind="cache-restream", severity="warning",
        score=float(misses - hits),
        summary=f"shard-set cache thrash: {misses} miss(es) vs {hits} "
                f"hit(s) with {evicted} LRU eviction(s)",
        evidence={"hits": hits, "misses": misses, "evictionsLru": evicted,
                  "evictionsCorrupt": int(
                      cache_stats.get("evictionsCorrupt", 0))},
        remedy="re-fits are re-blocking instead of reusing spilled "
               "shards: raise cyclone.oocore.cacheBytes or shrink the "
               "working set")]


def _rule_faults(profile: Optional[FitProfile], spans,
                 cfg: DoctorConfig) -> List[Finding]:
    faults = profile.faults_injected if profile is not None else 0
    retries = profile.retries if profile is not None else 0
    points: Dict[str, int] = {}
    for s in spans or []:
        if s.kind != "instant":
            continue
        if s.name == "fault":
            p = str(s.attrs.get("point", "?"))
            points[p] = points.get(p, 0) + 1
        elif s.name == "oocore.stage_retry":
            # staging retries carry their own instant name, not "retry"
            retries += 1
    if faults < 1 and retries < 1:
        return []
    return [Finding(
        kind="fault-pressure", severity="info",
        score=float(faults + retries),
        summary=f"{faults} injected fault(s), {retries} staging "
                f"retry(ies) in the window",
        evidence={"faults_injected": faults, "retries": retries,
                  "points": dict(sorted(points.items()))},
        remedy="chaos (or a flaky backend) is active: timings in this "
               "window measure the recovery path, not steady state")]


# -- entry point ---------------------------------------------------------------

def diagnose(subject: Any = None, *,
             spans=None,
             profile: Optional[FitProfile] = None,
             skew: Any = _LIVE,
             serving_stats: Optional[Dict[str, Any]] = None,
             cache_stats: Any = _LIVE,
             conf=None,
             source: str = "") -> DiagnosisReport:
    """Diagnose one analyzed window.

    ``subject`` may be a :class:`FitProfile`, a ``Tracer``, a span list,
    a flight-recorder dump dict (``{"spans": [...]}``) or a Chrome-trace
    dict (``{"traceEvents": [...]}``); keyword planes add or override.
    ``skew``/``cache_stats`` default to the live process-global sources
    (pass ``None`` to diagnose a trace file hermetically — the CLI and
    the flight-dump hook do, which is what makes their reports
    byte-identical across runs).
    """
    if subject is not None:
        if isinstance(subject, FitProfile):
            profile = subject if profile is None else profile
            source = source or "profile"
        elif isinstance(subject, dict) and "spans" in subject:
            spans = subject["spans"] if spans is None else spans
            source = source or "flight"
        elif isinstance(subject, dict) and "traceEvents" in subject:
            from cycloneml_tpu.observe.export import spans_from_chrome_trace
            spans = (spans_from_chrome_trace(subject)
                     if spans is None else spans)
            source = source or "trace"
        elif hasattr(subject, "snapshot"):          # a Tracer
            spans = subject.snapshot() if spans is None else spans
            source = source or "trace"
        else:                                       # a span sequence
            spans = list(subject) if spans is None else spans
            source = source or "trace"
    spans = list(spans) if spans is not None else None
    if profile is None and spans is not None:
        profile = FitProfile.from_spans(spans)

    cfg = DoctorConfig.from_conf(conf) if conf is not None else DoctorConfig()

    skew_snapshot = None
    if skew is _LIVE:
        from cycloneml_tpu.observe import skew as skew_mod
        det = skew_mod.active()
        skew = det
    if skew is not None and hasattr(skew, "lane_snapshot"):
        skew_snapshot = skew.lane_snapshot()
    elif isinstance(skew, dict):
        skew_snapshot = skew

    if cache_stats is _LIVE:
        from cycloneml_tpu.oocore import shard_set_cache
        stats = shard_set_cache().stats()
        # an untouched cache is not evidence of anything
        cache_stats = stats if (stats.get("hits", 0)
                                or stats.get("misses", 0)) else None

    findings: List[Finding] = []
    findings += _rule_roofline(profile, cfg)
    findings += _rule_recompile(spans, cfg)
    findings += _rule_transfer_stall(spans, profile, cfg)
    findings += _rule_straggler(spans, skew_snapshot, cfg)
    findings += _rule_underlap(spans, cfg)
    findings += _rule_serving(serving_stats, cfg)
    findings += _rule_precision(profile, cfg)
    findings += _rule_cache(cache_stats, cfg)
    findings += _rule_faults(profile, spans, cfg)

    findings.sort(key=lambda f: (-_SEVERITY_RANK.get(f.severity, 0),
                                 -f.score, f.kind))
    inputs = [name for name, present in (
        ("cache", cache_stats is not None and cache_stats is not _LIVE),
        ("profile", profile is not None),
        ("serving", bool(serving_stats)),
        ("skew", skew_snapshot is not None),
        ("spans", spans is not None),
    ) if present]
    return DiagnosisReport(source=source or "unknown",
                           n_spans=len(spans) if spans is not None else 0,
                           inputs=inputs, findings=findings)
