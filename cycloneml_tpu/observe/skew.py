"""Online straggler/skew detection over per-lane step times.

The reference's DAGScheduler decides speculation from per-task timing skew
(PAPER.md layer 3a); the TPU analog has no per-task granularity — one SPMD
dispatch is the whole mesh — but it DOES have repeating per-lane work whose
times are separable on the host: out-of-core shard staging (one lane per
shard slot), serving model lanes (one dispatch per lane), and per-worker
heartbeat round trips. This module watches those durations online:

- ``observe(group, position, seconds)`` feeds one sample. Instrumented
  sites: ``oocore/stream.py`` (group ``oocore.stage``, position
  ``shard<i>``), ``serving/batcher.py`` (group ``serving.dispatch``,
  position = lane name), ``parallel/resilience.py``
  (``HeartbeatReceiver.note_rtt`` — group ``heartbeat.rtt``, position =
  worker id: the MASTER-side lane fed by each worker's reported round
  trip over the extended heartbeat wire, so every worker's samples land
  in ONE detector and cross-host RTT skew is a real cross-lane
  comparison), and ``collectives._instrument_dispatch`` (group
  ``collectives.step``, position = program name — SLO-only, see below;
  compile-paying first dispatches excluded).
- Detection is rolling **median + MAD** across a group's positions: a
  position whose rolling median exceeds the group median by
  ``madFactor`` × MAD AND ``relFactor`` × median is a straggler. Both
  conditions must hold: MAD alone fires on microscopic jitter when the
  group is tight (MAD → 0), the relative factor alone misses skew on top
  of a wide spread. The verdict LATCHES — one ``StragglerDetected`` event
  per episode, not one per sample — and unlatches when the lane recovers.
- Groups in :data:`STRAGGLER_GROUPS` get cross-lane comparison; every
  group additionally gets an SLO check (``cyclone.telemetry.slo.*`` —
  0 disables): a sample over target fires ONE latched ``SloBreach`` (and a
  flight-recorder dump) until a sample comes back under target.
  ``collectives.step`` positions are program names — comparing different
  programs' times against each other is meaningless, so that group is
  SLO-only by construction.

Events go to the listener bus (status store ``skew`` list →
``/api/v1/skew`` → the web UI table, replayable from the journal) and to
subscribers: ``MeshSupervisor.attach_skew`` records stragglers so the
elastic scheduler (ROADMAP item 4) can re-dispatch a slow lane's work —
detection lands here, mitigation plugs into the subscription.

Disabled discipline: ``skew.observe`` is one module-global read when no
detector is installed (the ``faults.inject`` pattern); the context
installs one by default (``cyclone.telemetry.skew.enabled``).
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: groups whose positions are comparable lanes (cross-lane straggler
#: detection applies); everything else is SLO-only. ``heartbeat.rtt``
#: earned its place back (PR 12 removed it): the lanes are now fed
#: MASTER-side — each worker reports its measured round trip over the
#: extended heartbeat wire and ``HeartbeatReceiver.note_rtt`` lands
#: every worker's samples in the receiver process's ONE detector, so
#: the cross-worker comparison is structurally live (the sender-side
#: sample it replaces saw only its own lane).
#: ``fit.lane`` joined with the elastic re-dispatch (ISSUE 15): stacked/
#: CV tuning lanes (one position per grid point, sampled once per fold/
#: split) — a grid point whose fit time separates from the grid's median
#: latches, and the speculation layer re-dispatches its next lane work
STRAGGLER_GROUPS = frozenset({"oocore.stage", "serving.dispatch",
                              "heartbeat.rtt", "fit.lane"})

#: bound on distinct positions tracked per group — a pathological caller
#: (unbounded lane names) degrades to ignoring NEW lanes, never to
#: unbounded memory
MAX_POSITIONS_PER_GROUP = 256

#: shard indices fold into this many oocore lanes (``shard<i % N>``): skew
#: detection needs repeated samples per lane, and a 10k-shard epoch would
#: otherwise give every lane one sample per epoch and the detector none
OOCORE_SKEW_LANES = 64

MAX_KEPT_EVENTS = 64


class SkewDetector:
    """Rolling per-(group, position) duration windows + online skew/SLO
    verdicts. Thread-safe; event emission happens outside the lock."""

    def __init__(self, bus=None, window: int = 64, min_samples: int = 8,
                 mad_factor: float = 4.0, rel_factor: float = 1.5,
                 min_gap_s: float = 0.010,
                 slo_s: Optional[Dict[str, float]] = None, registry=None):
        self.bus = bus
        self.registry = registry
        self.window = max(int(window), 4)
        self.min_samples = max(int(min_samples), 2)
        self.mad_factor = float(mad_factor)
        self.rel_factor = float(rel_factor)
        # absolute-gap floor: at millisecond scale, benign jitter easily
        # exceeds any RELATIVE factor — a lane only convicts when it is
        # also materially slower in absolute terms (mitigation below this
        # gap could never pay for itself anyway)
        self.min_gap_s = float(min_gap_s)
        self._slo = dict(slo_s or {})
        self._lock = threading.Lock()
        self._samples: Dict[str, Dict[str, deque]] = {}
        # cached rolling median per (group -> position), invalidated only
        # for the lane that just received a sample: one O(W log W) median
        # for that lane + one O(P log P) group median/MAD per observe,
        # NOT a full O(P·W log W) recomputation
        self._medians: Dict[str, Dict[str, float]] = {}
        self._flagged: set = set()          # latched (group, position)
        self._slo_breached: set = set()     # latched (group, position)
        self._subs: List[Callable[[Any], None]] = []
        self._events: List[Any] = []        # bounded recent-event record

    @classmethod
    def from_conf(cls, conf, bus=None, registry=None) -> "SkewDetector":
        from cycloneml_tpu.conf import (
            SKEW_MAD_FACTOR, SKEW_MIN_GAP_MS, SKEW_MIN_SAMPLES,
            SKEW_REL_FACTOR, SKEW_WINDOW, SLO_SERVING_MS, SLO_STEP_MS,
        )
        slo: Dict[str, float] = {}
        step_ms = float(conf.get(SLO_STEP_MS))
        if step_ms > 0:
            slo["collectives.step"] = step_ms / 1e3
        serving_ms = float(conf.get(SLO_SERVING_MS))
        if serving_ms > 0:
            slo["serving.dispatch"] = serving_ms / 1e3
        return cls(bus=bus, registry=registry,
                   window=conf.get(SKEW_WINDOW),
                   min_samples=conf.get(SKEW_MIN_SAMPLES),
                   mad_factor=conf.get(SKEW_MAD_FACTOR),
                   rel_factor=conf.get(SKEW_REL_FACTOR),
                   min_gap_s=conf.get(SKEW_MIN_GAP_MS) / 1e3, slo_s=slo)

    # -- subscription (MeshSupervisor / future elastic scheduler) ------------
    def subscribe(self, fn: Callable[[Any], None]) -> None:
        with self._lock:
            self._subs.append(fn)

    # -- feeding ---------------------------------------------------------------
    def observe(self, group: str, position: str, seconds: float) -> None:
        """One duration sample; fires latched events when a verdict
        flips. Cheap by construction: ONE median over the sampled lane's
        ``window`` plus one median/MAD over the cached per-lane medians —
        never a full recomputation of every lane's window."""
        fire: List[Any] = []
        with self._lock:
            positions = self._samples.setdefault(group, {})
            dq = positions.get(position)
            if dq is None:
                if len(positions) >= MAX_POSITIONS_PER_GROUP:
                    return
                dq = positions[position] = deque(maxlen=self.window)
            dq.append(float(seconds))
            if group in STRAGGLER_GROUPS and len(dq) >= self.min_samples:
                self._medians.setdefault(group, {})[position] = \
                    statistics.median(dq)
            self._check_slo(group, position, float(seconds), fire)
            if group in STRAGGLER_GROUPS:
                self._check_straggler(group, position, fire)
        for ev in fire:
            self._emit(ev)

    def _check_slo(self, group: str, position: str, seconds: float,
                   fire: List[Any]) -> None:
        target = self._slo.get(group)
        if not target:
            return
        key = (group, position)
        if seconds > target:
            if key not in self._slo_breached:
                self._slo_breached.add(key)
                from cycloneml_tpu.util.events import SloBreach
                fire.append(SloBreach(group=group, position=position,
                                      observed_s=seconds, target_s=target))
        else:
            self._slo_breached.discard(key)   # recovered: re-arm the latch

    def _check_straggler(self, group: str, position: str,
                         fire: List[Any]) -> None:
        # cached per-lane medians (only the sampled lane was recomputed)
        eligible = self._medians.get(group, {})
        if len(eligible) < 2 or position not in eligible:
            return
        meds = list(eligible.values())
        med = statistics.median(meds)
        mad = statistics.median([abs(m - med) for m in meds])
        mine = eligible[position]
        is_straggler = (mine > med + self.mad_factor * mad
                        and mine > self.rel_factor * med and med > 0
                        and mine - med > self.min_gap_s)
        key = (group, position)
        if is_straggler:
            if key not in self._flagged:
                self._flagged.add(key)
                from cycloneml_tpu.util.events import StragglerDetected
                fire.append(StragglerDetected(
                    group=group, position=position, observed_s=mine,
                    median_s=med, mad_s=mad,
                    n_samples=len(self._samples[group][position])))
        else:
            self._flagged.discard(key)        # recovered: re-arm the latch

    # -- emission (outside the lock) -------------------------------------------
    def _emit(self, ev) -> None:
        from cycloneml_tpu.util.events import SloBreach, StragglerDetected
        with self._lock:
            self._events.append(ev)
            while len(self._events) > MAX_KEPT_EVENTS:
                self._events.pop(0)
            subs = list(self._subs)
        if isinstance(ev, StragglerDetected):
            logger.warning(
                "skew: straggler %s in group %s — rolling median %.4fs vs "
                "group median %.4fs (MAD %.4fs, %d samples)",
                ev.position, ev.group, ev.observed_s, ev.median_s, ev.mad_s,
                ev.n_samples)
        elif isinstance(ev, SloBreach):
            logger.warning("skew: SLO breach in %s (%s): %.4fs > %.4fs",
                           ev.group, ev.position, ev.observed_s, ev.target_s)
            from cycloneml_tpu.observe import flight
            flight.trigger("slo.breach", group=ev.group,
                           position=ev.position, observed_s=ev.observed_s)
        reg = self.registry
        if reg is not None:
            try:
                reg.counter(f"skew.{type(ev).__name__}").inc()
            except Exception:
                pass  # a broken metrics bridge must not kill the step
        if self.bus is not None:
            try:
                self.bus.post(ev)
            except Exception:
                pass  # a stopped bus must not fail the observing site
        for fn in subs:
            try:
                fn(ev)
            except Exception:
                logger.exception("skew subscriber failed")

    # -- introspection ---------------------------------------------------------
    def stragglers(self) -> List[Tuple[str, str]]:
        """Currently latched (group, position) straggler verdicts."""
        with self._lock:
            return sorted(self._flagged)

    def events(self) -> List[Any]:
        with self._lock:
            return list(self._events)

    def lane_snapshot(self, group: Optional[str] = None
                      ) -> Dict[str, Dict[str, Any]]:
        """Per-lane medians/MAD/verdicts for the doctor, gathered in ONE
        lock acquisition so a scrape racing ``observe`` can never pair
        one lane's fresh median with another lane's stale latch (the
        torn-rollup discipline batcher stats follow). Keys and lanes are
        sorted; verdict flags are the LATCHED sets, exactly what
        :meth:`stragglers`/:meth:`slo_breaches` report."""
        out: Dict[str, Dict[str, Any]] = {}
        with self._lock:
            groups = ([group] if group is not None
                      else sorted(self._samples))
            for g in groups:
                positions = self._samples.get(g, {})
                meds = self._medians.get(g, {})
                med_values = [meds[p] for p in sorted(meds)]
                group_med = (statistics.median(med_values)
                             if med_values else 0.0)
                mad = (statistics.median(
                    [abs(v - group_med) for v in med_values])
                    if med_values else 0.0)
                lanes = {}
                for pos in sorted(positions):
                    lanes[pos] = {
                        "n": len(positions[pos]),
                        "medianS": meds.get(pos),
                        "straggler": (g, pos) in self._flagged,
                        "sloBreached": (g, pos) in self._slo_breached,
                    }
                out[g] = {"groupMedianS": group_med, "madS": mad,
                          "lanes": lanes}
        return out

    def straggler_pressure(self, groups=None) -> int:
        """Count of currently latched straggler verdicts, optionally
        restricted to ``groups`` — the autoscaler's training-pressure
        signal (elastic/autoscale.py samples it each tick; latching
        means pressure holds until the lane actually recovers, so the
        hysteresis streak measures sustained trouble, not one spike)."""
        with self._lock:
            if groups is None:
                return len(self._flagged)
            wanted = set(groups)
            return sum(1 for g, _ in self._flagged if g in wanted)

    def slo_breaches(self, group: Optional[str] = None
                     ) -> List[Tuple[str, str]]:
        """Currently latched (group, position) SLO breaches, sorted;
        filter by ``group`` (e.g. ``collectives.step`` for the
        autoscaler's step-time leg)."""
        with self._lock:
            keys = sorted(self._slo_breached)
        if group is None:
            return keys
        return [k for k in keys if k[0] == group]

    def reset_position(self, group: str, position: str) -> None:
        """Forget ONE lane: samples, cached median and latched verdicts.
        The liveness re-arm hook (MeshSupervisor.readmit) — a worker
        returning on scale-up starts a fresh RTT lane instead of
        inheriting samples (and possibly a latched verdict) from its
        pre-departure placement."""
        key = (group, position)
        with self._lock:
            self._samples.get(group, {}).pop(position, None)
            self._medians.get(group, {}).pop(position, None)
            self._flagged.discard(key)
            self._slo_breached.discard(key)

    def reset(self, group: Optional[str] = None) -> None:
        with self._lock:
            if group is None:
                self._samples.clear()
                self._medians.clear()
                self._flagged.clear()
                self._slo_breached.clear()
            else:
                self._samples.pop(group, None)
                self._medians.pop(group, None)
                self._flagged = {k for k in self._flagged
                                 if k[0] != group}
                self._slo_breached = {k for k in self._slo_breached
                                      if k[0] != group}


# -- process-global switch (the faults._active / tracing._tracer pattern) -----
_lock = threading.Lock()
_detector: Optional[SkewDetector] = None


def install(detector: SkewDetector) -> Optional[SkewDetector]:
    """Install the process-global detector; returns the PREVIOUS one (the
    caller restores it when replacing temporarily, e.g. tests)."""
    global _detector
    with _lock:
        prev, _detector = _detector, detector
        return prev


def uninstall(detector: Optional[SkewDetector] = None) -> None:
    global _detector
    with _lock:
        if detector is None or _detector is detector:
            _detector = None


def active() -> Optional[SkewDetector]:
    return _detector


def observe(group: str, position: str, seconds: float) -> None:
    """Instrumentation-site entry: one module-global read when no detector
    is installed."""
    det = _detector
    if det is not None:
        det.observe(group, position, seconds)


def timed_observe(group: str, position: str):
    """Context manager timing a block into :func:`observe`; the shared
    no-op when no detector is installed."""
    if _detector is None:
        return _NOOP_TIMER
    return _Timer(group, position)


class _Timer:
    __slots__ = ("_group", "_position", "_t0")

    def __init__(self, group: str, position: str):
        self._group = group
        self._position = position

    def __enter__(self) -> "_Timer":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        if exc[0] is None:  # a failed lane's time is not a skew sample
            observe(self._group, self._position,
                    time.perf_counter() - self._t0)
        return False


class _NoopTimer:
    __slots__ = ()

    def __enter__(self) -> "_NoopTimer":
        return self

    def __exit__(self, *exc) -> bool:
        return False


_NOOP_TIMER = _NoopTimer()
