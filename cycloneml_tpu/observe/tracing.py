"""Step-level tracing: hierarchical spans over the trace→compile→dispatch→
collective path.

The framework's performance story lives in one narrow boundary (estimator →
block aggregator → ``tree_aggregate`` → psum), yet tracing-JIT systems hide
exactly where a fit's wall clock goes: staging costs (trace + XLA compile)
happen once, silently, and dominate small fits (Frostig et al., SysML 2018),
while steady-state cost is per-dispatch latency plus device→host readbacks.
This module makes those phases visible the way Dapper makes RPC trees
visible (Sigelman et al. 2010, PAPERS.md): every instrumented boundary opens
a :class:`Span` (kind + name + wall window + attrs) nested under the
current thread's open span, and the process-global :class:`Tracer` collects
them for per-fit :class:`~cycloneml_tpu.observe.profile.FitProfile`
aggregation and Chrome-trace export
(:mod:`cycloneml_tpu.observe.export` — loads in Perfetto / chrome://tracing).

Span kind taxonomy (docs/observability.md has the full catalogue):

=============  ==============================================================
kind           opened around
=============  ==============================================================
``job``        a ``ctx.run_job`` bracket (one estimator ``fit``)
``dispatch``   one optimizer-level device dispatch (loss eval, fused line
               search, L-BFGS chunk, GD step); ``evals`` attr carries the
               loss/grad evaluations the dispatch performed
``collective`` one dispatch of a ``tree_aggregate`` psum program
``compile``    the FIRST dispatch of a freshly built program — the call that
               pays tracing + XLA compilation (program-cache misses)
``transfer``   a blocking ``jax.device_get`` readback; ``bytes`` attr
``checkpoint`` ``TrainingCheckpointer`` save / commit / restore
``rebuild``    a ``MeshSupervisor.recover`` mesh rebuild
``instant``    zero-duration annotations: injected faults, step retries,
               program-cache hits/misses
``counter``    a Perfetto counter sample (Chrome-trace ``"C"`` phase):
               ``hbm.bytes_in_use`` / ``hbm.predicted_peak_bytes`` /
               ``flops.cumulative`` timelines from ``observe.costs``
=============  ==============================================================

Off by default with near-zero disabled cost: every instrumentation site
performs ONE module-global read (the same pattern ``faults.inject`` uses)
and :func:`span` returns a shared no-op context manager — no allocation, no
clock read. Enabled via :func:`enable` (``CycloneContext`` does this when
``cyclone.trace.enabled`` / ``CYCLONE_TRACE`` is set).

Tracer-awareness contract: instrumentation sites that can be reached at
JAX trace time (a program inlined into a larger jitted program) must NOT
open spans there — a span records host wall clock, which is meaningless
inside tracing and would bake host work into the program (see
``collectives._instrument_dispatch`` and the graftlint JX001 fixture
``tests/fixtures/graftlint/jx001_tracing_pass.py``).
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "Span", "Tracer", "enable", "disable", "active", "full_active",
    "install_if_absent", "span", "instant", "counter", "current_span_id",
    "nbytes",
]


class Span:
    """One closed (or instant) trace span. ``t0``/``t1`` are
    ``time.perf_counter`` readings; the owning tracer anchors them to wall
    time for export."""

    __slots__ = ("span_id", "parent_id", "kind", "name", "t0", "t1", "tid",
                 "attrs")

    def __init__(self, span_id: str, parent_id: str, kind: str, name: str,
                 tid: int, attrs: Dict[str, Any]):
        self.span_id = span_id
        self.parent_id = parent_id
        self.kind = kind
        self.name = name
        self.t0 = 0.0
        self.t1 = 0.0
        self.tid = tid
        self.attrs = attrs

    @property
    def duration_s(self) -> float:
        return max(self.t1 - self.t0, 0.0)

    def __repr__(self) -> str:  # debugging/test readability only
        return (f"Span({self.kind}:{self.name} id={self.span_id} "
                f"parent={self.parent_id or '-'} dur={self.duration_s:.6f})")


class _NoopSpan:
    """Shared do-nothing span: the entire disabled-tracing API surface."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def annotate(self, **attrs) -> None:
        pass

    def annotate_bytes(self, tree) -> None:
        # no nbytes walk on the disabled path
        pass

    @property
    def span_id(self) -> str:
        return ""


NOOP_SPAN = _NoopSpan()


class _LiveSpan:
    """Context manager recording one span into its tracer."""

    __slots__ = ("_tracer", "span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self.span = span

    def __enter__(self) -> "_LiveSpan":
        stack = self._tracer._stack()
        if stack and not self.span.parent_id:
            self.span.parent_id = stack[-1].span_id
        elif not stack and not self.span.parent_id:
            # root span in a process that adopted a distributed trace
            # context: parent to the submitting process's span (a
            # host-qualified id, or "" when no context was adopted)
            self.span.parent_id = self._tracer.parent_span_id
        stack.append(self.span)
        self.span.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.span.t1 = time.perf_counter()
        stack = self._tracer._stack()
        if stack and stack[-1] is self.span:
            stack.pop()
        self._tracer._record(self.span)
        return False

    def annotate(self, **attrs) -> None:
        """Attach attributes (usable during AND after the ``with`` block —
        the recorded span holds the same attrs dict)."""
        self.span.attrs.update(attrs)

    def annotate_bytes(self, tree) -> None:
        self.span.attrs["bytes"] = nbytes(tree)

    @property
    def span_id(self) -> str:
        return self.span.span_id


class Tracer:
    """Collects spans process-wide; thread-safe.

    Context propagation is per-thread (a thread-local span stack), so
    nested fits and concurrent fits in different threads each get a correct
    parent chain. Cross-thread propagation is explicit: capture
    :meth:`current_span_id` in the submitting thread and pass it as
    ``parent`` to :meth:`span` in the worker. Cross-PROCESS propagation is
    the trace context (:meth:`set_trace_context`): ``trace_id`` names the
    distributed trace this process participates in and ``parent_span_id``
    (a host-qualified id from the submitting process) becomes the parent
    of every root span recorded here — the Dapper join
    (``observe/collect.py`` merges the per-process traces).

    The buffer is a RING: past ``max_spans`` the OLDEST span is dropped
    (and counted in ``dropped``), so a long job always retains its most
    recent window — the flight-recorder semantics. Buffer positions are
    monotonic sequence numbers (``mark``/``snapshot(since)``/``drain``
    speak seq, not list index), so readers see exact once-each delivery
    across wrap-arounds.

    ``registry`` (a :class:`~cycloneml_tpu.util.metrics.MetricsRegistry`)
    bridges spans into the metrics system: every closed span updates
    ``span.<kind>`` (a Timer) and every instant bumps ``trace.<name>`` (a
    Counter) — visible through the Prometheus endpoint.
    """

    #: False on the flight-recorder tracer (observe/flight.py): sites that
    #: pay real money when traced (XLA cost harvest, budget analysis,
    #: per-job profile rollups) run only under a FULL tracer — the flight
    #: ring records spans and nothing else.
    full = True

    def __init__(self, max_spans: int = 100_000, registry=None):
        self.max_spans = max(1, int(max_spans))
        self.registry = registry
        # wall anchor: perf_counter offsets map onto real time for export
        self.epoch_wall = time.time()
        self.epoch_perf = time.perf_counter()
        self._spans: "collections.deque[Span]" = collections.deque()
        self._base = 0          # seq of the oldest span still in the ring
        self.dropped = 0        # ring overflow: oldest-dropped count
        self.trace_id = uuid.uuid4().hex[:16]
        self.parent_span_id = ""   # remote parent for root spans ("" = none)
        self._ids = itertools.count(1)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._tid_names: Dict[int, str] = {}

    @property
    def wall_base(self) -> float:
        """Offset mapping a span's ``perf_counter`` reading onto wall
        time: ``wall = wall_base + t``."""
        return self.epoch_wall - self.epoch_perf

    def set_trace_context(self, trace_id: str, parent_span_id: str = ""
                          ) -> None:
        """Adopt a distributed trace context (the deploy launch env's
        ``CYCLONE_TRACE_ID`` / ``CYCLONE_TRACE_PARENT``): subsequent ROOT
        spans parent to ``parent_span_id`` — a host-qualified id
        (``label/sN``) minted by the submitting process."""
        if trace_id:
            self.trace_id = str(trace_id)
        self.parent_span_id = str(parent_span_id or "")

    def thread_names(self) -> Dict[int, str]:
        """tid -> thread name for every thread that recorded a span (the
        Chrome-trace ``thread_name`` metadata source)."""
        with self._lock:
            return dict(self._tid_names)

    @property
    def spans_dropped(self) -> int:
        """Ring-overflow drop count as a first-class telemetry reading
        (the drop-counter rollup in ``TelemetryStatsUpdated`` and
        ``/api/v1/telemetry`` reads this; previously visible only in the
        trace export header)."""
        with self._lock:
            return self.dropped

    # -- context ---------------------------------------------------------------
    def _stack(self) -> List[Span]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def current_span_id(self) -> str:
        stack = self._stack()
        return stack[-1].span_id if stack else ""

    # -- recording -------------------------------------------------------------
    def span(self, kind: str, name: str = "", parent: str = "",
             **attrs) -> _LiveSpan:
        s = Span(f"s{next(self._ids)}", parent, kind, name or kind,
                 threading.get_ident(), attrs)
        return _LiveSpan(self, s)

    def instant(self, name: str, **attrs) -> None:
        """Zero-duration annotation under the current span (faults,
        retries, cache hits/misses)."""
        s = Span(f"s{next(self._ids)}", self.current_span_id(), "instant",
                 name, threading.get_ident(), attrs)
        s.t0 = s.t1 = time.perf_counter()
        self._record(s)

    def record_span(self, kind: str, name: str = "", t0: float = 0.0,
                    t1: float = 0.0, parent: str = "", **attrs) -> Span:
        """Record an already-timed span retroactively (``t0``/``t1`` are
        ``perf_counter`` readings). For producers whose phases span
        threads — the serving batcher times a request's queue phase on
        the submitting thread and its dispatch on the worker, then
        records one request span after the fact; a context-manager span
        could not bracket that lifetime."""
        s = Span(f"s{next(self._ids)}", parent, kind, name or kind,
                 threading.get_ident(), attrs)
        s.t0, s.t1 = t0, t1
        self._record(s)
        return s

    def counter(self, name: str, value: float) -> None:
        """One sample of a Perfetto counter track (exported as a
        Chrome-trace ``"C"``-phase event): device-memory / cumulative-FLOP
        timelines render as graphs next to the spans."""
        s = Span(f"s{next(self._ids)}", "", "counter", name,
                 threading.get_ident(), {"value": float(value)})
        s.t0 = s.t1 = time.perf_counter()
        self._record(s)

    def _record(self, s: Span) -> None:
        with self._lock:
            self._spans.append(s)
            while len(self._spans) > self.max_spans:
                # oldest-dropped: a bounded job keeps its RECENT window
                # (the flight-recorder contract); the count is surfaced in
                # the export header and FitProfile.spans_dropped
                self._spans.popleft()
                self._base += 1
                self.dropped += 1
            if s.tid not in self._tid_names:
                # _record always runs on the thread whose ident stamps the
                # span (context-manager exit / instant / retroactive
                # record_span all execute on the recording thread)
                self._tid_names[s.tid] = threading.current_thread().name
        reg = self.registry
        if reg is not None:
            try:
                if s.kind == "instant":
                    reg.counter(f"trace.{s.name}").inc()
                elif s.kind != "counter":
                    # counter samples have live gauges on the metrics side
                    # already (costs.register_memory_gauges) — a zero-
                    # duration timer entry would only skew span.* stats
                    reg.timer(f"span.{s.kind}").update(s.duration_s)
            except Exception:
                pass  # a broken metrics bridge must not kill the step

    # -- reading ---------------------------------------------------------------
    def _window(self, since: int) -> List[Span]:
        # callers hold self._lock
        start = max(0, since - self._base)
        if start <= 0:
            return list(self._spans)
        if start >= len(self._spans):
            return []
        return list(itertools.islice(self._spans, start, None))

    def snapshot(self, since: int = 0) -> List[Span]:
        """Spans recorded at sequence position >= ``since`` that are still
        in the ring (a stale ``since`` below the ring floor returns the
        whole surviving window)."""
        with self._lock:
            return self._window(since)

    def mark(self) -> int:
        """Current buffer position (monotonic sequence number — survives
        ring wrap-around) — pass to :meth:`profile_for` as ``since`` so a
        per-job rollup scans only the spans that job recorded, not the
        whole process history."""
        with self._lock:
            return self._base + len(self._spans)

    def drain(self, since: int) -> Tuple[List[Span], int]:
        """Atomic ``(snapshot(since), mark())``: the spans at position >=
        ``since`` plus the position to resume from. The one-lock read is
        what makes a collector loop exact — a concurrent producer between
        a separate ``mark()`` and ``snapshot()`` would be delivered twice.
        Spans are never removed; the returned mark is the cursor."""
        with self._lock:
            return self._window(since), self._base + len(self._spans)

    def clear(self) -> None:
        with self._lock:
            # sequence positions stay monotonic: a mark taken before
            # clear() yields only post-clear spans, never a replay
            self._base += len(self._spans)
            self._spans.clear()
            self.dropped = 0

    def profile_for(self, root_id: Optional[str] = None, since: int = 0):
        """A :class:`FitProfile` over the spans descending from ``root_id``
        (or every recorded span when None), starting at buffer position
        ``since`` (a :meth:`mark` taken before the root span opened)."""
        from cycloneml_tpu.observe.profile import FitProfile
        with self._lock:
            spans = self._window(since)
            dropped = self.dropped
        prof = FitProfile.from_spans(spans, root_id=root_id)
        prof.spans_dropped = dropped
        return prof

    def export_chrome_trace(self, path: str) -> str:
        from cycloneml_tpu.observe.export import export_chrome_trace
        return export_chrome_trace(self, path)


# -- process-global switch -----------------------------------------------------
# The disabled hot path is ONE read of this module global (the same
# discipline as faults._active); no lock, no allocation.
_lock = threading.Lock()
_tracer: Optional[Tracer] = None


def enable(max_spans: int = 100_000, registry=None) -> Tracer:
    """Install (or return the already-installed) process-global FULL
    tracer. An installed flight-recorder ring (``Tracer.full`` False) is
    UPGRADED: replaced by a fresh full tracer — full tracing supersedes
    the always-on ring, whose recent window is discarded (it exists to
    cover the runs that did not pay for this)."""
    global _tracer
    with _lock:
        if _tracer is None or not _tracer.full:
            _tracer = Tracer(max_spans=max_spans, registry=registry)
        return _tracer


def install_if_absent(tracer: Tracer) -> Tracer:
    """Install ``tracer`` only when no tracer is active; returns whichever
    tracer is installed afterwards (observe/flight.py uses this so the
    ring never displaces a full tracer)."""
    global _tracer
    with _lock:
        if _tracer is None:
            _tracer = tracer
        return _tracer


def disable() -> Optional[Tracer]:
    """Uninstall and return the global tracer (None when already off). The
    returned tracer stays readable — export after disabling is fine."""
    global _tracer
    with _lock:
        t, _tracer = _tracer, None
        return t


def active() -> Optional[Tracer]:
    return _tracer


def full_active() -> Optional[Tracer]:
    """The active tracer ONLY when it is a full one — the gate for sites
    whose traced path costs real work (XLA cost harvest, budget checks,
    per-job profile rollups). Under the flight-recorder ring this returns
    None: flight mode records spans and nothing else, which is what keeps
    always-on cheap."""
    t = _tracer
    if t is None or not t.full:
        return None
    return t


def span(kind: str, name: str = "", **attrs):
    """Open a span under the current thread's context; a shared no-op when
    tracing is disabled (one global read, zero allocation)."""
    t = _tracer
    if t is None:
        return NOOP_SPAN
    return t.span(kind, name, **attrs)


def instant(name: str, **attrs) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **attrs)


def counter(name: str, value: float) -> None:
    t = _tracer
    if t is not None:
        t.counter(name, value)


def current_span_id() -> str:
    t = _tracer
    if t is None:
        return ""
    return t.current_span_id()


def nbytes(tree: Any) -> int:
    """Byte size of a host pytree (dicts/lists/tuples of arrays+scalars) —
    used to annotate ``transfer`` spans after a ``jax.device_get``."""
    if isinstance(tree, dict):
        return sum(nbytes(v) for v in tree.values())
    if isinstance(tree, (list, tuple)):
        return sum(nbytes(v) for v in tree)
    n = getattr(tree, "nbytes", None)
    if n is not None:
        return int(n)
    return 8 if isinstance(tree, (int, float, complex, bool)) else 0
