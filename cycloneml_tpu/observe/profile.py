"""Per-fit profile aggregation over recorded spans.

The TPU-native analog of the reference's ``TaskMetrics`` rollup (ref:
executor/TaskMetrics.scala aggregated per stage by AppStatusListener): one
:class:`FitProfile` summarises where a fit's wall clock went — staging
(trace + XLA compile) vs steady-state dispatch vs device→host transfer —
plus the reliability counters a chaos run cares about (faults, retries,
mesh rebuilds). ``CycloneContext.run_job`` computes one per job when
tracing is enabled and posts it as a ``FitProfileCompleted`` event, so the
status store / web UI / history replay all carry it.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence


@dataclass
class FitProfile:
    """Aggregate of one fit's spans (see tracing.py for the kind taxonomy).

    ``eval_count`` sums the ``evals`` attr on dispatch spans — it matches
    the optimizer's ``n_evals`` ledger (``bench.py``'s "loss/grad evals")
    the same way ``dispatch_count`` matches ``n_dispatches``.
    ``steady_seconds`` is dispatch time excluding dispatches that paid a
    compile (their wall time is staging, not steady state).
    ``n_models`` is the model-axis width of the fit's dispatches (stacked
    fits — ``n_models`` > 1 — amortize every compile in this profile over
    that many models; see docs/multi-model.md).

    The cost block comes from XLA's own accounting (``observe.costs``;
    docs/observability.md has the units + backend availability matrix):
    ``programs`` holds one entry per program-cache identity the fit
    dispatched (executions × what XLA reports per execution);
    ``total_flops`` / ``total_bytes_accessed`` are the mesh-wide totals;
    ``hbm_peak_bytes`` is the largest per-device footprint (arguments +
    outputs + temporaries + generated code) of any dispatched program —
    the OOM-relevant number; ``achieved_flops`` is the steady-state
    executions' FLOPs over those same executions' dispatch time (staging
    executions excluded from both sides); ``arithmetic_intensity`` is
    FLOPs per byte
    accessed; ``roofline_fraction`` scores achieved FLOP/s against the
    per-backend roofline ``min(peak_flops, peak_bw × intensity)`` (Williams
    et al. 2009). Every cost field is ``None`` — explicitly "unavailable" —
    when the backend (or an untraced run) cannot report it;
    ``cost_availability`` summarizes (``full`` / ``flops_only`` /
    ``unavailable``) and ``memory_stats_available`` records whether live
    ``device.memory_stats()`` telemetry existed.
    """

    job_id: int = 0
    description: str = ""
    wall_seconds: float = 0.0
    compile_count: int = 0
    compile_seconds: float = 0.0
    cache_hits: int = 0
    cache_misses: int = 0
    dispatch_count: int = 0
    dispatch_seconds: float = 0.0
    steady_seconds: float = 0.0
    eval_count: int = 0
    collective_count: int = 0
    collective_seconds: float = 0.0
    transfer_count: int = 0
    transfer_seconds: float = 0.0
    transfer_bytes: int = 0
    checkpoint_saves: int = 0
    checkpoint_restores: int = 0
    checkpoint_seconds: float = 0.0
    retries: int = 0
    rebuilds: int = 0
    faults_injected: int = 0
    n_models: int = 1
    # fp8 tier fallbacks during this fit: the envelope probe (or a
    # non-finite fp8 solution) re-routed the fit to bf16 storage — see
    # docs/mixed-precision.md and the PrecisionFallback event
    fp8_fallbacks: int = 0
    # ring overflow during this tracer's lifetime (tracing.Tracer.dropped,
    # oldest-dropped): > 0 means the rollup undercounts — the profile saw
    # only the surviving window
    spans_dropped: int = 0
    # -- XLA cost & HBM accounting (None = unavailable on this backend) --
    total_flops: Optional[float] = None
    total_bytes_accessed: Optional[float] = None
    hbm_peak_bytes: Optional[int] = None
    hbm_argument_bytes: Optional[int] = None
    hbm_output_bytes: Optional[int] = None
    hbm_temp_bytes: Optional[int] = None
    achieved_flops: Optional[float] = None
    arithmetic_intensity: Optional[float] = None
    roofline_fraction: Optional[float] = None
    n_devices: int = 0
    cost_availability: str = "unavailable"
    memory_stats_available: bool = False
    programs: Dict[str, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    # this job's usage-ledger delta (observe.attribution): what the job's
    # scope row gained between run_job entry and exit — device-seconds,
    # FLOPs, h2d bytes etc. Empty when attribution was off for the fit.
    job_usage: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FitProfile":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @classmethod
    def from_spans(cls, spans: Sequence[Any],
                   root_id: Optional[str] = None,
                   cost_lookup: Optional[Any] = None) -> "FitProfile":
        """Fold spans into a profile. With ``root_id``, only spans whose
        parent chain reaches that span (plus the root itself) count — the
        per-job scoping ``run_job`` uses. ``cost_lookup`` maps a program id
        (the ``program`` attr harvest puts on dispatch/collective spans) to
        its registered cost entry; defaults to the process-global
        ``observe.costs`` registry."""
        if root_id:
            parent = {s.span_id: s.parent_id for s in spans}
            selected: List[Any] = []
            member: Dict[str, bool] = {root_id: True}

            def in_tree(sid: str) -> bool:
                chain = []
                while sid and sid not in member:
                    chain.append(sid)
                    sid = parent.get(sid, "")
                verdict = bool(sid) and member[sid]
                for c in chain:
                    member[c] = verdict
                return verdict

            for s in spans:
                if s.span_id == root_id or in_tree(s.span_id):
                    selected.append(s)
            spans = selected

        p = cls()
        compiles: List[Any] = []
        dispatches: List[Any] = []
        for s in spans:
            dur = s.duration_s
            k = s.kind
            if k == "job":
                if root_id is None or s.span_id == root_id:
                    p.wall_seconds = max(p.wall_seconds, dur)
                    p.description = p.description or s.name
            elif k == "compile":
                p.compile_count += 1
                p.compile_seconds += dur
                compiles.append(s)
            elif k == "dispatch":
                p.dispatch_count += 1
                p.dispatch_seconds += dur
                p.eval_count += int(s.attrs.get("evals", 0))
                p.n_models = max(p.n_models,
                                 int(s.attrs.get("n_models", 1)))
                dispatches.append(s)
            elif k == "collective":
                p.collective_count += 1
                p.collective_seconds += dur
            elif k == "transfer":
                p.transfer_count += 1
                p.transfer_seconds += dur
                p.transfer_bytes += int(s.attrs.get("bytes", 0))
            elif k == "checkpoint":
                if s.name == "save":
                    p.checkpoint_saves += 1
                    p.checkpoint_seconds += dur
                elif s.name == "restore":
                    p.checkpoint_restores += 1
                    p.checkpoint_seconds += dur
            elif k == "rebuild":
                p.rebuilds += 1
            elif k == "instant":
                if s.name == "fault":
                    p.faults_injected += 1
                elif s.name == "retry":
                    p.retries += 1
                elif s.name == "cache.hit":
                    p.cache_hits += 1
                elif s.name == "cache.miss":
                    p.cache_misses += 1
                elif s.name == "precision.fallback":
                    p.fp8_fallbacks += 1
        # steady state = dispatches that did not pay a compile anywhere in
        # their subtree. A compile may nest more than one level down
        # (loss.eval dispatch → tree_aggregate collective → compile), so
        # every ANCESTOR of a compile span is staging, not steady state.
        parents = {s.span_id: s.parent_id for s in spans}
        staging = set()
        for c in compiles:
            sid = c.parent_id
            while sid and sid not in staging:
                staging.add(sid)
                sid = parents.get(sid, "")
        p.steady_seconds = sum(
            s.duration_s for s in dispatches if s.span_id not in staging)
        p._fold_costs(spans, cost_lookup, staging)
        return p

    def _fold_costs(self, spans: Sequence[Any], cost_lookup,
                    staging) -> None:
        """Join the spans' per-program execution counts onto the harvested
        XLA cost registry and derive the roofline fields.

        ``achieved_flops`` keeps numerator and denominator consistent:
        steady-state executions' FLOPs over those same spans' wall time.
        Staging executions (a compile in the span's subtree) are excluded
        from BOTH sides — counting their flops against steady time would
        inflate the rate ~2x on short fits — and the denominator is the
        cost-carrying spans' own durations, so programs dispatched outside
        any optimizer dispatch span (summary/weight-sum aggregations)
        cannot contribute flops without contributing time."""
        execs: Dict[str, int] = {}
        steady_execs: Dict[str, int] = {}
        steady_cost_seconds = all_cost_seconds = 0.0
        for s in spans:
            if s.kind in ("dispatch", "collective"):
                pid = s.attrs.get("program")
                if pid:
                    execs[pid] = execs.get(pid, 0) + 1
                    all_cost_seconds += s.duration_s
                    if s.span_id not in staging:
                        steady_execs[pid] = steady_execs.get(pid, 0) + 1
                        steady_cost_seconds += s.duration_s
        if not execs:
            return
        from cycloneml_tpu.observe import costs as _costs
        if cost_lookup is None:
            cost_lookup = _costs.lookup
        self.memory_stats_available = _costs.memory_stats_available()
        flops_total = bytes_total = steady_flops = 0.0
        any_flops = any_mem = False
        for pid, n in sorted(execs.items()):
            entry = cost_lookup(pid)
            if entry is None:
                self.programs[pid] = {"executions": n,
                                      "cost_available": False}
                continue
            entry = dict(entry)
            entry["executions"] = n
            self.programs[pid] = entry
            self.n_devices = max(self.n_devices,
                                 int(entry.get("n_devices") or 0))
            if entry.get("flops_total"):
                any_flops = True
                flops_total += entry["flops_total"] * n
                steady_flops += entry["flops_total"] * steady_execs.get(pid, 0)
            if entry.get("bytes_accessed_total"):
                bytes_total += entry["bytes_accessed_total"] * n
            peak = entry.get("peak_bytes")
            if peak is not None and (self.hbm_peak_bytes is None
                                     or peak > self.hbm_peak_bytes):
                any_mem = True
                self.hbm_peak_bytes = int(peak)
                self.hbm_argument_bytes = entry.get("argument_bytes")
                self.hbm_output_bytes = entry.get("output_bytes")
                self.hbm_temp_bytes = entry.get("temp_bytes")
        if any_flops:
            self.total_flops = flops_total
            if bytes_total:
                self.total_bytes_accessed = bytes_total
                self.arithmetic_intensity = flops_total / bytes_total
            # steady executions over steady cost-span time; a fit whose
            # every cost-carrying dispatch paid a compile falls back to
            # total work over total cost-span time (still consistent)
            if steady_flops and steady_cost_seconds > 0:
                self.achieved_flops = steady_flops / steady_cost_seconds
            elif all_cost_seconds > 0:
                self.achieved_flops = flops_total / all_cost_seconds
            peak_flops, peak_bw = _costs.backend_peaks()
            if (self.achieved_flops and peak_flops and self.n_devices
                    and self.arithmetic_intensity):
                ceiling = min(peak_flops,
                              (peak_bw or peak_flops)
                              * self.arithmetic_intensity)
                self.roofline_fraction = (
                    self.achieved_flops / self.n_devices / ceiling)
        self.cost_availability = (
            "full" if any_flops and any_mem
            else "flops_only" if any_flops
            else "unavailable")

    def phase_summary(self) -> Dict[str, float]:
        """The compile-vs-steady-state breakdown bench.py prints."""
        return {
            "compile_s": round(self.compile_seconds, 4),
            "steady_s": round(self.steady_seconds, 4),
            "transfer_s": round(self.transfer_seconds, 4),
            "checkpoint_s": round(self.checkpoint_seconds, 4),
            "wall_s": round(self.wall_seconds, 4),
        }
