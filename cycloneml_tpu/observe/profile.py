"""Per-fit profile aggregation over recorded spans.

The TPU-native analog of the reference's ``TaskMetrics`` rollup (ref:
executor/TaskMetrics.scala aggregated per stage by AppStatusListener): one
:class:`FitProfile` summarises where a fit's wall clock went — staging
(trace + XLA compile) vs steady-state dispatch vs device→host transfer —
plus the reliability counters a chaos run cares about (faults, retries,
mesh rebuilds). ``CycloneContext.run_job`` computes one per job when
tracing is enabled and posts it as a ``FitProfileCompleted`` event, so the
status store / web UI / history replay all carry it.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence


@dataclass
class FitProfile:
    """Aggregate of one fit's spans (see tracing.py for the kind taxonomy).

    ``eval_count`` sums the ``evals`` attr on dispatch spans — it matches
    the optimizer's ``n_evals`` ledger (``bench.py``'s "loss/grad evals")
    the same way ``dispatch_count`` matches ``n_dispatches``.
    ``steady_seconds`` is dispatch time excluding dispatches that paid a
    compile (their wall time is staging, not steady state).
    ``n_models`` is the model-axis width of the fit's dispatches (stacked
    fits — ``n_models`` > 1 — amortize every compile in this profile over
    that many models; see docs/multi-model.md).
    """

    job_id: int = 0
    description: str = ""
    wall_seconds: float = 0.0
    compile_count: int = 0
    compile_seconds: float = 0.0
    cache_hits: int = 0
    cache_misses: int = 0
    dispatch_count: int = 0
    dispatch_seconds: float = 0.0
    steady_seconds: float = 0.0
    eval_count: int = 0
    collective_count: int = 0
    collective_seconds: float = 0.0
    transfer_count: int = 0
    transfer_seconds: float = 0.0
    transfer_bytes: int = 0
    checkpoint_saves: int = 0
    checkpoint_restores: int = 0
    checkpoint_seconds: float = 0.0
    retries: int = 0
    rebuilds: int = 0
    faults_injected: int = 0
    n_models: int = 1

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FitProfile":
        fields = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in fields})

    @classmethod
    def from_spans(cls, spans: Sequence[Any],
                   root_id: Optional[str] = None) -> "FitProfile":
        """Fold spans into a profile. With ``root_id``, only spans whose
        parent chain reaches that span (plus the root itself) count — the
        per-job scoping ``run_job`` uses."""
        if root_id:
            parent = {s.span_id: s.parent_id for s in spans}
            selected: List[Any] = []
            member: Dict[str, bool] = {root_id: True}

            def in_tree(sid: str) -> bool:
                chain = []
                while sid and sid not in member:
                    chain.append(sid)
                    sid = parent.get(sid, "")
                verdict = bool(sid) and member[sid]
                for c in chain:
                    member[c] = verdict
                return verdict

            for s in spans:
                if s.span_id == root_id or in_tree(s.span_id):
                    selected.append(s)
            spans = selected

        p = cls()
        compiles: List[Any] = []
        dispatches: List[Any] = []
        for s in spans:
            dur = s.duration_s
            k = s.kind
            if k == "job":
                if root_id is None or s.span_id == root_id:
                    p.wall_seconds = max(p.wall_seconds, dur)
                    p.description = p.description or s.name
            elif k == "compile":
                p.compile_count += 1
                p.compile_seconds += dur
                compiles.append(s)
            elif k == "dispatch":
                p.dispatch_count += 1
                p.dispatch_seconds += dur
                p.eval_count += int(s.attrs.get("evals", 0))
                p.n_models = max(p.n_models,
                                 int(s.attrs.get("n_models", 1)))
                dispatches.append(s)
            elif k == "collective":
                p.collective_count += 1
                p.collective_seconds += dur
            elif k == "transfer":
                p.transfer_count += 1
                p.transfer_seconds += dur
                p.transfer_bytes += int(s.attrs.get("bytes", 0))
            elif k == "checkpoint":
                if s.name == "save":
                    p.checkpoint_saves += 1
                    p.checkpoint_seconds += dur
                elif s.name == "restore":
                    p.checkpoint_restores += 1
                    p.checkpoint_seconds += dur
            elif k == "rebuild":
                p.rebuilds += 1
            elif k == "instant":
                if s.name == "fault":
                    p.faults_injected += 1
                elif s.name == "retry":
                    p.retries += 1
                elif s.name == "cache.hit":
                    p.cache_hits += 1
                elif s.name == "cache.miss":
                    p.cache_misses += 1
        # steady state = dispatches that did not pay a compile anywhere in
        # their subtree. A compile may nest more than one level down
        # (loss.eval dispatch → tree_aggregate collective → compile), so
        # every ANCESTOR of a compile span is staging, not steady state.
        parents = {s.span_id: s.parent_id for s in spans}
        staging = set()
        for c in compiles:
            sid = c.parent_id
            while sid and sid not in staging:
                staging.add(sid)
                sid = parents.get(sid, "")
        p.steady_seconds = sum(
            s.duration_s for s in dispatches if s.span_id not in staging)
        return p

    def phase_summary(self) -> Dict[str, float]:
        """The compile-vs-steady-state breakdown bench.py prints."""
        return {
            "compile_s": round(self.compile_seconds, 4),
            "steady_s": round(self.steady_seconds, 4),
            "transfer_s": round(self.transfer_seconds, 4),
            "checkpoint_s": round(self.checkpoint_seconds, 4),
            "wall_s": round(self.wall_seconds, 4),
        }
