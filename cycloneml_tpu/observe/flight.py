"""Always-on flight recorder: a bounded ring of recent spans that runs
even when full tracing is off, dumped on trouble.

The observability gap this closes: the first mesh rebuild (or shed burst,
or injected fault) in a LONG job is exactly the event nobody paid full
tracing for — by the time an operator re-runs with ``cyclone.trace.enabled``
the failure is gone. The flight recorder keeps the last
``cyclone.telemetry.flight.ringSpans`` spans in memory at all times and,
when a trigger fires, freezes that window and (when ``cyclone.trace.dir``
is set) writes it as a normal Chrome trace — the minutes *before* the
event, loadable in Perfetto after the fact.

Mechanics: :class:`FlightTracer` is a :class:`~cycloneml_tpu.observe.
tracing.Tracer` with ``full = False``, installed as THE process-global
tracer when no full tracer is active. Every instrumentation site therefore
keeps its one-global-read disabled discipline — a site sees "a tracer" and
records spans into the ring; the ``full`` flag gates everything that costs
real money (XLA cost harvest, budget analysis, per-job profile rollups,
metrics bridging), which is what keeps flight-only overhead small (the
``trace_overhead`` BENCH field pins the number). ``tracing.enable()``
upgrades a flight ring to a full tracer; full tracing never loses to the
ring.

Triggers (each a one-global-read no-op when nothing is installed):

=======================  =====================================================
reason                   fired from
=======================  =====================================================
``fault``                every chaos injection (``faults.FaultInjector.fire``)
``mesh.rebuild``         ``MeshSupervisor.recover`` entry — the window shows
                         what the mesh was doing when it degraded
``serving.shed``         a ServingOverloaded shed (queue backpressure or
                         admission-control shed burst)
``slo.breach``           the skew detector's SLO latch (observe/skew.py)
=======================  =====================================================

Dumps are throttled (``minIntervalMs``) so a burst freezes one window, not
one per shed request. The last few dumps stay readable in memory
(:func:`dumps`) whether or not a dump directory is configured.
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from cycloneml_tpu.observe import tracing
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

DEFAULT_RING_SPANS = 2048
MAX_KEPT_DUMPS = 16


class FlightTracer(tracing.Tracer):
    """The always-on ring: a Tracer that records spans and nothing else
    (``full = False`` — no metrics bridge, no cost harvest, no rollups)."""

    full = False

    def __init__(self, max_spans: int = DEFAULT_RING_SPANS):
        super().__init__(max_spans=max_spans, registry=None)


_lock = threading.Lock()
_dump_dir: Optional[str] = None
_min_interval_s = 1.0
_diagnose_dumps = True
_last_trigger = 0.0
_trigger_count = 0
_dumps: List[Dict[str, Any]] = []


def enable(ring_spans: int = DEFAULT_RING_SPANS) -> tracing.Tracer:
    """Install the flight ring unless a tracer (full or flight) is already
    active; returns whichever tracer ends up installed."""
    return tracing.install_if_absent(FlightTracer(max_spans=ring_spans))


def disable() -> None:
    """Uninstall the flight ring. A FULL tracer is left untouched — only
    the owner of full tracing (context/tests) may disable it."""
    t = tracing.active()
    if t is not None and not t.full:
        tracing.disable()


def active() -> Optional[tracing.Tracer]:
    """The installed FLIGHT ring, or None (a full tracer is not it)."""
    t = tracing.active()
    if t is not None and not t.full:
        return t
    return None


_KEEP = object()


def configure(dump_dir=_KEEP, min_interval_s: Optional[float] = None,
              diagnose: Optional[bool] = None) -> None:
    """Set where triggered dumps are written (``None``/empty = in-memory
    records only; omit the argument to keep the current directory), the
    trigger throttle, and whether dumps auto-attach a doctor report
    (``cyclone.doctor.flightDiagnosis``)."""
    global _dump_dir, _min_interval_s, _diagnose_dumps
    with _lock:
        if dump_dir is not _KEEP:
            _dump_dir = dump_dir or None
        if min_interval_s is not None:
            _min_interval_s = max(float(min_interval_s), 0.0)
        if diagnose is not None:
            _diagnose_dumps = bool(diagnose)


def trigger(reason: str, **attrs) -> Optional[Dict[str, Any]]:
    """Freeze the recent-span window and dump it.

    Works against whichever tracer is active (the flight ring, or a full
    tracer — then the dump is the last ``DEFAULT_RING_SPANS`` spans of the
    full buffer); a no-op when tracing is entirely off. Throttled: within
    ``minIntervalMs`` of the previous trigger only the counter moves.
    Returns the dump record (``reason``/``n_spans``/``path``) or None."""
    tr = tracing.active()
    if tr is None:
        return None
    global _last_trigger, _trigger_count
    now = time.monotonic()
    with _lock:
        _trigger_count += 1
        count = _trigger_count
        if _last_trigger and now - _last_trigger < _min_interval_s:
            return None
        _last_trigger = now
        dump_dir = _dump_dir
        diagnose_dump = _diagnose_dumps
    window = DEFAULT_RING_SPANS if tr.full else tr.max_spans
    # tail-limited read: under a FULL 100k-span tracer a whole-buffer
    # snapshot would copy everything under the tracer lock on the
    # triggering (step) thread — ask for the window's positions instead
    spans = tr.snapshot(since=max(0, tr.mark() - window))
    dump: Dict[str, Any] = {
        "reason": reason, "attrs": dict(attrs), "n_spans": len(spans),
        "trigger": count, "time": time.time(), "path": None,
        "spans": spans,
    }
    if diagnose_dump:
        # the dump arrives pre-triaged: the doctor runs over the frozen
        # ring (spans only, no live sources — deterministic for a given
        # window) and a doctor failure must never break the dump itself
        try:
            from cycloneml_tpu.observe.diagnose import diagnose
            dump["diagnosis"] = diagnose(
                spans=spans, skew=None, cache_stats=None,
                source="flight").to_dict()
        except Exception:
            logger.exception("flight recorder: dump diagnosis failed")
    if dump_dir:
        from cycloneml_tpu.observe import export
        slug = re.sub(r"[^A-Za-z0-9_.-]+", "_", reason)[:48] or "trigger"
        path = os.path.join(dump_dir, f"flight-{count:04d}-{slug}.trace.json")
        try:
            os.makedirs(dump_dir, exist_ok=True)
            other = {"flight_reason": reason, "flight_trigger": count,
                     **{f"flight_{k}": v for k, v in attrs.items()}}
            if "diagnosis" in dump:
                # the on-disk post-mortem carries its own triage
                other["diagnosis"] = dump["diagnosis"]
            obj = export.chrome_trace(tr, spans=spans, other=other)
            export.write_chrome_trace(obj, path)
            dump["path"] = path
            logger.warning("flight recorder: dumped %d spans to %s (%s)",
                           len(spans), path, reason)
        except OSError:
            logger.exception("flight recorder: dump to %s failed", dump_dir)
    with _lock:
        _dumps.append(dump)
        while len(_dumps) > MAX_KEPT_DUMPS:
            _dumps.pop(0)
    return dump


def dumps() -> List[Dict[str, Any]]:
    """The recent dump records (bounded), newest last."""
    with _lock:
        return list(_dumps)


def trigger_count() -> int:
    with _lock:
        return _trigger_count


def reset() -> None:
    """Clear dump records and the throttle (tests)."""
    global _last_trigger, _trigger_count
    with _lock:
        _dumps.clear()
        _last_trigger = 0.0
        _trigger_count = 0
