"""Local vector types.

API-parity with the reference's ``ml.linalg`` sealed Vector family
(ref: mllib-local/src/main/scala/org/apache/spark/ml/linalg/Vectors.scala:37,
DenseVector :499, SparseVector :603) — but backed by numpy on the host with
zero-copy hand-off to device arrays. All numeric work routes through
``cycloneml_tpu.linalg.blas`` (the dispatch boundary, ref BLAS.scala:27-55).
"""

from __future__ import annotations

import numpy as np
from typing import Iterable, List, Sequence, Tuple, Union


class Vector:
    """Sealed base (ref Vectors.scala:37)."""

    @property
    def size(self) -> int:
        raise NotImplementedError

    def to_array(self) -> np.ndarray:
        raise NotImplementedError

    def to_dense(self) -> "DenseVector":
        return DenseVector(self.to_array())

    def to_sparse(self) -> "SparseVector":
        arr = self.to_array()
        idx = np.nonzero(arr)[0]
        return SparseVector(len(arr), idx, arr[idx])

    def compressed(self) -> "Vector":
        """Pick the smaller representation (ref Vectors.scala compressed)."""
        nnz = self.num_nonzeros()
        # dense storage: 8n bytes; sparse: 12nnz + overhead
        if 1.5 * (nnz + 1.0) < self.size:
            return self.to_sparse()
        return self.to_dense()

    def num_nonzeros(self) -> int:
        return int(np.count_nonzero(self.to_array()))

    def num_actives(self) -> int:
        raise NotImplementedError

    def dot(self, other: "Vector") -> float:
        from cycloneml_tpu.linalg import blas
        return blas.dot(self, other)

    def norm(self, p: float = 2.0) -> float:
        return Vectors.norm(self, p)

    def sq_dist(self, other: "Vector") -> float:
        return Vectors.sqdist(self, other)

    def argmax(self) -> int:
        raise NotImplementedError

    def apply(self, i: int) -> float:
        return float(self.to_array()[i])

    def __getitem__(self, i: int) -> float:
        return self.apply(i)

    def __len__(self) -> int:
        return self.size

    def __eq__(self, other) -> bool:
        if not isinstance(other, Vector):
            return NotImplemented
        return self.size == other.size and np.array_equal(self.to_array(), other.to_array())

    def __hash__(self) -> int:
        # mirror reference semantics: dense/sparse with same values hash equal
        arr = self.to_array()
        nz = np.nonzero(arr)[0][:16]
        return hash((self.size, tuple(nz.tolist()), tuple(arr[nz].tolist())))


class DenseVector(Vector):
    """Dense float64 vector (ref Vectors.scala:499)."""

    __slots__ = ("values",)

    def __init__(self, values: Union[np.ndarray, Sequence[float]]):
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)

    @property
    def size(self) -> int:
        return self.values.shape[0]

    def to_array(self) -> np.ndarray:
        return self.values

    def num_actives(self) -> int:
        return self.size

    def argmax(self) -> int:
        if self.size == 0:
            return -1
        return int(np.argmax(self.values))

    def copy(self) -> "DenseVector":
        return DenseVector(self.values.copy())

    def __repr__(self) -> str:
        return f"DenseVector({self.values.tolist()})"


class SparseVector(Vector):
    """Sparse vector as (size, indices, values) (ref Vectors.scala:603)."""

    __slots__ = ("_size", "indices", "values")

    def __init__(self, size: int, indices: Union[np.ndarray, Sequence[int]],
                 values: Union[np.ndarray, Sequence[float]]):
        self._size = int(size)
        self.indices = np.asarray(indices, dtype=np.int32).reshape(-1)
        self.values = np.asarray(values, dtype=np.float64).reshape(-1)
        if self.indices.shape[0] != self.values.shape[0]:
            raise ValueError("indices and values must have the same length")
        if self.indices.size > 0:
            if np.any(np.diff(self.indices) <= 0):
                order = np.argsort(self.indices, kind="stable")
                self.indices = self.indices[order]
                self.values = self.values[order]
            if self.indices[-1] >= self._size:
                raise ValueError(f"index {self.indices[-1]} out of range for size {self._size}")

    @property
    def size(self) -> int:
        return self._size

    def to_array(self) -> np.ndarray:
        arr = np.zeros(self._size, dtype=np.float64)
        arr[self.indices] = self.values
        return arr

    def num_actives(self) -> int:
        return self.values.shape[0]

    def num_nonzeros(self) -> int:
        return int(np.count_nonzero(self.values))

    def apply(self, i: int) -> float:
        if i < 0 or i >= self._size:
            raise IndexError(i)
        j = np.searchsorted(self.indices, i)
        if j < len(self.indices) and self.indices[j] == i:
            return float(self.values[j])
        return 0.0

    def argmax(self) -> int:
        if self._size == 0:
            return -1
        if self.num_actives() == 0:
            return 0
        max_j = int(np.argmax(self.values))
        max_v = self.values[max_j]
        if max_v <= 0 and self.num_actives() < self._size:
            if max_v < 0:
                # first index not in indices (a zero beats any negative)
                present = set(self.indices.tolist())
                for i in range(self._size):
                    if i not in present:
                        return i
            else:
                return int(self.indices[max_j])
        return int(self.indices[max_j])

    def copy(self) -> "SparseVector":
        return SparseVector(self._size, self.indices.copy(), self.values.copy())

    def __repr__(self) -> str:
        return f"SparseVector({self._size}, {self.indices.tolist()}, {self.values.tolist()})"


class Vectors:
    """Factory methods (ref Vectors.scala object Vectors)."""

    @staticmethod
    def dense(*values) -> DenseVector:
        if len(values) == 1 and isinstance(values[0], (list, tuple, np.ndarray)):
            return DenseVector(values[0])
        return DenseVector(list(values))

    @staticmethod
    def sparse(size: int, arg1, arg2=None) -> SparseVector:
        if arg2 is None:
            # list of (index, value) pairs
            pairs = sorted(arg1)
            idx = [p[0] for p in pairs]
            vals = [p[1] for p in pairs]
            return SparseVector(size, idx, vals)
        return SparseVector(size, arg1, arg2)

    @staticmethod
    def zeros(size: int) -> DenseVector:
        return DenseVector(np.zeros(size))

    @staticmethod
    def norm(vector: Vector, p: float) -> float:
        """p-norm (ref Vectors.scala norm)."""
        values = vector.values if isinstance(vector, (DenseVector, SparseVector)) else vector.to_array()
        if p == 1:
            return float(np.sum(np.abs(values)))
        if p == 2:
            return float(np.sqrt(np.sum(values * values)))
        if np.isinf(p):
            return float(np.max(np.abs(values))) if len(values) else 0.0
        if p < 1:
            raise ValueError("p must be >= 1")
        return float(np.power(np.sum(np.power(np.abs(values), p)), 1.0 / p))

    @staticmethod
    def sqdist(v1: Vector, v2: Vector) -> float:
        """Squared euclidean distance (ref Vectors.scala sqdist)."""
        if v1.size != v2.size:
            raise ValueError("vector sizes differ")
        d = v1.to_array() - v2.to_array()
        return float(np.dot(d, d))
