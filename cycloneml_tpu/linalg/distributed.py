"""Distributed matrices.

Re-design of ``mllib/linalg/distributed`` (ref: RowMatrix.scala:47 — 868 LoC;
EigenValueDecomposition.scala:87 ARPACK Lanczos): a RowMatrix is an
``InstanceDataset``'s feature block, rows sharded over the mesh.

- ``compute_gramian``: XᵀX as one psum'd MXU matmul — replaces the
  treeAggregate of packed ``spr`` rank-1 updates (ref RowMatrix.scala:130,147).
- ``compute_svd``: for d ≤ max_gram_dim, eigendecomposition of the Gramian
  (the reference's LocalARPACK/LocalLAPACK branch :303); otherwise Lanczos
  with full reorthogonalization where each matvec XᵀXv is a distributed
  psum'd program — the ARPACK-equivalent (``dsaupd`` loop) without JNI.
- ``compute_principal_components``/``compute_covariance``
  (ref :486,523) — covariance from the Gramian + mean, eigh on the driver.
- ``multiply``, ``column_similarities`` (brute-force cosine via the Gramian —
  the DIMSUM sampling path is a CPU-era optimisation; one MXU matmul replaces
  it exactly).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.linalg.matrices import DenseMatrix, Matrix
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.stat.summarizer import Summarizer


class SVDResult(NamedTuple):
    U: Optional["RowMatrix"]
    s: DenseVector
    V: DenseMatrix


class RowMatrix:
    """Row-oriented distributed matrix without meaningful row indices
    (ref RowMatrix.scala:47).

    Backed by either the dense device tier (``InstanceDataset``) or the
    sparse ELL tier (``SparseInstanceDataset``) — the reference's RowMatrix
    is likewise storage-agnostic over dense/sparse vectors. Gramian and the
    Lanczos SVD operator dispatch on the tier; the sparse large-d path is
    the NYTimes-class bag-of-words configuration (BASELINE config 5)."""

    def __init__(self, dataset):
        self.dataset = dataset

    @classmethod
    def from_numpy(cls, ctx, x: np.ndarray) -> "RowMatrix":
        return cls(InstanceDataset.from_numpy(ctx, x))

    def num_rows(self) -> int:
        return self.dataset.n_rows

    def num_cols(self) -> int:
        return self.dataset.n_features

    # -- gramian ---------------------------------------------------------------
    def compute_gramian(self) -> DenseMatrix:
        """XᵀX (ref computeGramianMatrix:130 — treeAggregate of spr:147).

        On a mesh with a model axis (model_parallelism > 1) and a divisible
        feature dim, the Gram matrix is computed feature-sharded via the
        ppermute ring (SURVEY §5.7a) — no device materializes the full
        (d, d) — and gathered to the host here. Use
        :meth:`compute_gramian_sharded` to keep it on the mesh when d is too
        large to gather.
        """
        sharded = self.compute_gramian_sharded()
        if sharded is not None:
            return DenseMatrix.from_array(
                np.asarray(sharded, dtype=np.float64))
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.dataset.sparse import SparseInstanceDataset

        if isinstance(self.dataset, SparseInstanceDataset):
            # small-d sparse Gramian: densify each ELL block on device
            # (scatter into (block, d)) and run the same einsum; for large
            # d use compute_svd's Lanczos operator instead of materializing
            # (d, d)
            d = self.num_cols()
            if self.dataset.is_hybrid:
                def agg(indices, values, coo_row, coo_idx, coo_val, y, w):
                    n_b = indices.shape[0]
                    dense = jnp.zeros((n_b, d), values.dtype)
                    dense = dense.at[
                        jnp.arange(n_b)[:, None], indices].add(values)
                    dense = dense.at[coo_row, coo_idx].add(coo_val)
                    return jnp.einsum(
                        "bi,bj->ij",
                        dense * (w > 0)[:, None].astype(values.dtype),
                        dense, precision=jax.lax.Precision.HIGHEST)
            else:
                def agg(indices, values, y, w):
                    n_b = indices.shape[0]
                    dense = jnp.zeros((n_b, d), values.dtype)
                    dense = dense.at[
                        jnp.arange(n_b)[:, None], indices].add(values)
                    return jnp.einsum(
                        "bi,bj->ij",
                        dense * (w > 0)[:, None].astype(values.dtype),
                        dense, precision=jax.lax.Precision.HIGHEST)
            out = self.dataset.tree_aggregate_fn(agg)()
            return DenseMatrix.from_array(np.asarray(out, dtype=np.float64))

        from cycloneml_tpu.ops.kernels import fused_gramian, use_fused_kernels
        if use_fused_kernels(self.dataset.ctx):
            # fused Pallas sweep: per-tile MXU matmul into a revisited VMEM
            # accumulator, presence mask applied in-kernel — one storage-
            # width read of X, no masked copy
            out = self.dataset.tree_aggregate_fn(
                lambda x, y, w: fused_gramian(x, w=w))()
        else:
            def agg(x, y, w):
                # presence-masked XᵀX; narrow (bf16) blocks keep their
                # storage dtype as the einsum operands ({0,1} mask is
                # exact) and accumulate into f32
                from cycloneml_tpu.dataset.instance import is_narrow_dtype
                acc = jnp.float32 if is_narrow_dtype(x.dtype) else x.dtype
                return jnp.einsum(
                    "bi,bj->ij", x * (w > 0)[:, None].astype(x.dtype), x,
                    precision=jax.lax.Precision.HIGHEST,
                    preferred_element_type=acc)
            out = self.dataset.tree_aggregate_fn(agg)()
        return DenseMatrix.from_array(np.asarray(out, dtype=np.float64))

    def compute_gramian_sharded(self):
        """Model-axis-sharded Gramian (``P(model, None)`` device array), or
        None when the mesh has no model axis / d does not divide it."""
        from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
        from cycloneml_tpu.parallel import feature_sharding as fs
        if isinstance(self.dataset, SparseInstanceDataset):
            return None  # the ring is a dense-block pipeline
        rt = self.dataset.ctx.mesh_runtime
        d = self.num_cols()
        m = fs.model_parallelism(rt)
        if m <= 1 or d % m != 0:
            return None
        # the ppermute ring accumulates in X's dtype; narrow data-tier
        # blocks upcast at the TP boundary (fs.accumulator_width)
        x_tp = fs.feature_sharded_put(
            rt, fs.accumulator_width(self.dataset.x))
        return fs.gramian_feature_sharded(rt, x_tp, w=self.dataset.w)

    # -- covariance / pca ------------------------------------------------------
    def compute_covariance(self) -> DenseMatrix:
        """Sample covariance (ref computeCovariance:332): (XᵀX − n·x̄x̄ᵀ)/(n−1)."""
        n = self.num_rows()
        if n < 2:
            raise ValueError("need at least 2 rows for covariance")
        g = self.compute_gramian().to_array()
        mean = Summarizer.summarize(self.dataset).mean
        cov = (g - n * np.outer(mean, mean)) / (n - 1.0)
        return DenseMatrix.from_array(cov)

    def compute_principal_components_and_variance(
            self, k: int) -> Tuple[DenseMatrix, DenseVector]:
        """(ref computePrincipalComponentsAndExplainedVariance:486)."""
        d = self.num_cols()
        if not 1 <= k <= d:
            raise ValueError(f"k must be in [1,{d}]")
        cov = self.compute_covariance().to_array()
        vals, vecs = np.linalg.eigh(cov)  # ascending
        order = np.argsort(vals)[::-1]
        vals, vecs = vals[order], vecs[:, order]
        vecs = _sign_convention(vecs)
        total = max(vals.sum(), 1e-300)
        return (DenseMatrix.from_array(vecs[:, :k]),
                Vectors.dense(vals[:k] / total))

    def compute_principal_components(self, k: int) -> DenseMatrix:
        return self.compute_principal_components_and_variance(k)[0]

    # -- svd -------------------------------------------------------------------
    def compute_svd(self, k: int, compute_u: bool = False,
                    r_cond: float = 1e-9, max_gram_dim: int = 4096,
                    tol: float = 1e-10, max_iter: int = 300) -> SVDResult:
        """Top-k singular value decomposition (ref computeSVD:303).

        Mode selection mirrors the reference: small d → Gramian eigen on the
        driver ("LocalLAPACK"); large d → distributed Lanczos on the operator
        v ↦ XᵀXv ("DistARPACK", EigenValueDecomposition.scala:87).
        """
        d = self.num_cols()
        n = self.num_rows()
        if not 1 <= k <= d:
            raise ValueError(f"k must be in [1,{d}]")
        if d <= max_gram_dim:
            g = self.compute_gramian().to_array()
            vals, vecs = np.linalg.eigh(g)
            order = np.argsort(vals)[::-1]
            vals, vecs = vals[order][:k], vecs[:, order][:, :k]
        else:
            vals, vecs = self._lanczos(k, tol=tol, max_iter=max_iter)
        sigmas = np.sqrt(np.maximum(vals, 0.0))
        # rank by rCond relative to largest (ref :351)
        if sigmas.size == 0 or sigmas[0] <= 0:
            raise ValueError("matrix has rank 0")
        keep = sigmas > r_cond * sigmas[0]
        sigmas = sigmas[keep]
        vecs = _sign_convention(vecs[:, keep])
        s = Vectors.dense(sigmas)
        v = DenseMatrix.from_array(vecs)
        u = None
        if compute_u:
            # U = X V Σ⁻¹, rows stay sharded on device
            import jax
            import jax.numpy as jnp
            from cycloneml_tpu.dataset.sparse import SparseInstanceDataset
            if isinstance(self.dataset, SparseInstanceDataset):
                raise NotImplementedError(
                    "compute_u over the sparse tier: project with "
                    "multiply() after densifying, or request V/σ only")
            vs = jnp.asarray(vecs / sigmas[None, :])
            ux = jax.jit(lambda x, m: jnp.dot(
                x, m, precision=jax.lax.Precision.HIGHEST))(self.dataset.x, vs)
            ds = self.dataset.derive(x=ux, n_features=int(sigmas.size))
            u = RowMatrix(ds)
        return SVDResult(u, s, v)

    def _gram_matvec_fn(self):
        """q ↦ XᵀXq as one jitted psum aggregate — dense blocks use two
        MXU gemvs; sparse (ELL / ELL+COO) blocks use the gather/segment-sum
        pair the sparse training aggregators are built from. The reference
        ships the same product through treeAggregate inside ARPACK's
        reverse-communication loop (EigenValueDecomposition.scala:87)."""
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.dataset.sparse import SparseInstanceDataset

        d = self.num_cols()
        if isinstance(self.dataset, SparseInstanceDataset):
            from cycloneml_tpu.ml.optim import sparse_aggregators as sa
            if self.dataset.is_hybrid:
                def agg(indices, values, coo_row, coo_idx, coo_val, y, w, q):
                    m = sa._margins_hybrid(indices, values, coo_row,
                                           coo_idx, coo_val, q, 0.0)
                    m = m * (w > 0).astype(values.dtype)
                    return sa._scatter_grad_hybrid(
                        indices, values, coo_row, coo_idx, coo_val, m, d)
            else:
                def agg(indices, values, y, w, q):
                    m = sa._margins(indices, values, q, 0.0)
                    m = m * (w > 0).astype(values.dtype)
                    return sa._scatter_grad(indices, values, m, d)
            return self.dataset.tree_aggregate_fn(agg), \
                self.dataset.values.dtype
        return self.dataset.tree_aggregate_fn(
            lambda x, y, w, q: jnp.dot(
                x.T, jnp.dot(x, q, precision=jax.lax.Precision.HIGHEST)
                * (w > 0).astype(x.dtype),
                precision=jax.lax.Precision.HIGHEST)), self.dataset.x.dtype

    def _lanczos(self, k: int, tol: float, max_iter: int):
        """Lanczos with full reorthogonalization on the driver; the matvec
        is the distributed psum from :meth:`_gram_matvec_fn`."""
        d = self.num_cols()
        matvec_agg, dt = self._gram_matvec_fn()

        def matvec(q: np.ndarray) -> np.ndarray:
            return np.asarray(matvec_agg(q.astype(dt)), dtype=np.float64)

        rng = np.random.RandomState(0)
        m = min(d, max_iter)
        min_steps = min(max(3 * k, 20), m)
        # the Ritz-stability stop cannot resolve below the matvec dtype's
        # noise floor: on the f32 device path converged values still jitter
        # at ~eps relative, so flooring at 32·eps stops when further steps
        # only chase quantization (f64 keeps the user's tol)
        try:
            ritz_tol = max(tol, 32.0 * float(np.finfo(np.dtype(dt)).eps))
        except ValueError:  # non-float dt cannot happen for matvec, but
            ritz_tol = max(tol, 1e-12)
        q = rng.randn(d)
        q /= np.linalg.norm(q)
        qs = [q]
        alphas, betas = [], []
        prev_ritz = None
        for j in range(m):
            z = matvec(qs[j])
            a = float(qs[j] @ z)
            alphas.append(a)
            z = z - a * qs[j] - (betas[-1] * qs[j - 1] if betas else 0.0)
            # full reorthogonalization (twice for stability)
            for _ in range(2):
                for qi in qs:
                    z -= (qi @ z) * qi
            b = float(np.linalg.norm(z))
            if b < tol:
                break
            # grow the subspace past the 3k floor until the wanted Ritz
            # values stop moving — clustered tails need more than 3k steps
            # (ARPACK's restart loop plays this role in the reference)
            if j + 1 >= min_steps and (j + 1) % 5 == 0:
                t = np.diag(alphas)
                for i, bb in enumerate(betas):
                    t[i, i + 1] = t[i + 1, i] = bb
                ritz = np.sort(np.linalg.eigvalsh(t))[::-1][:k]
                if prev_ritz is not None and len(prev_ritz) == len(ritz):
                    denom = np.maximum(np.abs(ritz), 1e-300)
                    if np.max(np.abs(ritz - prev_ritz) / denom) < ritz_tol:
                        betas.append(b)
                        qs.append(z / b)
                        break
                prev_ritz = ritz
            betas.append(b)
            qs.append(z / b)
        t = np.diag(alphas)
        for i, b in enumerate(betas[: len(alphas) - 1]):
            t[i, i + 1] = t[i + 1, i] = b
        evals, evecs = np.linalg.eigh(t)
        order = np.argsort(evals)[::-1][:k]
        basis = np.stack(qs[: t.shape[0]], axis=1)
        return evals[order], basis @ evecs[:, order]

    # -- products --------------------------------------------------------------
    def multiply(self, b: Matrix) -> "RowMatrix":
        """X @ B with rows staying sharded (ref multiply:592)."""
        import jax
        import jax.numpy as jnp
        if b.num_rows != self.num_cols():
            raise ValueError("dimension mismatch")
        barr = jnp.asarray(np.asarray(b.to_array(), dtype=self.dataset.x.dtype))
        out = jax.jit(lambda x, m: jnp.dot(
            x, m, precision=jax.lax.Precision.HIGHEST))(self.dataset.x, barr)
        ds = self.dataset.derive(x=out, n_features=b.num_cols)
        return RowMatrix(ds)

    def column_similarities(self) -> DenseMatrix:
        """Upper-triangular cosine similarities between columns (ref
        columnSimilarities:613 — DIMSUM sampling unnecessary on the MXU)."""
        g = self.compute_gramian().to_array()
        norms = np.sqrt(np.maximum(np.diag(g), 1e-300))
        sim = g / norms[:, None] / norms[None, :]
        return DenseMatrix.from_array(np.triu(sim, 1))

    def compute_column_summary_statistics(self):
        return Summarizer.summarize(self.dataset)

    def to_numpy(self) -> np.ndarray:
        return self.dataset.to_numpy()[0]


def _sign_convention(vecs: np.ndarray) -> np.ndarray:
    """Deterministic sign: largest-|component| positive per column (keeps
    results comparable across runs/backends)."""
    if vecs.size == 0:
        return vecs
    idx = np.argmax(np.abs(vecs), axis=0)
    signs = np.sign(vecs[idx, np.arange(vecs.shape[1])])
    signs[signs == 0] = 1.0
    return vecs * signs[None, :]
