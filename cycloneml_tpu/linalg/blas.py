"""BLAS dispatch boundary — THE offload plugin point.

Mirrors the reference's ``ml.linalg.BLAS`` (ref: mllib-local/src/main/scala/
org/apache/spark/ml/linalg/BLAS.scala:27-55): every kernel funnels through a
size-based dispatch (``getBLAS:50`` — vectors with fewer than
``NATIVE_THRESHOLD`` elements use the pure-host path, larger ones the
accelerator path with silent fallback, ref :45). Here the host path is numpy
(replacing javaBLAS) and the accelerator path is ``jax.jit``-compiled XLA:TPU
kernels (replacing the netlib JNI nativeBLAS). The in-place mutation
semantics of the reference API (axpy/gemv/gemm writing into ``y``/``C``) are
preserved on the numpy-backed local types.

Routines covered (ref file:line): axpy:61, dot:122, copy, scal:237, spr, syr,
gemm, gemv — plus the raw device entry points (``device_*``) used by the
distributed layer where arrays are already on device.
"""

from __future__ import annotations

import functools
import os
from typing import Union

import numpy as np

from cycloneml_tpu.linalg.vectors import DenseVector, SparseVector, Vector
from cycloneml_tpu.linalg.matrices import DenseMatrix, Matrix, SparseMatrix

# Size-based dispatch mirrors getBLAS(256) (ref BLAS.scala:50), but the
# crossover for a host↔device hop is FLOPs, not elements: offload only when
# MXU throughput amortises the transfer. Overridable for testing.
DEVICE_FLOPS_THRESHOLD = int(os.environ.get("CYCLONE_BLAS_DEVICE_THRESHOLD", 1 << 22))

_jax = None


def _maybe_jax():
    """Lazy jax import with silent fallback (ref BLAS.scala:45)."""
    global _jax
    if _jax is None:
        try:
            import jax  # noqa: F811
            _jax = jax
        except Exception:
            _jax = False
    return _jax or None


# ---------------------------------------------------------------------------
# Device kernels (XLA:TPU) — jit-compiled once per shape, cached by jax
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _device_kernels():
    import jax
    import jax.numpy as jnp

    # Precision.HIGHEST: the MXU's default bf16 multiply loses ~3 decimal
    # digits — BLAS-parity kernels must accumulate in f32 (6-pass) instead.
    hi = jax.lax.Precision.HIGHEST

    @jax.jit
    def k_gemm(a, b):
        return jnp.dot(a, b, precision=hi)

    @jax.jit
    def k_gemv(a, x):
        return jnp.dot(a, x, precision=hi)

    @jax.jit
    def k_dot(x, y):
        return jnp.dot(x, y)

    @jax.jit
    def k_axpy(a, x, y):
        return a * x + y

    @jax.jit
    def k_scal(a, x):
        return a * x

    @jax.jit
    def k_syr(alpha, x, a):
        return a + alpha * jnp.outer(x, x)

    return {
        "gemm": k_gemm, "gemv": k_gemv, "dot": k_dot,
        "axpy": k_axpy, "scal": k_scal, "syr": k_syr,
    }


def device_gemm(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Raw device matmul for host-resident operands; falls back to numpy."""
    jax = _maybe_jax()
    flops = a.shape[0] * a.shape[1] * (b.shape[1] if b.ndim > 1 else 1)
    if jax is not None and flops >= DEVICE_FLOPS_THRESHOLD:
        return np.asarray(_device_kernels()["gemm"](a, b), dtype=np.float64)
    return a @ b


def device_gemv(a: np.ndarray, x: np.ndarray) -> np.ndarray:
    jax = _maybe_jax()
    if jax is not None and a.size >= DEVICE_FLOPS_THRESHOLD:
        return np.asarray(_device_kernels()["gemv"](a, x), dtype=np.float64)
    return a @ x


# ---------------------------------------------------------------------------
# Level 1
# ---------------------------------------------------------------------------

def axpy(a: float, x: Vector, y: DenseVector) -> None:
    """y += a * x (ref BLAS.scala:61). Mutates ``y`` in place."""
    if x.size != y.size:
        raise ValueError(f"size mismatch: {x.size} vs {y.size}")
    if isinstance(x, SparseVector):
        y.values[x.indices] += a * x.values
    else:
        y.values += a * np.asarray(x.to_array())


def dot(x: Vector, y: Vector) -> float:
    """x . y (ref BLAS.scala:122), with sparse/dense specialisations."""
    if x.size != y.size:
        raise ValueError(f"size mismatch: {x.size} vs {y.size}")
    if isinstance(x, SparseVector) and isinstance(y, DenseVector):
        return float(np.dot(x.values, y.values[x.indices]))
    if isinstance(x, DenseVector) and isinstance(y, SparseVector):
        return dot(y, x)
    if isinstance(x, SparseVector) and isinstance(y, SparseVector):
        common, ix, iy = np.intersect1d(x.indices, y.indices, return_indices=True)
        return float(np.dot(x.values[ix], y.values[iy]))
    xv, yv = x.to_array(), y.to_array()
    return float(np.dot(xv, yv))


def copy(x: Vector, y: DenseVector) -> None:
    """y := x (ref BLAS.scala copy)."""
    if x.size != y.size:
        raise ValueError("size mismatch")
    np.copyto(y.values, x.to_array())


def scal(a: float, x: Vector) -> None:
    """x *= a in place (ref BLAS.scala:237)."""
    x.values *= a  # both Dense and Sparse carry .values


# ---------------------------------------------------------------------------
# Level 2
# ---------------------------------------------------------------------------

def gemv(alpha: float, a: Matrix, x: Vector, beta: float, y: DenseVector) -> None:
    """y := alpha * A @ x + beta * y (ref BLAS.scala gemv). Mutates y."""
    if a.num_cols != x.size or a.num_rows != y.size:
        raise ValueError("dimension mismatch")
    if isinstance(a, SparseMatrix):
        out = alpha * (a.to_scipy() @ x.to_array())
    else:
        arr = a.to_array()
        if isinstance(x, SparseVector):
            out = alpha * (arr[:, x.indices] @ x.values)
        else:
            out = alpha * device_gemv(arr, x.to_array())
    y.values *= beta
    y.values += out


def spr(alpha: float, v: Vector, u: np.ndarray) -> None:
    """Packed symmetric rank-1 update: U += alpha * v vᵀ (upper triangle,
    column-major packed — ref BLAS.scala spr, used by RowMatrix Gramian
    ref RowMatrix.scala:147). ``u`` is the packed length n(n+1)/2 array."""
    n = v.size
    if u.shape[0] != n * (n + 1) // 2:
        raise ValueError("packed array size mismatch")
    if isinstance(v, SparseVector):
        idx, vals = v.indices, v.values
        # column-major upper-triangular packed: col j starts at j(j+1)/2
        for jj in range(len(idx)):
            j = int(idx[jj])
            col_start = j * (j + 1) // 2
            av = alpha * vals[jj]
            sel = idx[: jj + 1]
            u[col_start + sel] += av * vals[: jj + 1]
    else:
        vv = v.to_array()
        outer = np.outer(vv, vv)
        # upper col-major packed order [(i,j) for j in 0..n-1 for i in 0..j]
        # equals row-major tril enumeration of the transpose
        u += alpha * outer.T[np.tril_indices(n)]


def unpack_upper(u: np.ndarray, n: int) -> np.ndarray:
    """Expand a column-major upper-packed array into a full symmetric matrix."""
    a = np.zeros((n, n))
    k = 0
    for j in range(n):
        a[: j + 1, j] = u[k: k + j + 1]
        k += j + 1
    return a + np.triu(a, 1).T


def pack_upper(a: np.ndarray) -> np.ndarray:
    """Pack a symmetric matrix into column-major upper-packed storage."""
    n = a.shape[0]
    out = np.empty(n * (n + 1) // 2)
    k = 0
    for j in range(n):
        out[k: k + j + 1] = a[: j + 1, j]
        k += j + 1
    return out


def syr(alpha: float, x: Vector, a: DenseMatrix) -> None:
    """A += alpha * x xᵀ (ref BLAS.scala syr). Mutates A."""
    n = x.size
    if a.num_rows != n or a.num_cols != n:
        raise ValueError("dimension mismatch")
    if isinstance(x, SparseVector):
        arr = a.to_array()
        ix = x.indices
        arr[np.ix_(ix, ix)] += alpha * np.outer(x.values, x.values)
    else:
        a.to_array()[...] += alpha * np.outer(x.to_array(), x.to_array())


# ---------------------------------------------------------------------------
# Level 3
# ---------------------------------------------------------------------------

def gemm(alpha: float, a: Matrix, b: Matrix, beta: float, c: DenseMatrix) -> None:
    """C := alpha * A @ B + beta * C (ref BLAS.scala gemm). Mutates C."""
    if a.num_cols != b.num_rows or a.num_rows != c.num_rows or b.num_cols != c.num_cols:
        raise ValueError("dimension mismatch")
    if isinstance(a, SparseMatrix):
        prod = np.asarray((a.to_scipy() @ b.to_array()))
    elif isinstance(b, SparseMatrix):
        prod = np.asarray((b.to_scipy().T @ a.to_array().T)).T
    else:
        prod = device_gemm(a.to_array(), b.to_array())
    carr = c.to_array()
    carr *= beta
    carr += alpha * prod
