from cycloneml_tpu.linalg.vectors import (
    Vector, DenseVector, SparseVector, Vectors,
)
from cycloneml_tpu.linalg.matrices import (
    Matrix, DenseMatrix, SparseMatrix, Matrices,
)
from cycloneml_tpu.linalg import blas as BLAS

__all__ = [
    "Vector", "DenseVector", "SparseVector", "Vectors",
    "Matrix", "DenseMatrix", "SparseMatrix", "Matrices",
    "BLAS",
]
