"""Local matrix types.

API parity with ``ml.linalg`` matrices (ref: mllib-local/.../Matrices.scala:32
sealed Matrix, DenseMatrix :300, SparseMatrix :594). The reference stores
column-major to match Fortran BLAS; we store row-major (C order) because XLA
and the MXU are layout-agnostic at this level — ``to_array`` and indexing
semantics are preserved, ``values`` ordering is documented as row-major.
"""

from __future__ import annotations

import numpy as np
from typing import Sequence, Union

from cycloneml_tpu.linalg.vectors import DenseVector, SparseVector, Vector


class Matrix:
    """Sealed base (ref Matrices.scala:32)."""

    @property
    def num_rows(self) -> int:
        raise NotImplementedError

    @property
    def num_cols(self) -> int:
        raise NotImplementedError

    def to_array(self) -> np.ndarray:
        """(num_rows, num_cols) float64 array."""
        raise NotImplementedError

    def apply(self, i: int, j: int) -> float:
        return float(self.to_array()[i, j])

    def __getitem__(self, ij) -> float:
        return self.apply(*ij)

    def transpose(self) -> "Matrix":
        raise NotImplementedError

    @property
    def T(self) -> "Matrix":
        return self.transpose()

    def multiply(self, other: Union["Matrix", Vector]) -> Union["DenseMatrix", DenseVector]:
        from cycloneml_tpu.linalg import blas
        if isinstance(other, Vector):
            return DenseVector(blas.device_gemv(self.to_array(), other.to_array()))
        return DenseMatrix.from_array(blas.device_gemm(self.to_array(), other.to_array()))

    def num_nonzeros(self) -> int:
        return int(np.count_nonzero(self.to_array()))

    def num_actives(self) -> int:
        raise NotImplementedError

    def colwise(self):
        return self.to_array().T

    def row_iter(self):
        arr = self.to_array()
        for i in range(arr.shape[0]):
            yield DenseVector(arr[i])

    def col_iter(self):
        arr = self.to_array()
        for j in range(arr.shape[1]):
            yield DenseVector(arr[:, j])

    def __eq__(self, other) -> bool:
        if not isinstance(other, Matrix):
            return NotImplemented
        return (self.num_rows, self.num_cols) == (other.num_rows, other.num_cols) and \
            np.array_equal(self.to_array(), other.to_array())

    def __hash__(self):
        return hash((self.num_rows, self.num_cols))


class DenseMatrix(Matrix):
    """Dense matrix (ref Matrices.scala:300). Row-major storage."""

    __slots__ = ("_arr",)

    def __init__(self, num_rows: int, num_cols: int,
                 values: Union[np.ndarray, Sequence[float]],
                 is_transposed: bool = False):
        # `values` follows the reference's constructor contract: column-major
        # unless is_transposed. Internally normalised to a (rows, cols) C array.
        v = np.asarray(values, dtype=np.float64).reshape(-1)
        if v.size != num_rows * num_cols:
            raise ValueError("values length mismatch")
        if is_transposed:
            self._arr = np.ascontiguousarray(v.reshape(num_rows, num_cols))
        else:
            self._arr = np.ascontiguousarray(v.reshape(num_cols, num_rows).T)

    @classmethod
    def from_array(cls, arr: np.ndarray) -> "DenseMatrix":
        m = cls.__new__(cls)
        m._arr = np.ascontiguousarray(np.asarray(arr, dtype=np.float64))
        if m._arr.ndim != 2:
            raise ValueError("expected 2-D array")
        return m

    @property
    def num_rows(self) -> int:
        return self._arr.shape[0]

    @property
    def num_cols(self) -> int:
        return self._arr.shape[1]

    @property
    def values(self) -> np.ndarray:
        """Column-major flat values, matching the reference's field."""
        return np.asfortranarray(self._arr).ravel(order="F")

    def to_array(self) -> np.ndarray:
        return self._arr

    def num_actives(self) -> int:
        return self._arr.size

    def transpose(self) -> "DenseMatrix":
        return DenseMatrix.from_array(self._arr.T)

    def copy(self) -> "DenseMatrix":
        return DenseMatrix.from_array(self._arr.copy())

    def to_sparse(self) -> "SparseMatrix":
        return SparseMatrix.from_array(self._arr)

    def __repr__(self) -> str:
        return f"DenseMatrix({self.num_rows}x{self.num_cols})"


class SparseMatrix(Matrix):
    """CSR sparse matrix (ref Matrices.scala:594 stores CSC; we store CSR to
    match row-major instance blocks — the public (i,j) semantics are equal)."""

    __slots__ = ("_num_rows", "_num_cols", "indptr", "indices", "values")

    def __init__(self, num_rows: int, num_cols: int,
                 colptrs: Sequence[int], row_indices: Sequence[int],
                 values: Sequence[float]):
        # reference constructor contract is CSC; convert to CSR internally
        from scipy.sparse import csc_matrix
        csc = csc_matrix(
            (np.asarray(values, dtype=np.float64),
             np.asarray(row_indices, dtype=np.int32),
             np.asarray(colptrs, dtype=np.int32)),
            shape=(num_rows, num_cols))
        csr = csc.tocsr()
        self._num_rows, self._num_cols = num_rows, num_cols
        self.indptr = csr.indptr
        self.indices = csr.indices
        self.values = csr.data

    @classmethod
    def from_array(cls, arr: np.ndarray) -> "SparseMatrix":
        from scipy.sparse import csr_matrix
        csr = csr_matrix(np.asarray(arr, dtype=np.float64))
        m = cls.__new__(cls)
        m._num_rows, m._num_cols = arr.shape
        m.indptr, m.indices, m.values = csr.indptr, csr.indices, csr.data
        return m

    @classmethod
    def from_scipy(cls, sp) -> "SparseMatrix":
        csr = sp.tocsr()
        m = cls.__new__(cls)
        m._num_rows, m._num_cols = csr.shape
        m.indptr, m.indices, m.values = csr.indptr, csr.indices, np.asarray(csr.data, dtype=np.float64)
        return m

    def to_scipy(self):
        from scipy.sparse import csr_matrix
        return csr_matrix((self.values, self.indices, self.indptr),
                          shape=(self._num_rows, self._num_cols))

    @property
    def num_rows(self) -> int:
        return self._num_rows

    @property
    def num_cols(self) -> int:
        return self._num_cols

    def to_array(self) -> np.ndarray:
        return np.asarray(self.to_scipy().todense())

    def num_actives(self) -> int:
        return len(self.values)

    def num_nonzeros(self) -> int:
        return int(np.count_nonzero(self.values))

    def transpose(self) -> "SparseMatrix":
        return SparseMatrix.from_scipy(self.to_scipy().T)

    def to_dense(self) -> DenseMatrix:
        return DenseMatrix.from_array(self.to_array())

    def __repr__(self) -> str:
        return f"SparseMatrix({self._num_rows}x{self._num_cols}, nnz={self.num_actives()})"


class Matrices:
    """Factory methods (ref Matrices.scala object Matrices)."""

    @staticmethod
    def dense(num_rows: int, num_cols: int, values) -> DenseMatrix:
        return DenseMatrix(num_rows, num_cols, values)

    @staticmethod
    def sparse(num_rows: int, num_cols: int, colptrs, row_indices, values) -> SparseMatrix:
        return SparseMatrix(num_rows, num_cols, colptrs, row_indices, values)

    @staticmethod
    def from_array(arr: np.ndarray) -> DenseMatrix:
        return DenseMatrix.from_array(arr)

    @staticmethod
    def zeros(num_rows: int, num_cols: int) -> DenseMatrix:
        return DenseMatrix.from_array(np.zeros((num_rows, num_cols)))

    @staticmethod
    def ones(num_rows: int, num_cols: int) -> DenseMatrix:
        return DenseMatrix.from_array(np.ones((num_rows, num_cols)))

    @staticmethod
    def eye(n: int) -> DenseMatrix:
        return DenseMatrix.from_array(np.eye(n))

    @staticmethod
    def diag(vector: Vector) -> DenseMatrix:
        return DenseMatrix.from_array(np.diag(vector.to_array()))

    @staticmethod
    def horzcat(matrices) -> DenseMatrix:
        return DenseMatrix.from_array(np.hstack([m.to_array() for m in matrices]))

    @staticmethod
    def vertcat(matrices) -> DenseMatrix:
        return DenseMatrix.from_array(np.vstack([m.to_array() for m in matrices]))
