"""2-D distributed matrices: BlockMatrix / CoordinateMatrix / IndexedRowMatrix.

Re-design of ``mllib/linalg/distributed`` (ref: BlockMatrix.scala,
CoordinateMatrix.scala, IndexedRowMatrix.scala). The reference's BlockMatrix
is an RDD of ((blockRow, blockCol) → Matrix) with a GridPartitioner, and
multiply is a hand-built block-join + shuffle + per-block gemm + reduce. On
TPU none of that machinery is needed: a BlockMatrix is **one dense device
array with a 2-D NamedSharding** — rows over the (replica, data) mesh axes,
columns over the model axis. ``multiply`` is a single sharded ``jnp.dot``:
XLA inserts the all-gathers/reduce-scatters that the reference's
simulateMultiply/cogroup pipeline (BlockMatrix.scala:477) does by hand, and
the per-block gemms land on the MXU. "Blocks" (rowsPerBlock × colsPerBlock)
are exactly the per-device shards.

CoordinateMatrix keeps host COO entries (the ingest form) and converts;
IndexedRowMatrix pairs an int64 row-index vector with row-sharded data.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import numpy as np

from cycloneml_tpu.linalg.distributed import RowMatrix
from cycloneml_tpu.linalg.matrices import DenseMatrix
from cycloneml_tpu.mesh import DATA_AXIS, MODEL_AXIS, REPLICA_AXIS


def _grid_sharding(rt):
    from jax.sharding import NamedSharding, PartitionSpec as P
    return NamedSharding(rt.mesh, P((REPLICA_AXIS, DATA_AXIS), MODEL_AXIS))


def _pad_to(arr: np.ndarray, rm: int, cm: int) -> np.ndarray:
    m = ((arr.shape[0] + rm - 1) // rm) * rm
    n = ((arr.shape[1] + cm - 1) // cm) * cm
    if (m, n) == arr.shape:
        return arr
    out = np.zeros((m, n), dtype=arr.dtype)
    out[: arr.shape[0], : arr.shape[1]] = arr
    return out


class BlockMatrix:
    """Grid-sharded dense distributed matrix (ref BlockMatrix.scala:132)."""

    def __init__(self, ctx, arr, num_rows: int, num_cols: int):
        self.ctx = ctx
        self._arr = arr  # (m_pad, n_pad) device array, 2-D sharded
        self._num_rows = num_rows
        self._num_cols = num_cols

    # -- construction ----------------------------------------------------------
    @classmethod
    def from_numpy(cls, ctx, a: np.ndarray, dtype=None) -> "BlockMatrix":
        import jax
        from cycloneml_tpu.dataset.instance import compute_dtype
        rt = ctx.mesh_runtime
        dtype = dtype or compute_dtype()
        rows_mult = rt.data_parallelism * 8
        cols_mult = rt.mesh.devices.shape[2] * 8
        pad = _pad_to(np.asarray(a, dtype=dtype), rows_mult, cols_mult)
        arr = jax.device_put(pad, _grid_sharding(rt))
        return cls(ctx, arr, a.shape[0], a.shape[1])

    @property
    def rows_per_block(self) -> int:
        """Per-device shard height — the physical block size (metadata parity
        with ref rowsPerBlock)."""
        return self._arr.shape[0] // self.ctx.mesh_runtime.data_parallelism

    @property
    def cols_per_block(self) -> int:
        return self._arr.shape[1] // self.ctx.mesh_runtime.mesh.devices.shape[2]

    def num_rows(self) -> int:
        return self._num_rows

    def num_cols(self) -> int:
        return self._num_cols

    def validate(self) -> None:
        """(ref validate:199) — shape/sharding invariants."""
        assert self._arr.shape[0] >= self._num_rows
        assert self._arr.shape[1] >= self._num_cols

    # -- algebra (each one sharded jit program; XLA plans the collectives) -----
    def _ewise(self, other: "BlockMatrix", op) -> "BlockMatrix":
        import jax
        if (self._num_rows, self._num_cols) != (other._num_rows, other._num_cols):
            raise ValueError("dimension mismatch")
        # physical pads can differ between construction paths (from_numpy
        # pads to mesh multiples; transpose/multiply outputs keep theirs) —
        # align to the common physical shape before the elementwise op
        m = max(self._arr.shape[0], other._arr.shape[0])
        n = max(self._arr.shape[1], other._arr.shape[1])
        a = _pad_device_rows(_pad_device_cols(self._arr, n), m)
        b = _pad_device_rows(_pad_device_cols(other._arr, n), m)
        out = jax.jit(op)(a, b)
        return BlockMatrix(self.ctx, out, self._num_rows, self._num_cols)

    def add(self, other: "BlockMatrix") -> "BlockMatrix":
        return self._ewise(other, lambda a, b: a + b)

    def subtract(self, other: "BlockMatrix") -> "BlockMatrix":
        return self._ewise(other, lambda a, b: a - b)

    def scale(self, alpha: float) -> "BlockMatrix":
        import jax
        return BlockMatrix(self.ctx, jax.jit(lambda a: a * alpha)(self._arr),
                           self._num_rows, self._num_cols)

    def multiply(self, other: "BlockMatrix") -> "BlockMatrix":
        """A @ B as one sharded matmul (replaces simulateMultiply + shuffle,
        ref BlockMatrix.scala:477)."""
        import jax
        import jax.numpy as jnp
        if self._num_cols != other._num_rows:
            raise ValueError(
                f"A.cols({self._num_cols}) != B.rows({other._num_rows})")
        rt = self.ctx.mesh_runtime
        k = max(self._arr.shape[1], other._arr.shape[0])
        a = _pad_device_cols(self._arr, k)
        b = _pad_device_rows(other._arr, k)
        out_sh = _grid_sharding(rt)
        f = jax.jit(lambda x, y: jax.lax.with_sharding_constraint(
            jnp.dot(x, y, precision=jax.lax.Precision.HIGHEST), out_sh))
        return BlockMatrix(self.ctx, f(a, b), self._num_rows, other._num_cols)

    def transpose(self) -> "BlockMatrix":
        import jax
        rt = self.ctx.mesh_runtime
        out_sh = _grid_sharding(rt)
        f = jax.jit(lambda x: jax.lax.with_sharding_constraint(x.T, out_sh))
        return BlockMatrix(self.ctx, f(self._arr), self._num_cols, self._num_rows)

    # -- conversions -----------------------------------------------------------
    def to_local_matrix(self) -> DenseMatrix:
        a = np.asarray(self._arr)[: self._num_rows, : self._num_cols]
        return DenseMatrix.from_array(np.asarray(a, dtype=np.float64))

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self._arr)[: self._num_rows, : self._num_cols]

    def to_indexed_row_matrix(self) -> "IndexedRowMatrix":
        return IndexedRowMatrix.from_numpy(
            self.ctx, np.arange(self._num_rows, dtype=np.int64), self.to_numpy())

    def to_coordinate_matrix(self) -> "CoordinateMatrix":
        a = self.to_numpy()
        i, j = np.nonzero(a)
        return CoordinateMatrix(self.ctx, i.astype(np.int64), j.astype(np.int64),
                                a[i, j], self._num_rows, self._num_cols)


def _pad_device_cols(arr, k: int):
    import jax.numpy as jnp
    if arr.shape[1] == k:
        return arr
    return jnp.pad(arr, ((0, 0), (0, k - arr.shape[1])))


def _pad_device_rows(arr, k: int):
    import jax.numpy as jnp
    if arr.shape[0] == k:
        return arr
    return jnp.pad(arr, ((0, k - arr.shape[0]), (0, 0)))


class MatrixEntry(NamedTuple):
    i: int
    j: int
    value: float


class CoordinateMatrix:
    """COO-form distributed matrix (ref CoordinateMatrix.scala:52) — the
    ingest format for very sparse data; converts to the dense sharded forms
    for compute (XLA needs static dense shapes; SURVEY §7 sparse note)."""

    def __init__(self, ctx, rows: np.ndarray, cols: np.ndarray,
                 values: np.ndarray, num_rows: Optional[int] = None,
                 num_cols: Optional[int] = None):
        self.ctx = ctx
        self.rows = np.asarray(rows, dtype=np.int64)
        self.cols = np.asarray(cols, dtype=np.int64)
        self.values = np.asarray(values, dtype=np.float64)
        self._num_rows = int(num_rows if num_rows is not None
                             else (self.rows.max(initial=-1) + 1))
        self._num_cols = int(num_cols if num_cols is not None
                             else (self.cols.max(initial=-1) + 1))

    @classmethod
    def from_entries(cls, ctx, entries, num_rows=None, num_cols=None):
        e = [(int(i), int(j), float(v)) for i, j, v in entries]
        return cls(ctx, np.array([x[0] for x in e]), np.array([x[1] for x in e]),
                   np.array([x[2] for x in e]), num_rows, num_cols)

    def entries(self):
        return [MatrixEntry(int(i), int(j), float(v))
                for i, j, v in zip(self.rows, self.cols, self.values)]

    def num_rows(self) -> int:
        return self._num_rows

    def num_cols(self) -> int:
        return self._num_cols

    def transpose(self) -> "CoordinateMatrix":
        return CoordinateMatrix(self.ctx, self.cols, self.rows, self.values,
                                self._num_cols, self._num_rows)

    def to_numpy(self) -> np.ndarray:
        a = np.zeros((self._num_rows, self._num_cols))
        np.add.at(a, (self.rows, self.cols), self.values)
        return a

    def to_block_matrix(self) -> BlockMatrix:
        return BlockMatrix.from_numpy(self.ctx, self.to_numpy())

    def to_indexed_row_matrix(self) -> "IndexedRowMatrix":
        return IndexedRowMatrix.from_numpy(
            self.ctx, np.arange(self._num_rows, dtype=np.int64), self.to_numpy())

    def to_row_matrix(self) -> RowMatrix:
        return RowMatrix.from_numpy(self.ctx, self.to_numpy())


class IndexedRowMatrix:
    """Row-indexed distributed matrix (ref IndexedRowMatrix.scala:45):
    a RowMatrix whose rows carry meaningful int64 indices."""

    def __init__(self, ctx, indices: np.ndarray, row_matrix: RowMatrix,
                 num_rows: Optional[int] = None):
        self.ctx = ctx
        self.indices = np.asarray(indices, dtype=np.int64)
        self.row_matrix = row_matrix
        self._num_rows = int(num_rows if num_rows is not None
                             else (self.indices.max(initial=-1) + 1))

    @classmethod
    def from_numpy(cls, ctx, indices: np.ndarray, x: np.ndarray,
                   num_rows: Optional[int] = None) -> "IndexedRowMatrix":
        return cls(ctx, indices, RowMatrix.from_numpy(ctx, x), num_rows)

    def num_rows(self) -> int:
        return self._num_rows

    def num_cols(self) -> int:
        return self.row_matrix.num_cols()

    def compute_gramian_matrix(self) -> DenseMatrix:
        return self.row_matrix.compute_gramian()

    def compute_svd(self, k: int, compute_u: bool = False, **kw):
        return self.row_matrix.compute_svd(k, compute_u=compute_u, **kw)

    def multiply(self, b) -> "IndexedRowMatrix":
        return IndexedRowMatrix(self.ctx, self.indices,
                                self.row_matrix.multiply(b), self._num_rows)

    def column_similarities(self) -> DenseMatrix:
        return self.row_matrix.column_similarities()

    def to_row_matrix(self) -> RowMatrix:
        return self.row_matrix

    def to_numpy(self) -> np.ndarray:
        """Dense (num_rows, num_cols) with rows placed at their indices."""
        stored = self.row_matrix.to_numpy()
        out = np.zeros((self._num_rows, stored.shape[1]), dtype=stored.dtype)
        out[self.indices] = stored
        return out

    def to_block_matrix(self) -> BlockMatrix:
        return BlockMatrix.from_numpy(self.ctx, self.to_numpy())

    def to_coordinate_matrix(self) -> CoordinateMatrix:
        a = self.to_numpy()
        i, j = np.nonzero(a)
        return CoordinateMatrix(self.ctx, i, j, a[i, j],
                                self._num_rows, a.shape[1])
