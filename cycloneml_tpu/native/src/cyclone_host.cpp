// cyclone_host — native host-side runtime for the TPU framework.
//
// TPU-native equivalents of the reference's JNI substrate (SURVEY §2.6):
//   * loader: multithreaded libsvm/CSV → dense buffers (replaces the
//     HadoopRDD/text ingest path feeding MLUtils.loadLibSVMFile).
//   * codec: zstd (linked) + lz4 (dlopen'd) block compression — the
//     CompressionCodec plugin point (ref: core/.../io/CompressionCodec.scala:63)
//     for spill/checkpoint/event-log streams.
//   * kvstore: log-structured append-only KV with in-memory index and
//     compaction — the common/kvstore LevelDB.java analog backing the
//     status store / history provider.
//
// Pure C ABI (loaded via ctypes; no pybind11 in the image). All functions
// are thread-safe at the handle level.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dlfcn.h>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <zstd.h>

extern "C" {

// ---------------------------------------------------------------------------
// loader
// ---------------------------------------------------------------------------

struct SvmRow {
  float label;
  std::vector<std::pair<int32_t, float>> feats;
};

struct SvmFile {
  std::vector<SvmRow> rows;
  int64_t n_features = 0;
};

static void parse_svm_range(const char* data, size_t begin, size_t end,
                            std::vector<SvmRow>* out, int64_t* max_idx) {
  size_t pos = begin;
  int64_t local_max = -1;
  while (pos < end) {
    size_t eol = pos;
    while (eol < end && data[eol] != '\n') eol++;
    const char* p = data + pos;
    const char* stop = data + eol;
    pos = eol + 1;
    while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    if (p >= stop || *p == '#') continue;
    SvmRow row;
    char* next = nullptr;
    row.label = strtof(p, &next);
    if (next == p) continue;
    p = next;
    while (p < stop) {
      while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
      if (p >= stop) break;
      long idx = strtol(p, &next, 10);
      if (next == p || *next != ':') break;
      p = next + 1;
      float v = strtof(p, &next);
      if (next == p) break;
      p = next;
      row.feats.emplace_back((int32_t)(idx - 1), v);  // libsvm is 1-based
      if (idx - 1 > local_max) local_max = idx - 1;
    }
    out->push_back(std::move(row));
  }
  *max_idx = local_max;
}

// Parse whole file with n threads; returns handle, row/feature counts.
void* svm_open(const char* path, int n_threads, int64_t* n_rows,
               int64_t* n_features) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return nullptr;
  size_t size = (size_t)f.tellg();
  f.seekg(0);
  std::vector<char> buf(size);
  if (size && !f.read(buf.data(), size)) return nullptr;

  int nt = n_threads > 0 ? n_threads
                         : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (size < (size_t)(nt * 4096)) nt = 1;

  // chunk boundaries snapped to newlines
  std::vector<size_t> bounds(nt + 1, 0);
  bounds[nt] = size;
  for (int i = 1; i < nt; i++) {
    size_t b = size * i / nt;
    while (b < size && buf[b] != '\n') b++;
    bounds[i] = b < size ? b + 1 : size;
  }
  std::vector<std::vector<SvmRow>> parts(nt);
  std::vector<int64_t> maxes(nt, -1);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; i++)
    threads.emplace_back(parse_svm_range, buf.data(), bounds[i], bounds[i + 1],
                         &parts[i], &maxes[i]);
  for (auto& t : threads) t.join();

  auto* out = new SvmFile();
  int64_t mx = -1;
  for (int i = 0; i < nt; i++) {
    if (maxes[i] > mx) mx = maxes[i];
    for (auto& r : parts[i]) out->rows.push_back(std::move(r));
  }
  out->n_features = mx + 1;
  *n_rows = (int64_t)out->rows.size();
  *n_features = out->n_features;
  return out;
}

// Fill dense row-major x (n_rows × n_features) and y (n_rows).
int svm_fill(void* h, float* x, float* y, int64_t n_rows, int64_t n_features) {
  auto* f = (SvmFile*)h;
  if ((int64_t)f->rows.size() != n_rows) return -1;
  memset(x, 0, sizeof(float) * (size_t)(n_rows * n_features));
  for (int64_t r = 0; r < n_rows; r++) {
    y[r] = f->rows[r].label;
    float* row = x + r * n_features;
    for (auto& kv : f->rows[r].feats)
      if (kv.first >= 0 && kv.first < n_features) row[kv.first] = kv.second;
  }
  return 0;
}

void svm_free(void* h) { delete (SvmFile*)h; }

// CSV: numeric rectangular parse. Returns handle + dims.
struct CsvFile {
  std::vector<std::vector<double>> rows;
  int64_t n_cols = 0;
};

static void parse_csv_range(const char* data, size_t begin, size_t end,
                            char delim, std::vector<std::vector<double>>* out) {
  size_t pos = begin;
  while (pos < end) {
    size_t eol = pos;
    while (eol < end && data[eol] != '\n') eol++;
    const char* p = data + pos;
    const char* stop = data + eol;
    pos = eol + 1;
    while (p < stop && (*p == ' ' || *p == '\r')) p++;
    if (p >= stop) continue;
    std::vector<double> row;
    while (p < stop) {
      char* next = nullptr;
      double v = strtod(p, &next);
      if (next == p) { // non-numeric cell → NaN, skip to delim
        v = NAN;
        next = (char*)p;
        while (next < stop && *next != delim) next++;
      }
      row.push_back(v);
      p = next;
      while (p < stop && *p != delim) p++;
      if (p < stop) p++;  // skip delim
    }
    if (!row.empty()) out->push_back(std::move(row));
  }
}

void* csv_open(const char* path, char delim, int skip_header, int n_threads,
               int64_t* n_rows, int64_t* n_cols) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return nullptr;
  size_t size = (size_t)f.tellg();
  f.seekg(0);
  std::vector<char> buf(size);
  if (size && !f.read(buf.data(), size)) return nullptr;
  size_t start = 0;
  if (skip_header) {
    while (start < size && buf[start] != '\n') start++;
    if (start < size) start++;
  }
  int nt = n_threads > 0 ? n_threads
                         : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (size - start < (size_t)(nt * 4096)) nt = 1;
  std::vector<size_t> bounds(nt + 1, start);
  bounds[nt] = size;
  for (int i = 1; i < nt; i++) {
    size_t b = start + (size - start) * i / nt;
    while (b < size && buf[b] != '\n') b++;
    bounds[i] = b < size ? b + 1 : size;
  }
  std::vector<std::vector<std::vector<double>>> parts(nt);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; i++)
    threads.emplace_back(parse_csv_range, buf.data(), bounds[i], bounds[i + 1],
                         delim, &parts[i]);
  for (auto& t : threads) t.join();
  auto* out = new CsvFile();
  for (auto& p : parts)
    for (auto& r : p) out->rows.push_back(std::move(r));
  int64_t nc = 0;
  for (auto& r : out->rows)
    if ((int64_t)r.size() > nc) nc = (int64_t)r.size();
  out->n_cols = nc;
  *n_rows = (int64_t)out->rows.size();
  *n_cols = nc;
  return out;
}

int csv_fill(void* h, double* x, int64_t n_rows, int64_t n_cols) {
  auto* f = (CsvFile*)h;
  if ((int64_t)f->rows.size() != n_rows) return -1;
  for (int64_t r = 0; r < n_rows; r++) {
    double* row = x + r * n_cols;
    for (int64_t c = 0; c < n_cols; c++)
      row[c] = c < (int64_t)f->rows[r].size() ? f->rows[r][c] : 0.0;
  }
  return 0;
}

void csv_free(void* h) { delete (CsvFile*)h; }

// ---------------------------------------------------------------------------
// codec (ref CompressionCodec.scala:63-71 — zstd & lz4 block codecs)
// ---------------------------------------------------------------------------

int64_t codec_zstd_bound(int64_t n) { return (int64_t)ZSTD_compressBound((size_t)n); }

int64_t codec_zstd_compress(const void* src, int64_t n, void* dst, int64_t cap,
                            int level) {
  size_t r = ZSTD_compress(dst, (size_t)cap, src, (size_t)n, level);
  return ZSTD_isError(r) ? -1 : (int64_t)r;
}

int64_t codec_zstd_decompress(const void* src, int64_t n, void* dst, int64_t cap) {
  size_t r = ZSTD_decompress(dst, (size_t)cap, src, (size_t)n);
  return ZSTD_isError(r) ? -1 : (int64_t)r;
}

// lz4 via dlopen (liblz4.so.1 ships without headers/link-name in this image)
typedef int (*lz4_compress_fn)(const char*, char*, int, int);
typedef int (*lz4_decompress_fn)(const char*, char*, int, int);
typedef int (*lz4_bound_fn)(int);

static std::once_flag lz4_once;
static lz4_compress_fn lz4_compress_p = nullptr;
static lz4_decompress_fn lz4_decompress_p = nullptr;
static lz4_bound_fn lz4_bound_p = nullptr;

static void lz4_init() {
  void* lib = dlopen("liblz4.so.1", RTLD_NOW | RTLD_GLOBAL);
  if (!lib) lib = dlopen("liblz4.so", RTLD_NOW | RTLD_GLOBAL);
  if (!lib) return;
  lz4_compress_p = (lz4_compress_fn)dlsym(lib, "LZ4_compress_default");
  lz4_decompress_p = (lz4_decompress_fn)dlsym(lib, "LZ4_decompress_safe");
  lz4_bound_p = (lz4_bound_fn)dlsym(lib, "LZ4_compressBound");
}

int codec_lz4_available() {
  std::call_once(lz4_once, lz4_init);
  return lz4_compress_p && lz4_decompress_p && lz4_bound_p ? 1 : 0;
}

int64_t codec_lz4_bound(int64_t n) {
  if (!codec_lz4_available()) return -1;
  return (int64_t)lz4_bound_p((int)n);
}

int64_t codec_lz4_compress(const void* src, int64_t n, void* dst, int64_t cap) {
  if (!codec_lz4_available()) return -1;
  int r = lz4_compress_p((const char*)src, (char*)dst, (int)n, (int)cap);
  return r <= 0 ? -1 : (int64_t)r;
}

int64_t codec_lz4_decompress(const void* src, int64_t n, void* dst, int64_t cap) {
  if (!codec_lz4_available()) return -1;
  int r = lz4_decompress_p((const char*)src, (char*)dst, (int)n, (int)cap);
  return r < 0 ? -1 : (int64_t)r;
}

// ---------------------------------------------------------------------------
// kvstore (ref common/kvstore/.../LevelDB.java) — log-structured file KV
// ---------------------------------------------------------------------------
// Record: [u32 klen][u32 vlen][key][value]; vlen == 0xFFFFFFFF is a tombstone.

struct KvStore {
  std::string path;
  FILE* f = nullptr;
  std::unordered_map<std::string, std::pair<int64_t, uint32_t>> index;  // key → (value offset, vlen)
  std::mutex mu;
  int64_t live_bytes = 0, total_bytes = 0;
};

static const uint32_t KV_TOMBSTONE = 0xFFFFFFFFu;

static bool kv_load_index(KvStore* s) {
  fseeko(s->f, 0, SEEK_SET);
  int64_t pos = 0;
  uint32_t hdr[2];
  std::vector<char> kbuf;
  for (;;) {
    if (fread(hdr, sizeof(uint32_t), 2, s->f) != 2) break;
    uint32_t klen = hdr[0], vlen = hdr[1];
    kbuf.resize(klen);
    if (klen && fread(kbuf.data(), 1, klen, s->f) != klen) break;
    int64_t voff = pos + 8 + klen;
    std::string key(kbuf.data(), klen);
    if (vlen == KV_TOMBSTONE) {
      auto it = s->index.find(key);
      if (it != s->index.end()) {
        s->live_bytes -= 8 + klen + it->second.second;
        s->index.erase(it);
      }
      pos = voff;
    } else {
      if (fseeko(s->f, vlen, SEEK_CUR) != 0) break;
      auto it = s->index.find(key);
      if (it != s->index.end()) s->live_bytes -= 8 + klen + it->second.second;
      s->index[key] = {voff, vlen};
      s->live_bytes += 8 + klen + vlen;
      pos = voff + vlen;
    }
  }
  s->total_bytes = pos;
  // truncate any torn tail write
  fseeko(s->f, pos, SEEK_SET);
  return true;
}

void* kv_open(const char* path) {
  auto* s = new KvStore();
  s->path = path;
  s->f = fopen(path, "a+b");
  if (!s->f) { delete s; return nullptr; }
  kv_load_index(s);
  fseeko(s->f, s->total_bytes, SEEK_SET);
  return s;
}

int kv_put(void* h, const void* k, int64_t klen, const void* v, int64_t vlen) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  fseeko(s->f, s->total_bytes, SEEK_SET);
  uint32_t hdr[2] = {(uint32_t)klen, (uint32_t)vlen};
  if (fwrite(hdr, sizeof(uint32_t), 2, s->f) != 2) return -1;
  if (klen && fwrite(k, 1, (size_t)klen, s->f) != (size_t)klen) return -1;
  if (vlen && fwrite(v, 1, (size_t)vlen, s->f) != (size_t)vlen) return -1;
  std::string key((const char*)k, (size_t)klen);
  auto it = s->index.find(key);
  if (it != s->index.end()) s->live_bytes -= 8 + klen + it->second.second;
  s->index[key] = {s->total_bytes + 8 + klen, (uint32_t)vlen};
  s->total_bytes += 8 + klen + vlen;
  s->live_bytes += 8 + klen + vlen;
  return 0;
}

int64_t kv_get(void* h, const void* k, int64_t klen, void* out, int64_t cap) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(std::string((const char*)k, (size_t)klen));
  if (it == s->index.end()) return -1;
  uint32_t vlen = it->second.second;
  if ((int64_t)vlen > cap) return (int64_t)vlen;  // caller re-calls with room
  fflush(s->f);
  fseeko(s->f, it->second.first, SEEK_SET);
  if (vlen && fread(out, 1, vlen, s->f) != vlen) return -1;
  fseeko(s->f, s->total_bytes, SEEK_SET);
  return (int64_t)vlen;
}

int kv_delete(void* h, const void* k, int64_t klen) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string key((const char*)k, (size_t)klen);
  auto it = s->index.find(key);
  if (it == s->index.end()) return -1;
  fseeko(s->f, s->total_bytes, SEEK_SET);
  uint32_t hdr[2] = {(uint32_t)klen, KV_TOMBSTONE};
  fwrite(hdr, sizeof(uint32_t), 2, s->f);
  fwrite(k, 1, (size_t)klen, s->f);
  s->live_bytes -= 8 + klen + it->second.second;
  s->index.erase(it);
  s->total_bytes += 8 + klen;
  return 0;
}

int64_t kv_count(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  return (int64_t)s->index.size();
}

int kv_flush(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  return fflush(s->f);
}

// Rewrite only live records (ref LevelDB compaction); returns 0 on success.
int kv_compact(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string tmp = s->path + ".compact";
  FILE* nf = fopen(tmp.c_str(), "wb");
  if (!nf) return -1;
  fflush(s->f);
  std::unordered_map<std::string, std::pair<int64_t, uint32_t>> nindex;
  int64_t pos = 0;
  std::vector<char> vbuf;
  for (auto& kv : s->index) {
    uint32_t vlen = kv.second.second;
    vbuf.resize(vlen);
    fseeko(s->f, kv.second.first, SEEK_SET);
    if (vlen && fread(vbuf.data(), 1, vlen, s->f) != vlen) { fclose(nf); return -1; }
    uint32_t hdr[2] = {(uint32_t)kv.first.size(), vlen};
    fwrite(hdr, sizeof(uint32_t), 2, nf);
    fwrite(kv.first.data(), 1, kv.first.size(), nf);
    if (vlen) fwrite(vbuf.data(), 1, vlen, nf);
    nindex[kv.first] = {pos + 8 + (int64_t)kv.first.size(), vlen};
    pos += 8 + kv.first.size() + vlen;
  }
  fclose(nf);
  fclose(s->f);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    s->f = fopen(s->path.c_str(), "a+b");
    return -1;
  }
  s->f = fopen(s->path.c_str(), "a+b");
  s->index = std::move(nindex);
  s->total_bytes = s->live_bytes = pos;
  return 0;
}

struct KvIter {
  KvStore* s;
  std::vector<std::string> keys;
  size_t pos = 0;
};

void* kv_iter(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto* it = new KvIter();
  it->s = s;
  it->keys.reserve(s->index.size());
  for (auto& kv : s->index) it->keys.push_back(kv.first);
  return it;
}

// Writes next key into kbuf; returns klen, or -1 at end, or required size if
// kcap too small (iterator does not advance in that case).
int64_t kv_iter_next(void* hi, void* kbuf, int64_t kcap) {
  auto* it = (KvIter*)hi;
  if (it->pos >= it->keys.size()) return -1;
  const std::string& k = it->keys[it->pos];
  if ((int64_t)k.size() > kcap) return (int64_t)k.size();
  memcpy(kbuf, k.data(), k.size());
  it->pos++;
  return (int64_t)k.size();
}

void kv_iter_free(void* hi) { delete (KvIter*)hi; }

void kv_close(void* h) {
  auto* s = (KvStore*)h;
  if (s->f) fclose(s->f);
  delete s;
}

}  // extern "C"
