// cyclone_host — native host-side runtime for the TPU framework.
//
// TPU-native equivalents of the reference's JNI substrate (SURVEY §2.6):
//   * loader: multithreaded libsvm/CSV → dense buffers (replaces the
//     HadoopRDD/text ingest path feeding MLUtils.loadLibSVMFile).
//   * codec: zstd (linked) + lz4 (dlopen'd) block compression — the
//     CompressionCodec plugin point (ref: core/.../io/CompressionCodec.scala:63)
//     for spill/checkpoint/event-log streams.
//   * kvstore: log-structured append-only KV with in-memory index and
//     compaction — the common/kvstore LevelDB.java analog backing the
//     status store / history provider.
//
// Pure C ABI (loaded via ctypes; no pybind11 in the image). All functions
// are thread-safe at the handle level.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dlfcn.h>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include <zstd.h>

extern "C" {

// ---------------------------------------------------------------------------
// loader
// ---------------------------------------------------------------------------

struct SvmRow {
  double label;  // f64: regression targets must survive the round trip
  std::vector<std::pair<int32_t, float>> feats;
};

struct SvmFile {
  std::vector<SvmRow> rows;
  int64_t n_features = 0;
};

static void parse_svm_range(const char* data, size_t begin, size_t end,
                            std::vector<SvmRow>* out, int64_t* max_idx) {
  size_t pos = begin;
  int64_t local_max = -1;
  while (pos < end) {
    size_t eol = pos;
    while (eol < end && data[eol] != '\n') eol++;
    const char* p = data + pos;
    const char* stop = data + eol;
    pos = eol + 1;
    while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
    if (p >= stop || *p == '#') continue;
    SvmRow row;
    char* next = nullptr;
    row.label = strtod(p, &next);
    if (next == p) continue;
    p = next;
    while (p < stop) {
      while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
      if (p >= stop) break;
      long idx = strtol(p, &next, 10);
      if (next == p || *next != ':') break;
      p = next + 1;
      float v = strtof(p, &next);
      if (next == p) break;
      p = next;
      row.feats.emplace_back((int32_t)(idx - 1), v);  // libsvm is 1-based
      if (idx - 1 > local_max) local_max = idx - 1;
    }
    out->push_back(std::move(row));
  }
  *max_idx = local_max;
}

// Flat CSR output for the STREAM path: per-row std::vector allocations in
// SvmRow dominate single-core parse time at Criteo row rates; the flat
// form appends into four growing arrays and hands chunks out via memcpy.
struct SvmFlat {
  std::vector<double> y;
  std::vector<int32_t> nnz;
  std::vector<int32_t> idx;
  std::vector<float> val;
};

static inline const char* svm_skip_ws(const char* p, const char* stop) {
  while (p < stop && (*p == ' ' || *p == '\t' || *p == '\r')) p++;
  return p;
}

static void parse_svm_range_flat(const char* data, size_t begin, size_t end,
                                 SvmFlat* out, int64_t* max_idx) {
  size_t pos = begin;
  int64_t local_max = -1;
  while (pos < end) {
    size_t eol = pos;
    while (eol < end && data[eol] != '\n') eol++;
    const char* p = data + pos;
    const char* stop = data + eol;
    pos = eol + 1;
    p = svm_skip_ws(p, stop);
    if (p >= stop || *p == '#') continue;
    char* next = nullptr;
    double label = strtod(p, &next);
    if (next == p) continue;
    p = next;
    int32_t count = 0;
    while (p < stop) {
      p = svm_skip_ws(p, stop);
      if (p >= stop) break;
      // manual index parse (strtol's locale/overflow machinery is the
      // single hottest line at tens of millions of tokens)
      const char* q = p;
      bool neg = false;
      if (*q == '-' || *q == '+') { neg = (*q == '-'); q++; }
      const char* d0 = q;
      long idxv = 0;
      while (q < stop && *q >= '0' && *q <= '9') {
        idxv = idxv * 10 + (*q - '0');
        q++;
      }
      if (q == d0 || q - d0 > 18 || q >= stop || *q != ':') break;
      if (neg) idxv = -idxv;
      p = q + 1;
      // fast value path: a plain integer token (the common hashed-count
      // case) converts directly; anything else falls back to strtof
      float v;
      q = p;
      neg = false;
      if (q < stop && (*q == '-' || *q == '+')) { neg = (*q == '-'); q++; }
      d0 = q;
      long mant = 0;
      while (q < stop && *q >= '0' && *q <= '9') {
        mant = mant * 10 + (*q - '0');
        q++;
      }
      if (q > d0 && q - d0 <= 18 &&
          (q >= stop || *q == ' ' || *q == '\t' || *q == '\r')) {
        v = (float)(neg ? -mant : mant);
        p = q;
      } else {
        v = strtof(p, &next);
        if (next == p) break;
        p = next;
      }
      out->idx.push_back((int32_t)(idxv - 1));  // libsvm is 1-based
      out->val.push_back(v);
      count++;
      if (idxv - 1 > local_max) local_max = idxv - 1;
    }
    out->y.push_back(label);
    out->nnz.push_back(count);
  }
  *max_idx = local_max;
}

// Parse whole file with n threads; returns handle, row/feature counts.
void* svm_open(const char* path, int n_threads, int64_t* n_rows,
               int64_t* n_features) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return nullptr;
  size_t size = (size_t)f.tellg();
  f.seekg(0);
  std::vector<char> buf(size);
  if (size && !f.read(buf.data(), size)) return nullptr;

  int nt = n_threads > 0 ? n_threads
                         : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (size < (size_t)(nt * 4096)) nt = 1;

  // chunk boundaries snapped to newlines
  std::vector<size_t> bounds(nt + 1, 0);
  bounds[nt] = size;
  for (int i = 1; i < nt; i++) {
    size_t b = size * i / nt;
    while (b < size && buf[b] != '\n') b++;
    bounds[i] = b < size ? b + 1 : size;
  }
  std::vector<std::vector<SvmRow>> parts(nt);
  std::vector<int64_t> maxes(nt, -1);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; i++)
    threads.emplace_back(parse_svm_range, buf.data(), bounds[i], bounds[i + 1],
                         &parts[i], &maxes[i]);
  for (auto& t : threads) t.join();

  auto* out = new SvmFile();
  int64_t mx = -1;
  for (int i = 0; i < nt; i++) {
    if (maxes[i] > mx) mx = maxes[i];
    for (auto& r : parts[i]) out->rows.push_back(std::move(r));
  }
  out->n_features = mx + 1;
  *n_rows = (int64_t)out->rows.size();
  *n_features = out->n_features;
  return out;
}

// Fill dense row-major x (n_rows × n_features) and y (n_rows).
int svm_fill(void* h, float* x, float* y, int64_t n_rows, int64_t n_features) {
  auto* f = (SvmFile*)h;
  if ((int64_t)f->rows.size() != n_rows) return -1;
  memset(x, 0, sizeof(float) * (size_t)(n_rows * n_features));
  for (int64_t r = 0; r < n_rows; r++) {
    y[r] = (float)f->rows[r].label;
    float* row = x + r * n_features;
    for (auto& kv : f->rows[r].feats)
      if (kv.first >= 0 && kv.first < n_features) row[kv.first] = kv.second;
  }
  return 0;
}

void svm_free(void* h) { delete (SvmFile*)h; }

// -- streaming libsvm (bounded memory) --------------------------------------
//
// The whole-file loader above materializes every row before filling a dense
// buffer — fine for datasets that fit driver RAM, unusable for the
// Criteo-1TB class. The stream reads a fixed byte window at a time,
// multithread-parses it, and hands rows out chunk-by-chunk in CSR form
// (labels + per-row nnz + flat (index, value) pairs); peak memory is
// O(window + parsed-window rows), independent of file size.

struct SvmStream {
  FILE* f = nullptr;
  std::string carry;  // partial trailing line of the last window
  SvmFlat pend;       // parsed rows not yet handed out (flat CSR)
  size_t prow = 0;    // next pending row
  size_t pnz = 0;     // offset of that row's nonzeros in pend.idx/val
  int64_t buf_bytes;
  int nt;
  bool eof = false;
  int64_t max_idx = -1;  // max feature index seen so far (running)
  int64_t pos = 0;       // absolute file offset of the next unread byte
  int64_t limit = -1;    // split end (-1 = whole file): lines STARTING at
                         // offset <= limit are ours (HadoopRDD
                         // LineRecordReader split semantics)
};

void* svm_stream_open(const char* path, int64_t buf_bytes, int n_threads) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new SvmStream();
  s->f = f;
  s->buf_bytes = buf_bytes > 0 ? buf_bytes : (8 << 20);
  s->nt = n_threads > 0 ? n_threads : (int)std::thread::hardware_concurrency();
  if (s->nt < 1) s->nt = 1;
  return s;
}

void svm_stream_free(void* h);

// Byte-range split reader (ref: core/.../rdd/HadoopRDD.scala:87 +
// LineRecordReader): a split [start, end) skips through the first newline
// when start > 0 (that partial/boundary line belongs to the previous
// split, which reads one line PAST its end), and keeps every line whose
// first byte sits at offset <= end.
void* svm_stream_open_range(const char* path, int64_t buf_bytes,
                            int n_threads, int64_t start, int64_t end) {
  auto* s = (SvmStream*)svm_stream_open(path, buf_bytes, n_threads);
  if (!s) return nullptr;
  if (start > 0) {
    if (fseek(s->f, (long)start, SEEK_SET) != 0) {
      svm_stream_free(s);
      return nullptr;
    }
    s->pos = start;
    // discard through the first newline
    int c;
    while ((c = fgetc(s->f)) != EOF) {
      s->pos++;
      if (c == '\n') break;
    }
    if (c == EOF) s->eof = true;
    // the skip consumed past the split end: every line starting in
    // [start, end] belonged to the previous split's read-one-line-past-
    // end — emitting the next line here would duplicate it with the
    // split that owns it (splits narrower than one line)
    if (end >= 0 && s->pos > end) s->eof = true;
  }
  s->limit = end;
  return s;
}

static bool svm_stream_refill(SvmStream* s) {
  // read windows until one parses to at least one row (comment-only windows
  // and longer-than-window lines retry) or genuine EOF. A loop, not
  // recursion: each skipped window must release its buffer and stack frame
  // before the next (a multi-GB comment region would otherwise hold every
  // window alive at once).
 retry:
  // read one window, snap to the last newline, parse it in parallel
  std::vector<char> buf;
  buf.reserve(s->carry.size() + (size_t)s->buf_bytes);
  buf.insert(buf.end(), s->carry.begin(), s->carry.end());
  s->carry.clear();
  size_t old = buf.size();
  int64_t win_start = s->pos - (int64_t)old;  // abs offset of buf[0]
  buf.resize(old + (size_t)s->buf_bytes);
  size_t got = fread(buf.data() + old, 1, (size_t)s->buf_bytes, s->f);
  buf.resize(old + got);
  s->pos += (int64_t)got;
  if (got < (size_t)s->buf_bytes) s->eof = true;
  if (buf.empty()) return false;

  if (s->limit >= 0 && win_start + (int64_t)buf.size() > s->limit) {
    // split end inside this window: keep through the first newline at
    // abs offset >= limit (the line STARTING at limit is still ours;
    // the next split discards it as its partial first line)
    size_t cut = s->limit > win_start ? (size_t)(s->limit - win_start) : 0;
    while (cut < buf.size() && buf[cut] != '\n') cut++;
    if (cut < buf.size()) {
      buf.resize(cut + 1);
      s->eof = true;
    }
    // newline not in window yet: the final line spills past it — fall
    // through; the carry logic keeps reading until it completes
  }

  size_t end = buf.size();
  if (!s->eof) {
    // hold back the partial final line for the next window
    size_t last_nl = end;
    while (last_nl > 0 && buf[last_nl - 1] != '\n') last_nl--;
    if (last_nl == 0) {
      // a single line longer than the window: grow the carry and retry
      s->carry.assign(buf.begin(), buf.end());
      goto retry;
    }
    s->carry.assign(buf.begin() + last_nl, buf.end());
    end = last_nl;
  }

  int nt = s->nt;
  if (end < (size_t)(nt * 4096)) nt = 1;
  std::vector<size_t> bounds(nt + 1, 0);
  bounds[nt] = end;
  for (int i = 1; i < nt; i++) {
    size_t b = end * i / nt;
    while (b < end && buf[b] != '\n') b++;
    bounds[i] = b < end ? b + 1 : end;
  }
  std::vector<SvmFlat> parts(nt);
  std::vector<int64_t> maxes(nt, -1);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; i++)
    threads.emplace_back(parse_svm_range_flat, buf.data(), bounds[i],
                         bounds[i + 1], &parts[i], &maxes[i]);
  for (auto& t : threads) t.join();
  s->pend.y.clear();
  s->pend.nnz.clear();
  s->pend.idx.clear();
  s->pend.val.clear();
  s->prow = 0;
  s->pnz = 0;
  for (int i = 0; i < nt; i++) {
    if (maxes[i] > s->max_idx) s->max_idx = maxes[i];
    SvmFlat& p = s->pend;
    p.y.insert(p.y.end(), parts[i].y.begin(), parts[i].y.end());
    p.nnz.insert(p.nnz.end(), parts[i].nnz.begin(), parts[i].nnz.end());
    p.idx.insert(p.idx.end(), parts[i].idx.begin(), parts[i].idx.end());
    p.val.insert(p.val.end(), parts[i].val.begin(), parts[i].val.end());
  }
  // a window of only comments/blank lines parses to zero rows; that is not
  // end-of-stream
  if (s->pend.y.empty() && !s->eof) goto retry;
  return !s->pend.y.empty();
}

// Fill up to max_rows rows (CSR: y, row_nnz, flat idx/val capped at cap_nnz).
// Returns rows filled; 0 at end of stream; -2 if a single row's nnz exceeds
// cap_nnz (caller must grow the buffer). max_feature reports the running
// max feature index + 1 over everything parsed so far.
int64_t svm_stream_next(void* h, double* y, int32_t* row_nnz, int32_t* idx,
                        float* val, int64_t max_rows, int64_t cap_nnz,
                        int64_t* max_feature) {
  auto* s = (SvmStream*)h;
  int64_t rows = 0, used = 0;
  while (rows < max_rows) {
    if (s->prow >= s->pend.y.size()) {
      if (s->eof) break;
      if (!svm_stream_refill(s)) break;
      continue;
    }
    // take as many whole pending rows as fit the row and nnz caps, then
    // bulk-copy their flat index/value slices
    size_t take = 0;
    int64_t take_nnz = 0;
    while (s->prow + take < s->pend.y.size() &&
           rows + (int64_t)take < max_rows) {
      int64_t n = s->pend.nnz[s->prow + take];
      if (n > cap_nnz) return -2;
      if (used + take_nnz + n > cap_nnz) break;
      take_nnz += n;
      take++;
    }
    if (take == 0) break;  // chunk full by nnz
    memcpy(y + rows, s->pend.y.data() + s->prow, take * sizeof(double));
    memcpy(row_nnz + rows, s->pend.nnz.data() + s->prow,
           take * sizeof(int32_t));
    memcpy(idx + used, s->pend.idx.data() + s->pnz,
           (size_t)take_nnz * sizeof(int32_t));
    memcpy(val + used, s->pend.val.data() + s->pnz,
           (size_t)take_nnz * sizeof(float));
    rows += (int64_t)take;
    used += take_nnz;
    s->prow += take;
    s->pnz += (size_t)take_nnz;
  }
  if (s->prow >= s->pend.y.size() && s->eof) {
    s->pend = SvmFlat();  // release the last window's rows promptly
    s->prow = 0;
    s->pnz = 0;
  }
  *max_feature = s->max_idx + 1;
  return rows;
}

void svm_stream_free(void* h) {
  auto* s = (SvmStream*)h;
  if (s->f) fclose(s->f);
  delete s;
}

// CSV: numeric rectangular parse. Returns handle + dims.
struct CsvFile {
  std::vector<std::vector<double>> rows;
  int64_t n_cols = 0;
};

static void parse_csv_range(const char* data, size_t begin, size_t end,
                            char delim, std::vector<std::vector<double>>* out) {
  size_t pos = begin;
  while (pos < end) {
    size_t eol = pos;
    while (eol < end && data[eol] != '\n') eol++;
    const char* p = data + pos;
    const char* stop = data + eol;
    pos = eol + 1;
    while (p < stop && (*p == ' ' || *p == '\r')) p++;
    if (p >= stop) continue;
    std::vector<double> row;
    while (p < stop) {
      char* next = nullptr;
      double v = strtod(p, &next);
      if (next == p) { // non-numeric cell → NaN, skip to delim
        v = NAN;
        next = (char*)p;
        while (next < stop && *next != delim) next++;
      }
      row.push_back(v);
      p = next;
      while (p < stop && *p != delim) p++;
      if (p < stop) p++;  // skip delim
    }
    if (!row.empty()) out->push_back(std::move(row));
  }
}

void* csv_open(const char* path, char delim, int skip_header, int n_threads,
               int64_t* n_rows, int64_t* n_cols) {
  std::ifstream f(path, std::ios::binary | std::ios::ate);
  if (!f) return nullptr;
  size_t size = (size_t)f.tellg();
  f.seekg(0);
  std::vector<char> buf(size);
  if (size && !f.read(buf.data(), size)) return nullptr;
  size_t start = 0;
  if (skip_header) {
    while (start < size && buf[start] != '\n') start++;
    if (start < size) start++;
  }
  int nt = n_threads > 0 ? n_threads
                         : (int)std::thread::hardware_concurrency();
  if (nt < 1) nt = 1;
  if (size - start < (size_t)(nt * 4096)) nt = 1;
  std::vector<size_t> bounds(nt + 1, start);
  bounds[nt] = size;
  for (int i = 1; i < nt; i++) {
    size_t b = start + (size - start) * i / nt;
    while (b < size && buf[b] != '\n') b++;
    bounds[i] = b < size ? b + 1 : size;
  }
  std::vector<std::vector<std::vector<double>>> parts(nt);
  std::vector<std::thread> threads;
  for (int i = 0; i < nt; i++)
    threads.emplace_back(parse_csv_range, buf.data(), bounds[i], bounds[i + 1],
                         delim, &parts[i]);
  for (auto& t : threads) t.join();
  auto* out = new CsvFile();
  for (auto& p : parts)
    for (auto& r : p) out->rows.push_back(std::move(r));
  int64_t nc = 0;
  for (auto& r : out->rows)
    if ((int64_t)r.size() > nc) nc = (int64_t)r.size();
  out->n_cols = nc;
  *n_rows = (int64_t)out->rows.size();
  *n_cols = nc;
  return out;
}

int csv_fill(void* h, double* x, int64_t n_rows, int64_t n_cols) {
  auto* f = (CsvFile*)h;
  if ((int64_t)f->rows.size() != n_rows) return -1;
  for (int64_t r = 0; r < n_rows; r++) {
    double* row = x + r * n_cols;
    for (int64_t c = 0; c < n_cols; c++)
      row[c] = c < (int64_t)f->rows[r].size() ? f->rows[r][c] : 0.0;
  }
  return 0;
}

void csv_free(void* h) { delete (CsvFile*)h; }

// ---------------------------------------------------------------------------
// codec (ref CompressionCodec.scala:63-71 — zstd & lz4 block codecs)
// ---------------------------------------------------------------------------

int64_t codec_zstd_bound(int64_t n) { return (int64_t)ZSTD_compressBound((size_t)n); }

int64_t codec_zstd_compress(const void* src, int64_t n, void* dst, int64_t cap,
                            int level) {
  size_t r = ZSTD_compress(dst, (size_t)cap, src, (size_t)n, level);
  return ZSTD_isError(r) ? -1 : (int64_t)r;
}

int64_t codec_zstd_decompress(const void* src, int64_t n, void* dst, int64_t cap) {
  size_t r = ZSTD_decompress(dst, (size_t)cap, src, (size_t)n);
  return ZSTD_isError(r) ? -1 : (int64_t)r;
}

// lz4 via dlopen (liblz4.so.1 ships without headers/link-name in this image)
typedef int (*lz4_compress_fn)(const char*, char*, int, int);
typedef int (*lz4_decompress_fn)(const char*, char*, int, int);
typedef int (*lz4_bound_fn)(int);

static std::once_flag lz4_once;
static lz4_compress_fn lz4_compress_p = nullptr;
static lz4_decompress_fn lz4_decompress_p = nullptr;
static lz4_bound_fn lz4_bound_p = nullptr;

static void lz4_init() {
  void* lib = dlopen("liblz4.so.1", RTLD_NOW | RTLD_GLOBAL);
  if (!lib) lib = dlopen("liblz4.so", RTLD_NOW | RTLD_GLOBAL);
  if (!lib) return;
  lz4_compress_p = (lz4_compress_fn)dlsym(lib, "LZ4_compress_default");
  lz4_decompress_p = (lz4_decompress_fn)dlsym(lib, "LZ4_decompress_safe");
  lz4_bound_p = (lz4_bound_fn)dlsym(lib, "LZ4_compressBound");
}

int codec_lz4_available() {
  std::call_once(lz4_once, lz4_init);
  return lz4_compress_p && lz4_decompress_p && lz4_bound_p ? 1 : 0;
}

int64_t codec_lz4_bound(int64_t n) {
  if (!codec_lz4_available()) return -1;
  return (int64_t)lz4_bound_p((int)n);
}

int64_t codec_lz4_compress(const void* src, int64_t n, void* dst, int64_t cap) {
  if (!codec_lz4_available()) return -1;
  int r = lz4_compress_p((const char*)src, (char*)dst, (int)n, (int)cap);
  return r <= 0 ? -1 : (int64_t)r;
}

int64_t codec_lz4_decompress(const void* src, int64_t n, void* dst, int64_t cap) {
  if (!codec_lz4_available()) return -1;
  int r = lz4_decompress_p((const char*)src, (char*)dst, (int)n, (int)cap);
  return r < 0 ? -1 : (int64_t)r;
}

// ---------------------------------------------------------------------------
// kvstore (ref common/kvstore/.../LevelDB.java) — log-structured file KV
// ---------------------------------------------------------------------------
// Record: [u32 klen][u32 vlen][key][value]; vlen == 0xFFFFFFFF is a tombstone.

struct KvStore {
  std::string path;
  FILE* f = nullptr;
  std::unordered_map<std::string, std::pair<int64_t, uint32_t>> index;  // key → (value offset, vlen)
  std::mutex mu;
  int64_t live_bytes = 0, total_bytes = 0;
};

static const uint32_t KV_TOMBSTONE = 0xFFFFFFFFu;

static bool kv_load_index(KvStore* s) {
  fseeko(s->f, 0, SEEK_SET);
  int64_t pos = 0;
  uint32_t hdr[2];
  std::vector<char> kbuf;
  for (;;) {
    if (fread(hdr, sizeof(uint32_t), 2, s->f) != 2) break;
    uint32_t klen = hdr[0], vlen = hdr[1];
    kbuf.resize(klen);
    if (klen && fread(kbuf.data(), 1, klen, s->f) != klen) break;
    int64_t voff = pos + 8 + klen;
    std::string key(kbuf.data(), klen);
    if (vlen == KV_TOMBSTONE) {
      auto it = s->index.find(key);
      if (it != s->index.end()) {
        s->live_bytes -= 8 + klen + it->second.second;
        s->index.erase(it);
      }
      pos = voff;
    } else {
      if (fseeko(s->f, vlen, SEEK_CUR) != 0) break;
      auto it = s->index.find(key);
      if (it != s->index.end()) s->live_bytes -= 8 + klen + it->second.second;
      s->index[key] = {voff, vlen};
      s->live_bytes += 8 + klen + vlen;
      pos = voff + vlen;
    }
  }
  s->total_bytes = pos;
  // truncate any torn tail write
  fseeko(s->f, pos, SEEK_SET);
  return true;
}

void* kv_open(const char* path) {
  auto* s = new KvStore();
  s->path = path;
  s->f = fopen(path, "a+b");
  if (!s->f) { delete s; return nullptr; }
  kv_load_index(s);
  fseeko(s->f, s->total_bytes, SEEK_SET);
  return s;
}

int kv_put(void* h, const void* k, int64_t klen, const void* v, int64_t vlen) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  fseeko(s->f, s->total_bytes, SEEK_SET);
  uint32_t hdr[2] = {(uint32_t)klen, (uint32_t)vlen};
  if (fwrite(hdr, sizeof(uint32_t), 2, s->f) != 2) return -1;
  if (klen && fwrite(k, 1, (size_t)klen, s->f) != (size_t)klen) return -1;
  if (vlen && fwrite(v, 1, (size_t)vlen, s->f) != (size_t)vlen) return -1;
  std::string key((const char*)k, (size_t)klen);
  auto it = s->index.find(key);
  if (it != s->index.end()) s->live_bytes -= 8 + klen + it->second.second;
  s->index[key] = {s->total_bytes + 8 + klen, (uint32_t)vlen};
  s->total_bytes += 8 + klen + vlen;
  s->live_bytes += 8 + klen + vlen;
  return 0;
}

int64_t kv_get(void* h, const void* k, int64_t klen, void* out, int64_t cap) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto it = s->index.find(std::string((const char*)k, (size_t)klen));
  if (it == s->index.end()) return -1;
  uint32_t vlen = it->second.second;
  if ((int64_t)vlen > cap) return (int64_t)vlen;  // caller re-calls with room
  fflush(s->f);
  fseeko(s->f, it->second.first, SEEK_SET);
  if (vlen && fread(out, 1, vlen, s->f) != vlen) return -1;
  fseeko(s->f, s->total_bytes, SEEK_SET);
  return (int64_t)vlen;
}

int kv_delete(void* h, const void* k, int64_t klen) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string key((const char*)k, (size_t)klen);
  auto it = s->index.find(key);
  if (it == s->index.end()) return -1;
  fseeko(s->f, s->total_bytes, SEEK_SET);
  uint32_t hdr[2] = {(uint32_t)klen, KV_TOMBSTONE};
  fwrite(hdr, sizeof(uint32_t), 2, s->f);
  fwrite(k, 1, (size_t)klen, s->f);
  s->live_bytes -= 8 + klen + it->second.second;
  s->index.erase(it);
  s->total_bytes += 8 + klen;
  return 0;
}

int64_t kv_count(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  return (int64_t)s->index.size();
}

int kv_flush(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  return fflush(s->f);
}

// Rewrite only live records (ref LevelDB compaction); returns 0 on success.
int kv_compact(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  std::string tmp = s->path + ".compact";
  FILE* nf = fopen(tmp.c_str(), "wb");
  if (!nf) return -1;
  fflush(s->f);
  std::unordered_map<std::string, std::pair<int64_t, uint32_t>> nindex;
  int64_t pos = 0;
  std::vector<char> vbuf;
  for (auto& kv : s->index) {
    uint32_t vlen = kv.second.second;
    vbuf.resize(vlen);
    fseeko(s->f, kv.second.first, SEEK_SET);
    if (vlen && fread(vbuf.data(), 1, vlen, s->f) != vlen) { fclose(nf); return -1; }
    uint32_t hdr[2] = {(uint32_t)kv.first.size(), vlen};
    fwrite(hdr, sizeof(uint32_t), 2, nf);
    fwrite(kv.first.data(), 1, kv.first.size(), nf);
    if (vlen) fwrite(vbuf.data(), 1, vlen, nf);
    nindex[kv.first] = {pos + 8 + (int64_t)kv.first.size(), vlen};
    pos += 8 + kv.first.size() + vlen;
  }
  fclose(nf);
  fclose(s->f);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) {
    s->f = fopen(s->path.c_str(), "a+b");
    return -1;
  }
  s->f = fopen(s->path.c_str(), "a+b");
  s->index = std::move(nindex);
  s->total_bytes = s->live_bytes = pos;
  return 0;
}

struct KvIter {
  KvStore* s;
  std::vector<std::string> keys;
  size_t pos = 0;
};

void* kv_iter(void* h) {
  auto* s = (KvStore*)h;
  std::lock_guard<std::mutex> g(s->mu);
  auto* it = new KvIter();
  it->s = s;
  it->keys.reserve(s->index.size());
  for (auto& kv : s->index) it->keys.push_back(kv.first);
  return it;
}

// Writes next key into kbuf; returns klen, or -1 at end, or required size if
// kcap too small (iterator does not advance in that case).
int64_t kv_iter_next(void* hi, void* kbuf, int64_t kcap) {
  auto* it = (KvIter*)hi;
  if (it->pos >= it->keys.size()) return -1;
  const std::string& k = it->keys[it->pos];
  if ((int64_t)k.size() > kcap) return (int64_t)k.size();
  memcpy(kbuf, k.data(), k.size());
  it->pos++;
  return (int64_t)k.size();
}

void kv_iter_free(void* hi) { delete (KvIter*)hi; }

void kv_close(void* h) {
  auto* s = (KvStore*)h;
  if (s->f) fclose(s->f);
  delete s;
}

}  // extern "C"
