"""ctypes surface over the native host library, with pure-Python fallbacks.

Three subsystems (SURVEY §2.6 native inventory):
- :func:`parse_libsvm_native` / :func:`parse_csv_native` — multithreaded C++
  parsers feeding dense arrays (the ingest path to ``InstanceDataset``).
- :class:`CompressionCodec` — zstd/lz4 block codecs (ref:
  core/.../io/CompressionCodec.scala:63-71; zlib stands in when the .so is
  unavailable).
- :class:`KVStore` — log-structured persistent KV (ref: common/kvstore
  LevelDB.java), used by the event journal / status store.
"""

from __future__ import annotations

import ctypes
import os
import struct
import zlib
from typing import Iterator, Optional, Tuple

import numpy as np

from cycloneml_tpu.native import load


def _fn(lib, name, restype, argtypes):
    f = getattr(lib, name)
    f.restype = restype
    f.argtypes = argtypes
    return f


_c_i64 = ctypes.c_int64
_c_vp = ctypes.c_void_p


class _Lib:
    """Typed function table, built once."""

    _instance = None

    def __init__(self, lib):
        self.svm_open = _fn(lib, "svm_open", _c_vp,
                            [ctypes.c_char_p, ctypes.c_int,
                             ctypes.POINTER(_c_i64), ctypes.POINTER(_c_i64)])
        self.svm_fill = _fn(lib, "svm_fill", ctypes.c_int,
                            [_c_vp, _c_vp, _c_vp, _c_i64, _c_i64])
        self.svm_free = _fn(lib, "svm_free", None, [_c_vp])
        self.svm_stream_open = _fn(lib, "svm_stream_open", _c_vp,
                                   [ctypes.c_char_p, _c_i64, ctypes.c_int])
        self.svm_stream_open_range = _fn(
            lib, "svm_stream_open_range", _c_vp,
            [ctypes.c_char_p, _c_i64, ctypes.c_int, _c_i64, _c_i64])
        self.svm_stream_next = _fn(lib, "svm_stream_next", _c_i64,
                                   [_c_vp, _c_vp, _c_vp, _c_vp, _c_vp,
                                    _c_i64, _c_i64, ctypes.POINTER(_c_i64)])
        self.svm_stream_free = _fn(lib, "svm_stream_free", None, [_c_vp])
        self.csv_open = _fn(lib, "csv_open", _c_vp,
                            [ctypes.c_char_p, ctypes.c_char, ctypes.c_int,
                             ctypes.c_int, ctypes.POINTER(_c_i64),
                             ctypes.POINTER(_c_i64)])
        self.csv_fill = _fn(lib, "csv_fill", ctypes.c_int,
                            [_c_vp, _c_vp, _c_i64, _c_i64])
        self.csv_free = _fn(lib, "csv_free", None, [_c_vp])
        self.zstd_bound = _fn(lib, "codec_zstd_bound", _c_i64, [_c_i64])
        self.zstd_compress = _fn(lib, "codec_zstd_compress", _c_i64,
                                 [_c_vp, _c_i64, _c_vp, _c_i64, ctypes.c_int])
        self.zstd_decompress = _fn(lib, "codec_zstd_decompress", _c_i64,
                                   [_c_vp, _c_i64, _c_vp, _c_i64])
        self.lz4_available = _fn(lib, "codec_lz4_available", ctypes.c_int, [])
        self.lz4_bound = _fn(lib, "codec_lz4_bound", _c_i64, [_c_i64])
        self.lz4_compress = _fn(lib, "codec_lz4_compress", _c_i64,
                                [_c_vp, _c_i64, _c_vp, _c_i64])
        self.lz4_decompress = _fn(lib, "codec_lz4_decompress", _c_i64,
                                  [_c_vp, _c_i64, _c_vp, _c_i64])
        self.kv_open = _fn(lib, "kv_open", _c_vp, [ctypes.c_char_p])
        self.kv_put = _fn(lib, "kv_put", ctypes.c_int,
                          [_c_vp, _c_vp, _c_i64, _c_vp, _c_i64])
        self.kv_get = _fn(lib, "kv_get", _c_i64,
                          [_c_vp, _c_vp, _c_i64, _c_vp, _c_i64])
        self.kv_delete = _fn(lib, "kv_delete", ctypes.c_int, [_c_vp, _c_vp, _c_i64])
        self.kv_count = _fn(lib, "kv_count", _c_i64, [_c_vp])
        self.kv_flush = _fn(lib, "kv_flush", ctypes.c_int, [_c_vp])
        self.kv_compact = _fn(lib, "kv_compact", ctypes.c_int, [_c_vp])
        self.kv_iter = _fn(lib, "kv_iter", _c_vp, [_c_vp])
        self.kv_iter_next = _fn(lib, "kv_iter_next", _c_i64, [_c_vp, _c_vp, _c_i64])
        self.kv_iter_free = _fn(lib, "kv_iter_free", None, [_c_vp])
        self.kv_close = _fn(lib, "kv_close", None, [_c_vp])


def _lib() -> Optional[_Lib]:
    if _Lib._instance is None:
        raw = load()
        if raw is None:
            return None
        _Lib._instance = _Lib(raw)
    return _Lib._instance


def native_available() -> bool:
    return _lib() is not None


# ---------------------------------------------------------------------------
# loaders
# ---------------------------------------------------------------------------

def parse_libsvm_native(path: str, n_features: Optional[int] = None,
                        n_threads: int = 0) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Dense (X float32, y float64) via the C++ parser; None → use fallback."""
    lib = _lib()
    if lib is None:
        return None
    nr, nf = _c_i64(), _c_i64()
    h = lib.svm_open(path.encode(), n_threads, ctypes.byref(nr), ctypes.byref(nf))
    if not h:
        return None
    try:
        rows = nr.value
        d = n_features if n_features is not None else nf.value
        x = np.zeros((rows, max(d, 1)), dtype=np.float32)
        y = np.zeros(rows, dtype=np.float32)
        rc = lib.svm_fill(h, x.ctypes.data_as(_c_vp), y.ctypes.data_as(_c_vp),
                          rows, x.shape[1])
        if rc != 0:
            return None
        return x[:, :d] if d else x, y.astype(np.float64)
    finally:
        lib.svm_free(h)


def stream_libsvm_chunks(path: str, chunk_rows: int = 65536,
                         cap_nnz: Optional[int] = None,
                         buf_bytes: int = 8 << 20, n_threads: int = 0,
                         byte_range: Optional[Tuple[int, int]] = None):
    """Yield ``(y, row_nnz, flat_idx, flat_val, max_feature)`` CSR chunks of a
    libsvm file with bounded memory (the Criteo-class ingest path; the
    reference's analog streams HadoopRDD partitions through
    MLUtils.loadLibSVMFile, MLUtils.scala:77 / HadoopRDD.scala:87).

    Peak memory is O(buf_bytes + chunk buffers), independent of file size.
    Uses the multithreaded C++ scanner when available, else a pure-Python
    line streamer with identical chunk semantics. ``max_feature`` is the
    running (1 + max feature index) over everything parsed SO FAR — only
    final after the last chunk.

    ``byte_range=(start, end)`` reads one HadoopRDD-style split: skip the
    partial first line when ``start > 0``, own every line starting at
    offset <= ``end``. Concatenating all splits of a partition of the file
    reproduces the single-reader row set exactly.
    """
    if cap_nnz is None:
        cap_nnz = chunk_rows * 64
    lib = _lib()
    if lib is None:
        if byte_range is not None:
            raise NotImplementedError(
                "byte_range needs the native scanner (not built here)")
        yield from _stream_libsvm_py(path, chunk_rows, cap_nnz)
        return
    if byte_range is not None:
        h = lib.svm_stream_open_range(path.encode(), buf_bytes, n_threads,
                                      byte_range[0], byte_range[1])
    else:
        h = lib.svm_stream_open(path.encode(), buf_bytes, n_threads)
    if not h:
        raise IOError(f"cannot open {path!r}")
    try:
        while True:
            y = np.empty(chunk_rows, dtype=np.float64)
            nnz = np.empty(chunk_rows, dtype=np.int32)
            fidx = np.empty(cap_nnz, dtype=np.int32)
            fval = np.empty(cap_nnz, dtype=np.float32)
            mf = _c_i64()
            n = lib.svm_stream_next(
                h, y.ctypes.data_as(_c_vp), nnz.ctypes.data_as(_c_vp),
                fidx.ctypes.data_as(_c_vp), fval.ctypes.data_as(_c_vp),
                chunk_rows, cap_nnz, ctypes.byref(mf))
            if n == -2:
                raise ValueError(
                    f"a row of {path!r} has more than cap_nnz={cap_nnz} "
                    "nonzeros; raise cap_nnz")
            if n <= 0:
                break
            used = int(nnz[:n].sum())
            yield (y[:n], nnz[:n], fidx[:used], fval[:used], int(mf.value))
    finally:
        lib.svm_stream_free(h)


def _stream_libsvm_py(path: str, chunk_rows: int, cap_nnz: int):
    """Line-streaming fallback with the same chunk contract."""
    y, nnz, fidx, fval = [], [], [], []
    used = 0
    max_feature = 0

    def flush():
        return (np.asarray(y, dtype=np.float64),
                np.asarray(nnz, dtype=np.int32),
                np.asarray(fidx, dtype=np.int32),
                np.asarray(fval, dtype=np.float32), max_feature)

    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            row_idx = [int(p.split(":")[0]) - 1 for p in parts[1:]]
            row_val = [float(p.split(":")[1]) for p in parts[1:]]
            if len(row_idx) > cap_nnz:
                raise ValueError(
                    f"a row of {path!r} has more than cap_nnz={cap_nnz} "
                    "nonzeros; raise cap_nnz")
            if len(y) >= chunk_rows or used + len(row_idx) > cap_nnz:
                yield flush()
                y, nnz, fidx, fval = [], [], [], []
                used = 0
            y.append(float(parts[0]))
            nnz.append(len(row_idx))
            fidx.extend(row_idx)
            fval.extend(row_val)
            used += len(row_idx)
            if row_idx:
                max_feature = max(max_feature, max(row_idx) + 1)
    if y:
        yield flush()


def parse_csv_native(path: str, delimiter: str = ",", skip_header: bool = False,
                     n_threads: int = 0) -> Optional[np.ndarray]:
    lib = _lib()
    if lib is None:
        return None
    nr, nc = _c_i64(), _c_i64()
    h = lib.csv_open(path.encode(), delimiter.encode()[0], int(skip_header),
                     n_threads, ctypes.byref(nr), ctypes.byref(nc))
    if not h:
        return None
    try:
        x = np.zeros((nr.value, max(nc.value, 1)), dtype=np.float64)
        if lib.csv_fill(h, x.ctypes.data_as(_c_vp), nr.value, x.shape[1]) != 0:
            return None
        return x
    finally:
        lib.csv_free(h)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

class CompressionCodec:
    """Block codec with a 9-byte header (codec id + uncompressed length) so
    streams are self-describing, matching the reference's codec-per-conf
    model (``cyclone.io.compression.codec``)."""

    ZSTD, LZ4, ZLIB = 1, 2, 3
    _names = {1: "zstd", 2: "lz4", 3: "zlib"}

    def __init__(self, codec: str = "zstd", level: int = 3):
        self.level = level
        lib = _lib()
        if codec == "zstd" and lib is not None:
            self._id = self.ZSTD
        elif codec == "lz4" and lib is not None and lib.lz4_available():
            self._id = self.LZ4
        else:
            self._id = self.ZLIB  # pure-python stand-in
        self.name = self._names[self._id]

    def compress(self, data: bytes) -> bytes:
        lib = _lib()
        hdr = struct.pack("<BQ", self._id, len(data))
        if self._id == self.ZSTD:
            cap = lib.zstd_bound(len(data))
            out = ctypes.create_string_buffer(cap)
            n = lib.zstd_compress(data, len(data), out, cap, self.level)
            if n < 0:
                raise IOError("zstd compression failed")
            return hdr + out.raw[:n]
        if self._id == self.LZ4:
            cap = lib.lz4_bound(len(data))
            out = ctypes.create_string_buffer(cap)
            n = lib.lz4_compress(data, len(data), out, cap)
            if n < 0:
                raise IOError("lz4 compression failed")
            return hdr + out.raw[:n]
        return hdr + zlib.compress(data, self.level)

    @staticmethod
    def decompress(blob: bytes) -> bytes:
        cid, n = struct.unpack("<BQ", blob[:9])
        payload = blob[9:]
        if cid == CompressionCodec.ZLIB:
            return zlib.decompress(payload)
        lib = _lib()
        if lib is None:
            raise IOError("native codec required for this stream")
        out = ctypes.create_string_buffer(max(n, 1))
        if cid == CompressionCodec.ZSTD:
            r = lib.zstd_decompress(payload, len(payload), out, max(n, 1))
        else:
            r = lib.lz4_decompress(payload, len(payload), out, max(n, 1))
        if r < 0:
            raise IOError("decompression failed")
        return out.raw[:r]


# ---------------------------------------------------------------------------
# kvstore
# ---------------------------------------------------------------------------

class KVStore:
    """Persistent KV on the native log-structured store; pure-Python engine
    with the identical on-disk format when the .so is unavailable."""

    def __init__(self, path: str):
        self.path = path
        self._lib = _lib()
        self._py: Optional[_PyKv] = None
        if self._lib is not None:
            self._h = self._lib.kv_open(path.encode())
            if not self._h:
                raise IOError(f"cannot open kvstore at {path}")
        else:
            self._py = _PyKv(path)

    def put(self, key: bytes, value: bytes) -> None:
        if self._py is not None:
            return self._py.put(key, value)
        if self._lib.kv_put(self._h, key, len(key), value, len(value)) != 0:
            raise IOError("kv put failed")

    def get(self, key: bytes) -> Optional[bytes]:
        if self._py is not None:
            return self._py.get(key)
        cap = 1 << 16
        while True:
            out = ctypes.create_string_buffer(cap)
            n = self._lib.kv_get(self._h, key, len(key), out, cap)
            if n < 0:
                return None
            if n <= cap:
                return out.raw[:n]
            cap = n

    def delete(self, key: bytes) -> bool:
        if self._py is not None:
            return self._py.delete(key)
        return self._lib.kv_delete(self._h, key, len(key)) == 0

    def __len__(self) -> int:
        if self._py is not None:
            return len(self._py.index)
        return self._lib.kv_count(self._h)

    def keys(self) -> Iterator[bytes]:
        if self._py is not None:
            yield from list(self._py.index.keys())
            return
        it = self._lib.kv_iter(self._h)
        try:
            cap = 1 << 12
            buf = ctypes.create_string_buffer(cap)
            while True:
                n = self._lib.kv_iter_next(it, buf, cap)
                if n < 0:
                    break
                if n > cap:
                    cap, buf = n, ctypes.create_string_buffer(n)
                    continue
                yield buf.raw[:n]
        finally:
            self._lib.kv_iter_free(it)

    def flush(self) -> None:
        if self._py is not None:
            return self._py.flush()
        self._lib.kv_flush(self._h)

    def compact(self) -> None:
        if self._py is not None:
            return self._py.compact()
        if self._lib.kv_compact(self._h) != 0:
            raise IOError("compaction failed")

    def close(self) -> None:
        if self._py is not None:
            return self._py.close()
        if self._h:
            self._lib.kv_close(self._h)
            self._h = None


_TOMB = 0xFFFFFFFF


class _PyKv:
    """Same record format as the C++ store: [u32 klen][u32 vlen][k][v]."""

    def __init__(self, path: str):
        self.path = path
        self.index = {}
        self.f = open(path, "a+b")
        self._load()

    def _load(self):
        self.f.seek(0)
        pos = 0
        while True:
            hdr = self.f.read(8)
            if len(hdr) < 8:
                break
            klen, vlen = struct.unpack("<II", hdr)
            key = self.f.read(klen)
            if len(key) < klen:
                break
            if vlen == _TOMB:
                self.index.pop(key, None)
                pos += 8 + klen
            else:
                val = self.f.read(vlen)
                if len(val) < vlen:
                    break
                self.index[key] = (pos + 8 + klen, vlen)
                pos += 8 + klen + vlen
        self.total = pos
        self.f.seek(pos)
        self.f.truncate(pos)

    def put(self, key: bytes, value: bytes):
        self.f.seek(self.total)
        self.f.write(struct.pack("<II", len(key), len(value)) + key + value)
        self.index[key] = (self.total + 8 + len(key), len(value))
        self.total += 8 + len(key) + len(value)

    def get(self, key: bytes) -> Optional[bytes]:
        ent = self.index.get(key)
        if ent is None:
            return None
        self.f.flush()
        self.f.seek(ent[0])
        v = self.f.read(ent[1])
        self.f.seek(self.total)
        return v

    def delete(self, key: bytes) -> bool:
        if key not in self.index:
            return False
        self.f.seek(self.total)
        self.f.write(struct.pack("<II", len(key), _TOMB) + key)
        self.total += 8 + len(key)
        del self.index[key]
        return True

    def flush(self):
        self.f.flush()

    def compact(self):
        tmp = self.path + ".compact"
        with open(tmp, "wb") as nf:
            nindex, pos = {}, 0
            for k, (off, vlen) in self.index.items():
                self.f.seek(off)
                v = self.f.read(vlen)
                nf.write(struct.pack("<II", len(k), vlen) + k + v)
                nindex[k] = (pos + 8 + len(k), vlen)
                pos += 8 + len(k) + vlen
        self.f.close()
        os.replace(tmp, self.path)
        self.f = open(self.path, "a+b")
        self.index, self.total = nindex, pos

    def close(self):
        self.f.close()
