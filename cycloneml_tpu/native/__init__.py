"""Native host runtime — lazy build & load.

Compiles ``src/cyclone_host.cpp`` into a shared library on first use (g++ is
in the image; no pip deps). Every consumer goes through :mod:`host`, which
falls back to pure-Python implementations when the toolchain is unavailable,
so the framework never hard-depends on the .so.
"""

from __future__ import annotations

import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "cyclone_host.cpp")
_LIB_DIR = os.path.join(_HERE, "_lib")
_LIB = os.path.join(_LIB_DIR, "libcyclone_host.so")

_lock = threading.Lock()
_lib_handle = None
_build_failed = False


def _needs_build() -> bool:
    return (not os.path.exists(_LIB)
            or os.path.getmtime(_LIB) < os.path.getmtime(_SRC))


def build(force: bool = False) -> Optional[str]:
    """Compile the native library; returns its path or None on failure."""
    global _build_failed
    with _lock:
        if not force and not _needs_build():
            return _LIB
        os.makedirs(_LIB_DIR, exist_ok=True)
        cmd = ["g++", "-O3", "-march=native", "-std=c++17", "-shared", "-fPIC",
               _SRC, "-o", _LIB, "-lzstd", "-lpthread", "-ldl"]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=300)
            _build_failed = False
            return _LIB
        except Exception:
            # -march=native can be unsupported in exotic sandboxes; retry plain
            try:
                cmd.remove("-march=native")
                subprocess.run(cmd, check=True, capture_output=True, timeout=300)
                _build_failed = False
                return _LIB
            except Exception:
                _build_failed = True
                return None


def load():
    """ctypes handle to the built library, or None (fallbacks engage)."""
    global _lib_handle
    if _lib_handle is not None:
        return _lib_handle
    if _build_failed:
        return None
    path = build()
    if path is None:
        return None
    import ctypes
    with _lock:
        if _lib_handle is None:
            _lib_handle = ctypes.CDLL(path)
    return _lib_handle
